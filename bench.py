"""Benchmark: TPC-H throughput on the local accelerator, vs a measured
sqlite baseline over the IDENTICAL generated data.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline metric: lineitem rows/sec through the full jit-compiled Q1
fragment (scan pages resident on device), median of BENCH_RUNS timed runs
after warmup. `detail` carries the same measurement for Q6 (fused
scan-filter global agg), Q3 (join + large-domain agg + topN) and Q18
(double join + group-by-orderkey), each with its own vs_baseline.

Baseline: the reference publishes no absolute numbers (BASELINE.md), and
no JVM exists in this environment, so the measured proxy is sqlite3
executing the same SQL over the same rows (the test suite's correctness
oracle, standing in for H2QueryRunner). It is measured once and cached in
BASELINE_MEASURED.json (keyed by scale factor) because loading SF1 into
sqlite takes minutes; delete the file to re-measure. Roofline context: Q1
touches ~7 of 16 lineitem columns ~= 0.4 GB at SF1; at v5e HBM bandwidth
(~820 GB/s) one pass is ~0.5 ms, so wall time is dominated by how few
passes the compiled fragment makes, not FLOPs.

Join-heavy queries (Q3/Q18) run LIFESPAN-BATCHED (BENCH_FRAG_QUERIES,
default "3,18"; BENCH_LIFESPAN_BATCHES, default 8): the driving scan
streams in 8 row-range lifespans through one prepared executor, which
shrinks every program's shapes 8x — the only mode whose join programs
the remote TPU compile service survives (whole-plan AND per-fragment
compiles get SIGKILLed).

Env knobs: BENCH_SF (default 1.0), BENCH_RUNS (5), BENCH_WARMUP (2),
BENCH_QUERIES (comma list, default "1,6,3,18"), BENCH_FRAG_QUERIES
(comma list run fragment-wise, default "3,18").
"""

import json
import os
import statistics
import sys
import time
from typing import Optional

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")


def _err(e) -> str:
    """Errors ride the final JSON line the driver parses — keep them
    short (a full axon compiler log once made the line unparseable)."""
    return f"{type(e).__name__}: {e}"[:200]


def measure_sqlite_baseline(conn, sf, qids):
    """Wall time per query in sqlite3 over the same generated rows."""
    import sqlite3

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from test_tpch_full import to_sqlite  # dialect bridge
    from oracle import table_df
    from tpch_queries import QUERIES

    db = sqlite3.connect(":memory:")
    tables = ["region", "nation", "supplier", "customer", "part",
              "partsupp", "orders", "lineitem"]
    for t in tables:
        df = table_df(conn, t)
        # DATE ints -> ISO strings for sqlite comparability
        for col in df.columns:
            if conn.table(t).types[col].name == "date":
                import datetime
                epoch = datetime.date(1970, 1, 1)
                df[col] = df[col].map(
                    lambda d: (epoch + datetime.timedelta(days=int(d))
                               ).isoformat())
        df.to_sql(t, db, index=False)
    out = {}
    for qid in qids:
        sql = to_sqlite(QUERIES[qid])
        t0 = time.perf_counter()
        db.execute(sql).fetchall()
        out[str(qid)] = time.perf_counter() - t0
    db.close()
    return out


def load_or_measure_baseline(conn, sf, qids):
    key = f"sf{sf:g}"
    data = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            data = json.load(f)
    missing = [q for q in qids
               if str(q) not in data.get(key, {}).get("sqlite_seconds", {})]
    if missing:
        measured = measure_sqlite_baseline(conn, sf, missing)
        entry = data.setdefault(key, {}).setdefault("sqlite_seconds", {})
        entry.update(measured)
        data[key]["note"] = (
            "sqlite3 :memory: wall seconds on identical generated data; "
            "measured on this machine, cached (delete file to re-measure)")
        try:
            with open(BASELINE_FILE, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
        except OSError:
            pass
    return data[key]["sqlite_seconds"]


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "5"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    qids = [int(q) for q in
            os.environ.get("BENCH_QUERIES", "1,6,3,18").split(",")]
    frag_qids = {int(q) for q in os.environ.get(
        "BENCH_FRAG_QUERIES", "3,18").split(",") if q}
    if os.environ.get("BENCH_CHILD") != "1":
        return _main_orchestrator(sf, qids)

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:  # functional testing off-TPU (e.g. BENCH_PLATFORM=cpu)
        import jax
        jax.config.update("jax_platforms", plat)

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine

    conn = TpchConnector(sf)
    engine = LocalEngine(conn)
    baseline = load_or_measure_baseline(conn, sf, qids)

    batched = int(os.environ.get("BENCH_LIFESPAN_BATCHES", "8"))
    detail = {}
    for qid in qids:
        try:
            if qid in frag_qids:
                _bench_one_batched(conn, qid, QUERIES[qid], baseline,
                                   runs, warmup, detail, batched)
            else:
                _bench_one(engine, qid, QUERIES[qid], baseline, runs,
                           warmup, detail)
        except Exception as e:  # noqa: BLE001 — a failed query must not
            # take down the whole benchmark report
            detail[f"q{qid:02d}"] = {"error": _err(e)}
            print(f"# q{qid:02d}: FAILED {_err(e)}", file=sys.stderr)

    head_name, head = _headline(detail)
    print(json.dumps({
        "metric": f"tpch_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }))


def _headline(detail):
    """Prefer q01; fall back to the first query that actually ran (a
    timed-out compile must not zero out the whole report)."""
    clean = {k: v for k, v in detail.items() if "error" not in v}
    for pref in ("q01", "q06"):
        if pref in clean:
            return pref, clean[pref]
    if clean:
        k = sorted(clean)[0]
        return k, clean[k]
    k = sorted(detail)[0]
    return k, {"rows_per_sec": 0.0, "vs_baseline": 0.0}


def _probe_device(timeout_s: float) -> Optional[str]:
    """Compile-and-run a trivial program on the default backend in a
    subprocess. Returns None when healthy, else a short error string.
    Guards the whole report: a wedged accelerator tunnel otherwise eats
    every per-query timeout back to back."""
    import subprocess

    plat = os.environ.get("BENCH_PLATFORM")
    pre = (f"import jax; jax.config.update('jax_platforms', {plat!r}); "
           if plat else "import jax; ")
    code = (pre + "import jax.numpy as jnp;"
            "print('PROBE', int(jax.jit(lambda a, b: a + b)"
            "(jnp.int32(2), jnp.int32(3))), jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s,
                           env=dict(os.environ, BENCH_CHILD="1"))
    except subprocess.TimeoutExpired:
        return f"device probe timed out after {timeout_s:.0f}s"
    if "PROBE 5" not in r.stdout:
        tail = (r.stderr.splitlines() or [""])[-1]
        return f"device probe failed (rc={r.returncode}) {tail}"[:200]
    return None


def _main_orchestrator(sf, qids) -> None:
    """Run each query in its own subprocess with a hard timeout: a wedged
    accelerator tunnel or a compiler crash on one query must not take
    down the whole benchmark report (the driver consumes the final JSON
    line unconditionally)."""
    import subprocess

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "600"))
    err = _probe_device(probe_timeout)
    if err is not None:
        print(f"# device probe: {err}", file=sys.stderr)
        print(json.dumps({
            "metric": f"tpch_q01_sf{sf:g}_rows_per_sec",
            "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            "detail": {"error": err},
        }))
        return

    timeout_s = float(os.environ.get("BENCH_QUERY_TIMEOUT", "2400"))
    # Lifespan-batched join queries compile ~8 smaller programs through
    # the remote service; a measured cold q3 takes ~23 min and tunnel
    # contention can stretch it — give the same budget as whole-plan
    # queries (the device probe already guards true wedges).
    join_timeout_s = float(os.environ.get("BENCH_JOIN_QUERY_TIMEOUT",
                                          "2400"))
    detail = {}
    for qid in qids:
        env = dict(os.environ, BENCH_CHILD="1", BENCH_QUERIES=str(qid))
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True,
                timeout=join_timeout_s if qid in (3, 18) else timeout_s)
            sys.stderr.write(r.stderr.splitlines()[-1] + "\n"
                             if r.stderr.splitlines() else "")
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is None:
                tail = (r.stderr.splitlines() or [""])[-1][:120]
                detail[f"q{qid:02d}"] = {
                    "error": f"no output (rc={r.returncode}) {tail}"[:200]}
            else:
                detail.update(json.loads(line).get("detail", {}))
        except subprocess.TimeoutExpired:
            used = join_timeout_s if qid in (3, 18) else timeout_s
            detail[f"q{qid:02d}"] = {
                "error": f"timeout after {used:.0f}s (join-heavy "
                         "programs OOM the remote compile service)"}
            print(f"# q{qid:02d}: TIMEOUT after {used:.0f}s",
                  file=sys.stderr)
    # whole-plan q1 can hit remote-compile stalls; retry it
    # lifespan-batched (small programs) before giving up on a number
    if 1 in qids and "error" in detail.get("q01", {}):
        print("# q01: retrying lifespan-batched", file=sys.stderr)
        env = dict(os.environ, BENCH_CHILD="1", BENCH_QUERIES="1",
                   BENCH_FRAG_QUERIES="1")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=join_timeout_s)
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is not None:
                got = json.loads(line).get("detail", {})
                if "error" not in got.get("q01", {"error": 1}):
                    detail.update(got)
        except subprocess.TimeoutExpired:
            print("# q01 batched retry: TIMEOUT", file=sys.stderr)

    head_name, head = _headline(detail)
    print(json.dumps({
        "metric": f"tpch_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }))


def _bench_one_batched(conn, qid, sql, baseline, runs, warmup, detail,
                       batches):
    """Lifespan-batched timing: the driving scan streams in `batches`
    row-range lifespans through ONE prepared executor (grouped-execution
    shape; reference Lifespan.java). Shrinking the per-program shapes by
    `batches`x is what lets join-heavy plans compile on the remote TPU
    service at all — measured cold compile ~23 min, warm run seconds."""
    import jax

    from presto_tpu.config import Session
    from presto_tpu.exec.lifespan import BatchedRunner
    from presto_tpu.sql.analyzer import Planner
    from presto_tpu.sql.parser import parse_sql

    plan = Planner(conn).plan_query(parse_sql(sql))
    runner = BatchedRunner(
        conn, plan, batches,
        session=Session({"dynamic_filtering_enabled": "false"}))
    if not runner.batchable:
        raise RuntimeError(f"q{qid}: plan shape is not lifespan-batchable")
    in_rows = conn.table(runner.driving).num_rows
    for _ in range(warmup):
        out = runner.run()
        jax.block_until_ready(out.num_rows)
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = runner.run()
        jax.block_until_ready((out.columns[0].values if out.columns
                               else out.num_rows, out.num_rows))
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"q{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "mode": f"lifespan_batched_{batches}",
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# q{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"batches={batches} sqlite={base_s:.2f}s "
          f"speedup={base_s / med if base_s else 0:.1f}x",
          file=sys.stderr)


def _bench_one(engine, qid, sql, baseline, runs, warmup, detail):
    import jax

    from presto_tpu.sql.parser import parse_sql

    plan = engine.planner.plan_query(parse_sql(sql))
    plan = engine.executor._resolve_subqueries(plan)
    # Converge capacities (overflow retries) before timing.
    caps = {}
    for _ in range(8):
        fn, scans, watch = engine.executor._lower(plan, caps)
        jitted = jax.jit(fn)
        pages = [engine.executor._fetch(s) for s in scans]
        out, needed = jitted(pages)
        import numpy as np
        needed = np.asarray(needed)
        grew = False
        for nid, need in zip(watch, needed):
            if int(need) > caps[nid]:
                from presto_tpu.data.column import bucket_capacity
                caps[nid] = bucket_capacity(int(need))
                grew = True
        if not grew:
            break
    else:
        raise RuntimeError(
            f"q{qid}: capacity retries did not converge; refusing to "
            "time a truncated fragment")
    in_rows = sum(int(p.num_rows) for p in pages)
    for _ in range(warmup):
        out, _n = jitted(pages)
        jax.block_until_ready(out.num_rows)
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out, _n = jitted(pages)
        jax.block_until_ready((out.columns[0].values if out.columns
                               else out.num_rows, out.num_rows))
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"q{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# q{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"sqlite={base_s:.2f}s speedup={base_s/med if base_s else 0:.1f}x",
          file=sys.stderr)


if __name__ == "__main__":
    main()
