"""Benchmark: TPC-H throughput on the local accelerator, vs a measured
sqlite baseline over the IDENTICAL generated data.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline metric: geomean rows/s over the full 22-query TPC-H suite
(scan pages resident on device), per-query median of BENCH_RUNS timed
runs after warmup; `detail` carries every query's median/rows-per-sec/
vs_baseline. Scan/agg shapes run as one fused program; join/window
plans run as per-operator islands (exec/executor.py) — the same paths a
worker uses.

Baseline: the reference publishes no absolute numbers (BASELINE.md), and
no JVM exists in this environment, so the measured proxy is sqlite3
executing the same SQL over the same rows (the test suite's correctness
oracle, standing in for H2QueryRunner). It is measured once and cached in
BASELINE_MEASURED.json (keyed by scale factor) because loading SF1 into
sqlite takes minutes; delete the file to re-measure. Roofline context: Q1
touches ~7 of 16 lineitem columns ~= 0.4 GB at SF1; at v5e HBM bandwidth
(~820 GB/s) one pass is ~0.5 ms, so wall time is dominated by how few
passes the compiled fragment makes, not FLOPs.

Execution routing (ISSUE 6): every query runs through a fallback
LADDER instead of a single pinned mode. Join-heavy plans try the
distributed device mesh FIRST (plan fragmented over N local devices,
ICI all_to_all hash exchanges with packed same-dtype collectives —
fragment-wise bounded programs, the production join path); scan/agg
shapes keep the fused whole-plan lane first; lifespan batching is the
last rung. Each detail entry records which `mode` executed
(fused / islands / dist_mesh_N / lifespan_batched_N); a query that
exhausts the ladder reports {"error": ..., "modes_tried": [...]}.

Adaptive-optimizer lane (ISSUE 9): every TPC-H entry carries a `hbo`
sub-dict — the query planned+executed twice against one shared
HistoryStore (run1 cold, run2 history-warm), recording per run the
HBO hit/miss counts, whether join reordering fired, and dynamic-filter
lifespans skipped, so the history-warm second run is visible in the
JSON.

Env knobs: BENCH_SF (default 1.0), BENCH_RUNS (5), BENCH_WARMUP (2),
BENCH_QUERIES (comma list or "all", the default), BENCH_FRAG_QUERIES
(comma list run lifespan-batched FIRST instead, default none),
BENCH_MESH_DEVICES (mesh width for the dist_mesh rung, default 4;
0/1 disables — on the host-CPU platform the child exports
XLA_FLAGS=--xla_force_host_platform_device_count before jax loads),
BENCH_QUERY_TIMEOUT (s, default 2400). Device-probe budget:
BENCH_PROBE_ATTEMPTS (2) x BENCH_PROBE_TIMEOUT (120 s) capped at
BENCH_PROBE_BUDGET (300 s) total — ONE wall-clock deadline shared by
every probe the run makes (initial, cpu-fallback, mid-run re-probes),
covering sleeps as well as probe subprocesses; if the accelerator
never answers, the suite falls back to JAX_PLATFORMS=cpu so the final
JSON line is always emitted (labeled cpu_fallback).

TPC-DS lane (reference:
presto-benchto-benchmarks/.../benchmarks/presto/tpcds.yaml): set
BENCH_DS_QUERIES to a comma list (or "default" for a 10-query
scan/agg/join subset) to append ds_qNN entries to detail; BENCH_DS_SF
(default 0.1) scales the DS dataset. DS entries join the suite geomean
alongside the TPC-H ones.

Serving-tier lane: BENCH_SERVE=0 disables the `detail.serve` round
(event-loop front door driven by the closed-loop harness at
BENCH_SERVE_CLIENTS scales, default 200,600,1000, each scale
submitting BENCH_SERVE_STATEMENTS statements — default = the client
count — plus an aio-vs-threaded shell A/B sized by
BENCH_SERVE_AB_CLIENTS / BENCH_SERVE_AB_REQUESTS).

Data-plane lane: BENCH_DATA_PLANE=0 disables the `detail.data_plane`
round (serde encode/decode GB/s on a lineitem-shaped page, spool +
exchange drain GB/s over a multi-frame body, and q01/q06 at
BENCH_DATA_PLANE_SF — default 10 — streamed through bounded scan runs
and checked against a direct numpy oracle);
BENCH_DATA_PLANE_TIMEOUT_S (default 1800) bounds the child.
"""

import json
import os
import statistics
import sys
import time
from typing import Optional

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")


def _err(e) -> str:
    """Errors ride the final JSON line the driver parses — keep them
    short (a full axon compiler log once made the line unparseable)."""
    return f"{type(e).__name__}: {e}"[:200]


def _mesh_want() -> int:
    """Requested mesh width for the dist_mesh bench rung (0/1 off)."""
    return int(os.environ.get("BENCH_MESH_DEVICES", "4"))


def _ensure_host_devices() -> None:
    """The dist_mesh rung needs N local devices; the host-CPU platform
    only exposes them when asked BEFORE jax initializes. Harmless on a
    real accelerator (the flag affects only the host platform)."""
    want = _mesh_want()
    if want > 1 and "jax" not in sys.modules:
        cur = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in cur:
            os.environ["XLA_FLAGS"] = (
                f"{cur} --xla_force_host_platform_device_count={want}"
            ).strip()


def _mesh_ndev() -> int:
    """Usable mesh width: the request capped by what jax actually has
    (a TPU pod slice may expose fewer chips than asked)."""
    want = _mesh_want()
    if want <= 1:
        return want
    import jax
    return min(want, len(jax.devices()))


def _sqlite_db(conn):
    """Load the generated tables into sqlite once (minutes at SF1)."""
    import sqlite3

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from oracle import table_df

    db = sqlite3.connect(":memory:")
    tables = ["region", "nation", "supplier", "customer", "part",
              "partsupp", "orders", "lineitem"]
    for t in tables:
        df = table_df(conn, t)
        # DATE ints -> ISO strings for sqlite comparability
        for col in df.columns:
            if conn.table(t).types[col].name == "date":
                import datetime
                epoch = datetime.date(1970, 1, 1)
                df[col] = df[col].map(
                    lambda d: (epoch + datetime.timedelta(days=int(d))
                               ).isoformat())
        df.to_sql(t, db, index=False)
    return db


#: cap per sqlite query: index-less nested-loop joins can run for hours;
#: an interrupted query records the cap as a FLOOR (our vs_baseline then
#: understates the speedup — the honest direction)
SQLITE_QUERY_CAP_S = float(os.environ.get("BENCH_SQLITE_CAP", "900"))


def measure_sqlite_baseline(conn, sf, qids, db=None):
    """Wall time per query in sqlite3 over the same generated rows.

    Only a genuine cap interrupt records SQLITE_QUERY_CAP_S as a floor; any
    other failure (a to_sqlite mistranslation, an immediate sqlite error)
    must NOT be cached as a 900 s baseline — that would inflate vs_baseline
    in our favor. Such queries are skipped (no baseline -> vs_baseline 0,
    the honest direction)."""
    import sqlite3
    import threading

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from test_tpch_full import to_sqlite  # dialect bridge
    from tpch_queries import QUERIES

    own = db is None
    if own:
        db = _sqlite_db(conn)
    out = {}
    for qid in qids:
        sql = to_sqlite(QUERIES[qid])
        fired = threading.Event()

        def _interrupt():
            fired.set()
            db.interrupt()

        timer = threading.Timer(SQLITE_QUERY_CAP_S, _interrupt)
        timer.start()
        t0 = time.perf_counter()
        try:
            db.execute(sql).fetchall()
            out[str(qid)] = time.perf_counter() - t0
        except sqlite3.OperationalError as e:
            if fired.is_set() and "interrupt" in str(e).lower():
                out[str(qid)] = SQLITE_QUERY_CAP_S  # cap = floor
                print(f"# sqlite q{qid}: interrupted at "
                      f"{SQLITE_QUERY_CAP_S:.0f}s (baseline is a floor)",
                      file=sys.stderr)
            else:
                print(f"# sqlite q{qid}: ERROR (no baseline recorded) "
                      f"{_err(e)}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — never cache a bogus cap
            print(f"# sqlite q{qid}: ERROR (no baseline recorded) "
                  f"{_err(e)}", file=sys.stderr)
        finally:
            timer.cancel()
    if own:
        db.close()
    return out


def load_or_measure_baseline(conn, sf, qids):
    key = f"sf{sf:g}"
    data = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            data = json.load(f)
    missing = [q for q in qids
               if str(q) not in data.get(key, {}).get("sqlite_seconds", {})]
    if missing:
        # measure AND save one query at a time (single shared db load):
        # heavy sqlite joins at SF1 take many minutes each, and a
        # timeout mid-way must not discard the queries already measured
        db = _sqlite_db(conn)
        run_measured = {}       # survives a failed/raced file write
        for qid in missing:
            run_measured.update(
                measure_sqlite_baseline(conn, sf, [qid], db=db))
            if os.path.exists(BASELINE_FILE):
                with open(BASELINE_FILE) as f:
                    data = json.load(f)
            entry = data.setdefault(key, {}).setdefault(
                "sqlite_seconds", {})
            entry.update(run_measured)
            data[key]["note"] = (
                "sqlite3 :memory: wall seconds on identical generated "
                "data; measured on this machine, cached (delete file "
                "to re-measure)")
            try:
                tmp = f"{BASELINE_FILE}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump(data, f, indent=1, sort_keys=True)
                os.replace(tmp, BASELINE_FILE)
            except OSError:
                pass
    return data[key]["sqlite_seconds"]


#: scan/agg/join-representative TPC-DS subset for the default DS lane
DS_DEFAULT = [3, 7, 19, 42, 43, 52, 55, 96, 98, 27]


def _ds_qids():
    # a small scan/agg-shaped DS lane runs by DEFAULT so every round's
    # artifact carries a TPC-DS number; "default" widens to 10 queries,
    # "none" disables
    spec = os.environ.get("BENCH_DS_QUERIES", "3,42,52")
    if not spec or spec == "none":
        return []
    if spec == "default":
        return list(DS_DEFAULT)
    if spec == "all":        # every adapted spec query, not the subset
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tests"))
        from tpcds_queries import QUERIES as DSQ
        return sorted(DSQ)
    return [int(q) for q in spec.split(",")]


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "5"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    spec = os.environ.get("BENCH_QUERIES", "all")
    qids = (list(range(1, 23)) if spec == "all"
            else [int(q) for q in spec.split(",") if q])
    frag_qids = {int(q) for q in os.environ.get(
        "BENCH_FRAG_QUERIES", "").split(",") if q}
    ds_one = os.environ.get("BENCH_DS_ONE")
    pq_one = os.environ.get("BENCH_PQ_ONE")
    if os.environ.get("BENCH_CHILD") != "1":
        return _main_orchestrator(sf, qids)
    if os.environ.get("BENCH_LOAD_ONE"):
        return _load_child()
    if os.environ.get("BENCH_CHURN_ONE"):
        return _churn_child()
    if os.environ.get("BENCH_MV_ONE"):
        return _mv_child()
    if os.environ.get("BENCH_MEMORY_ONE"):
        return _memory_child()
    if os.environ.get("BENCH_DATA_PLANE_ONE"):
        return _data_plane_child()
    if os.environ.get("BENCH_SERVE_ONE"):
        return _serve_child()
    if os.environ.get("BENCH_CLUSTER_MESH_ONE"):
        return _cluster_mesh_child()
    if ds_one:
        return _ds_child(int(ds_one), runs, warmup)
    if pq_one:
        return _pq_child(int(pq_one), sf, runs, warmup)

    _ensure_host_devices()
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:  # functional testing off-TPU (e.g. BENCH_PLATFORM=cpu)
        import jax
        jax.config.update("jax_platforms", plat)

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine

    conn = TpchConnector(sf)
    engine = LocalEngine(conn)
    baseline = load_or_measure_baseline(conn, sf, qids)

    batched = int(os.environ.get("BENCH_LIFESPAN_BATCHES", "8"))
    detail = {}
    for qid in qids:
        _bench_ladder(conn, engine, qid, QUERIES[qid], baseline, runs,
                      warmup, detail, batched,
                      frag_first=qid in frag_qids)

    head_name, head = _headline(detail)
    print(json.dumps({
        "metric": f"tpch_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }))


def _headline(detail):
    """Suite geomean over every query that ran (rows/s and
    vs_baseline); a single query's failure lowers coverage but cannot
    zero the report. Falls back to q01 when fewer than 3 queries
    succeeded (e.g. a smoke run)."""
    import math

    clean = {k: v for k, v in detail.items()
             if isinstance(v, dict) and "error" not in v
             and v.get("rows_per_sec", 0) > 0}
    if len(clean) >= 3:
        rps = [v["rows_per_sec"] for v in clean.values()]
        vsb = [v["vs_baseline"] for v in clean.values()
               if v.get("vs_baseline", 0) > 0]
        geo = math.exp(sum(math.log(x) for x in rps) / len(rps))
        geo_vs = (math.exp(sum(math.log(x) for x in vsb) / len(vsb))
                  if vsb else 0.0)
        return f"geomean{len(clean)}q", {
            "rows_per_sec": round(geo, 1),
            "vs_baseline": round(geo_vs, 3)}
    for pref in ("q01", "q06"):
        if pref in clean:
            return pref, clean[pref]
    if clean:
        k = sorted(clean)[0]
        return k, clean[k]
    qkeys = sorted(k for k, v in detail.items()
                   if isinstance(v, dict) and k.startswith(("q", "ds_",
                                                            "pq_")))
    k = qkeys[0] if qkeys else "none"
    return k, {"rows_per_sec": 0.0, "vs_baseline": 0.0}


def _child_env(**extra):
    """Env for a bench child. PRESTO_TPU_PLATFORM is stripped unless
    BENCH_PLATFORM asks for a pin — a CPU pin inherited from a test
    harness would silently bench the wrong backend."""
    env = {k: v for k, v in os.environ.items()
           if k != "PRESTO_TPU_PLATFORM"}
    plat = env.get("BENCH_PLATFORM")
    if plat:
        env["PRESTO_TPU_PLATFORM"] = plat
    env.update(BENCH_CHILD="1", **extra)
    return env


def _probe_device(timeout_s: float) -> Optional[str]:
    """Compile-and-run a trivial program on the default backend in a
    subprocess. Returns None when healthy, else a short error string.
    Guards the whole report: a wedged accelerator tunnel otherwise eats
    every per-query timeout back to back."""
    import subprocess

    plat = os.environ.get("BENCH_PLATFORM")
    pre = (f"import jax; jax.config.update('jax_platforms', {plat!r}); "
           if plat else "import jax; ")
    code = (pre + "import jax.numpy as jnp;"
            "print('PROBE', int(jax.jit(lambda a, b: a + b)"
            "(jnp.int32(2), jnp.int32(3))), jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=_child_env())
    except subprocess.TimeoutExpired:
        return f"device probe timed out after {timeout_s:.0f}s"
    if "PROBE 5" not in r.stdout:
        tail = (r.stderr.splitlines() or [""])[-1]
        return f"device probe failed (rc={r.returncode}) {tail}"[:200]
    return None


#: ONE wall-clock deadline for every probe the whole run makes —
#: initial, cpu-fallback, and mid-run re-probes all draw down the same
#: BENCH_PROBE_BUDGET. Per-call deadlines let a run with a wedged
#: tunnel stack several full budgets plus backoff sleeps (BENCH_r05:
#: 4 x 300 s probes + 60/120/240/480 s sleeps) past the harness
#: timeout, so the labeled-infra-error JSON never landed (rc=124,
#: parsed: null). Lazily armed at the first probe so import costs
#: nothing against the budget.
_PROBE_DEADLINE: Optional[float] = None


def _probe_deadline() -> float:
    global _PROBE_DEADLINE
    if _PROBE_DEADLINE is None:
        budget_s = float(os.environ.get("BENCH_PROBE_BUDGET", "300"))
        _PROBE_DEADLINE = time.perf_counter() + budget_s
    return _PROBE_DEADLINE


def _probe_remaining() -> float:
    return _probe_deadline() - time.perf_counter()


def _probe_grant_grace(seconds: float) -> None:
    """Extend the global probe deadline by a BOUNDED one-off slice (the
    cpu-fallback probe after the accelerator burned the whole budget) —
    total probe wall time stays <= budget + grace, never another full
    budget per call site."""
    global _PROBE_DEADLINE
    _PROBE_DEADLINE = max(_probe_deadline(),
                          time.perf_counter() + seconds)


def _probe_with_retry(attempts, timeout_s, log) -> Optional[str]:
    """Probe up to `attempts` times with growing sleeps between failures
    (the tunnel wedges transiently: round-4's single 600 s probe turned
    an infra blip into a 0.0 artifact). The WHOLE retry loop — probes
    plus sleeps, ACROSS every call this process makes — is bounded by
    the global BENCH_PROBE_BUDGET deadline (default 300 s): a wedged
    tunnel gets a fair retry window but can never hold the report
    hostage for tens of minutes. Returns None when healthy, else the
    last error; every attempt is recorded in `log`."""
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "60"))
    budget_s = float(os.environ.get("BENCH_PROBE_BUDGET", "300"))
    deadline = _probe_deadline()
    err = None
    for i in range(max(1, attempts)):
        remaining = deadline - time.perf_counter()
        if remaining <= 1.0:
            log.append(f"attempt {i + 1}: skipped (probe budget "
                       f"{budget_s:.0f}s exhausted)")
            print(f"# device probe {log[-1]}", file=sys.stderr)
            # a skipped probe is NOT a healthy probe: without a real
            # answer inside the budget the device must count as down
            err = err or (f"device probe budget {budget_s:.0f}s "
                          "exhausted before a probe could run")
            break
        t0 = time.perf_counter()
        err = _probe_device(min(timeout_s, max(remaining, 1.0)))
        dt = time.perf_counter() - t0
        log.append(f"attempt {i + 1}: "
                   + ("ok" if err is None else err) + f" ({dt:.0f}s)")
        print(f"# device probe {log[-1]}", file=sys.stderr)
        if err is None:
            return None
        if i + 1 < attempts:
            sleep_s = min(backoff * (2 ** i), 480.0,
                          max(deadline - time.perf_counter(), 0.0))
            if sleep_s > 0:
                print(f"# device probe: sleeping {sleep_s:.0f}s "
                      "before retry", file=sys.stderr)
                time.sleep(sleep_s)
    return err


def _run_query_child(qid, timeout_s, batched: bool, ds: bool = False):
    """One query in one subprocess; returns (detail_entry, stderr_tail)."""
    import subprocess

    if ds == "pq":
        extra = {"BENCH_PQ_ONE": str(qid), "BENCH_QUERIES": ""}
        key = f"pq_q{qid:02d}"
    elif ds:
        extra = {"BENCH_DS_ONE": str(qid), "BENCH_QUERIES": ""}
        key = f"ds_q{qid:02d}"
    else:
        extra = {"BENCH_QUERIES": str(qid)}
        key = f"q{qid:02d}"
        if batched:
            extra["BENCH_FRAG_QUERIES"] = str(qid)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(**extra),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}, ""
    tail = (r.stderr.splitlines() or [""])[-1]
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        return ({"error": f"no output (rc={r.returncode}) "
                          f"{tail[:120]}"[:200]}, tail)
    got = json.loads(line).get("detail", {})
    return got.get(key, {"error": "child produced no entry"}), tail


def _main_orchestrator(sf, qids) -> None:
    """Run each query in its own subprocess with a hard timeout: a wedged
    accelerator tunnel or a compiler crash on one query must not take
    down the whole benchmark report (the driver consumes the final JSON
    line unconditionally). Resilience discipline (reference:
    presto-benchto-benchmarks/.../benchmarks/presto/tpch.yaml runs each
    query 6x with prewarm and records every one):

    - the device probe retries with backoff across a real window;
    - a query that fails whole-plan is retried lifespan-batched (small
      programs compile where whole-plan ones are rejected);
    - a per-query TIMEOUT triggers a quick re-probe: if the tunnel
      wedged mid-run the remaining queries are labeled infra errors
      instead of burning N x BENCH_QUERY_TIMEOUT;
    - infra failure is always labeled (`infra_error`), never an
      unlabeled 0.0;
    - if the accelerator never comes up within the probe budget, the
      suite FALLS BACK to JAX_PLATFORMS=cpu (labeled `cpu_fallback`) so
      the run still produces a functional-correctness artifact instead
      of an empty infra_error line."""
    # a HEALTHY tunnel compiles the trivial probe in seconds; 2 attempts
    # x 120 s inside a 300 s total budget (BENCH_PROBE_BUDGET) rides out
    # a transient blip without wedging the driver for ~40 minutes the
    # way the old 5 x 300 s schedule did
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    probe_attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "2"))
    probe_log = []
    fallback_reason = None
    err = _probe_with_retry(probe_attempts, probe_timeout, probe_log)
    if err is not None and os.environ.get("BENCH_PLATFORM") != "cpu":
        # accelerator wedged: rerun the suite on the host CPU so the
        # final JSON line always lands (perf numbers are then labeled,
        # not comparable to accelerator runs)
        fallback_reason = err
        print("# device probe failed; falling back to "
              "BENCH_PLATFORM=cpu", file=sys.stderr)
        os.environ["BENCH_PLATFORM"] = "cpu"
        os.environ["JAX_PLATFORMS"] = "cpu"
        # the accelerator probes may have spent the whole global
        # budget; the host-cpu probe gets one bounded grace slice so
        # the functional-correctness artifact still has a chance
        _probe_grant_grace(min(probe_timeout, 120.0))
        err = _probe_with_retry(1, min(probe_timeout, 120.0), probe_log)
    if err is not None:
        print(json.dumps({
            "metric": f"tpch_infra_error_sf{sf:g}_rows_per_sec",
            "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            "detail": {"infra_error": err, "probe_log": probe_log,
                       "note": "accelerator tunnel unhealthy and cpu "
                               "fallback probe failed; no engine perf "
                               "claim can be made this run"},
        }))
        return

    # Per-query budget: warm (cached) queries run in seconds; a cold
    # island-program compile through the remote service takes minutes.
    timeout_s = float(os.environ.get("BENCH_QUERY_TIMEOUT", "2400"))
    frag_qids = {int(q) for q in os.environ.get(
        "BENCH_FRAG_QUERIES", "").split(",") if q}
    detail = {}
    wedged = None
    for qid in qids:
        if wedged is not None:
            detail[f"q{qid:02d}"] = {"error": f"infra: {wedged}"}
            continue
        entry, tail = _run_query_child(qid, timeout_s, qid in frag_qids)
        if "error" in entry and qid not in frag_qids:
            print(f"# q{qid:02d}: whole-plan failed ({entry['error']}); "
                  "retrying lifespan-batched", file=sys.stderr)
            retry, _ = _run_query_child(qid, timeout_s, batched=True)
            if "error" not in retry:
                entry = retry
        if "error" in entry and entry["error"].startswith("timeout"):
            # distinguish "this query is slow/broken" from "tunnel
            # died"; the quick probe draws on the same global budget —
            # with it exhausted, a short 5 s sanity probe still runs so
            # a wedged tunnel is labeled rather than silently retried
            quick = _probe_device(min(300.0, probe_timeout,
                                      max(_probe_remaining(), 5.0)))
            if quick is not None:
                requick = _probe_with_retry(2, probe_timeout, probe_log)
                if requick is not None:
                    wedged = f"tunnel wedged mid-run at q{qid:02d}"
                    print(f"# {wedged}; labeling remaining queries",
                          file=sys.stderr)
        detail[f"q{qid:02d}"] = entry
        if tail:
            sys.stderr.write(tail + "\n")
    # TPC-DS lane (VERDICT r4 #10): ds_qNN entries join the geomean
    for qid in _ds_qids():
        if wedged is not None:
            detail[f"ds_q{qid:02d}"] = {"error": f"infra: {wedged}"}
            continue
        entry, tail = _run_query_child(qid, timeout_s, batched=False,
                                       ds=True)
        detail[f"ds_q{qid:02d}"] = entry
        if tail:
            sys.stderr.write(tail + "\n")

    # parquet scan lane (VERDICT r4 #5): same TPC-H queries, data read
    # from parquet files instead of the generator (q6 by default so the
    # lakehouse scan path gets a number; "none" disables)
    pq_spec = os.environ.get("BENCH_PARQUET_QUERIES", "6")
    for qid in ([int(q) for q in pq_spec.split(",")
                 if q and q != "none"]
                if pq_spec and pq_spec != "none" else []):
        if wedged is not None:
            detail[f"pq_q{qid:02d}"] = {"error": f"infra: {wedged}"}
            continue
        entry, tail = _run_query_child(qid, timeout_s, batched=False,
                                       ds="pq")
        detail[f"pq_q{qid:02d}"] = entry
        if tail:
            sys.stderr.write(tail + "\n")

    # admission front-door round (one JSON `admission` entry: ledger,
    # queue-wait percentiles, shed counters); BENCH_LOAD=0 disables
    if os.environ.get("BENCH_LOAD", "1") != "0":
        if wedged is not None:
            detail["admission"] = {"error": f"infra: {wedged}"}
        else:
            detail["admission"] = _run_load_child(
                float(os.environ.get("BENCH_LOAD_TIMEOUT_S", "240"))
                + 120.0)

    # elastic-membership churn round (one JSON `churn` entry: query
    # correctness under seeded join/drain/kill, membership counters);
    # BENCH_CHURN=0 disables
    if os.environ.get("BENCH_CHURN", "1") != "0":
        if wedged is not None:
            detail["churn"] = {"error": f"infra: {wedged}"}
        else:
            detail["churn"] = _run_churn_child(
                float(os.environ.get("BENCH_CHURN_TIMEOUT_S", "240"))
                + 120.0)

    # streaming-ingest + materialized-view round (one JSON `mv` entry:
    # incremental refresh cost vs full recompute over a continuously-
    # appending lineitem, plus staleness); BENCH_MV=0 disables
    if os.environ.get("BENCH_MV", "1") != "0":
        if wedged is not None:
            detail["mv"] = {"error": f"infra: {wedged}"}
        else:
            detail["mv"] = _run_mv_child(
                float(os.environ.get("BENCH_MV_TIMEOUT_S", "240"))
                + 120.0)

    # memory-arbitration round (one JSON `memory` entry: constrained-
    # budget wall vs unconstrained for the lifespan-fallback and
    # build-side-spill-join shapes, spill/revocation counters, killer
    # demo, exactness bit); BENCH_MEMORY=0 disables
    if os.environ.get("BENCH_MEMORY", "1") != "0":
        if wedged is not None:
            detail["memory"] = {"error": f"infra: {wedged}"}
        else:
            detail["memory"] = _run_memory_child(
                float(os.environ.get("BENCH_MEMORY_TIMEOUT_S", "240"))
                + 120.0)

    # serving-tier round (one JSON `serve` entry: event-loop front
    # door at 200 -> 1000 concurrent long-polling clients — p99,
    # server-side threads, keep-alive reuse — plus a shell A/B of the
    # aio loop vs the retired thread-per-connection shell). The engine
    # is a constant-time stub, so this lane runs even when the device
    # probe is wedged; BENCH_SERVE=0 disables
    if os.environ.get("BENCH_SERVE", "1") != "0":
        detail["serve"] = _run_serve_child(
            float(os.environ.get("BENCH_SERVE_TIMEOUT_S", "300"))
            + 120.0)

    # data-plane round (one JSON `data_plane` entry: serde GB/s,
    # exchange-drain GB/s, q01/q06 at SF10 through streaming scan
    # runs, oracle-exactness bit); BENCH_DATA_PLANE=0 disables
    if os.environ.get("BENCH_DATA_PLANE", "1") != "0":
        if wedged is not None:
            detail["data_plane"] = {"error": f"infra: {wedged}"}
        else:
            detail["data_plane"] = _run_data_plane_child(
                float(os.environ.get("BENCH_DATA_PLANE_TIMEOUT_S",
                                     "1800")) + 120.0)

    # cluster-mesh tier round (one JSON `cluster_mesh` entry: q03/q18
    # through the HTTP cluster with mesh-lowered fused execution —
    # walls plus the ICI-vs-HTTP exchange byte split);
    # BENCH_CLUSTER_MESH=0 disables
    if os.environ.get("BENCH_CLUSTER_MESH", "1") != "0":
        if wedged is not None:
            detail["cluster_mesh"] = {"error": f"infra: {wedged}"}
        else:
            detail["cluster_mesh"] = _run_cluster_mesh_child(
                float(os.environ.get("BENCH_CLUSTER_MESH_TIMEOUT_S",
                                     "300")) + 120.0)

    if wedged is not None:
        detail["infra_error"] = wedged
        detail["probe_log"] = probe_log
    if fallback_reason is not None:
        detail["platform"] = "cpu_fallback"
        detail["fallback_reason"] = fallback_reason
        detail["probe_log"] = probe_log

    head_name, head = _headline(detail)
    lane = "tpch_cpu_fallback" if fallback_reason is not None else "tpch"
    summary = {
        "metric": f"{lane}_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }
    # Regression gate: compare this run against the newest landed
    # BENCH round and self-report the verdict (advisory here; the
    # `python -m presto_tpu.obs.bench_check` CLI is the hard gate).
    try:
        from presto_tpu.obs.bench_check import compare_rounds, \
            find_rounds
        rounds = find_rounds(os.path.dirname(os.path.abspath(__file__)))
        if rounds:
            with open(rounds[-1], "r", encoding="utf-8") as f:
                landed = json.load(f)
            summary["detail"]["bench_check"] = compare_rounds(
                landed, {"parsed": summary})
    except Exception as e:  # noqa: BLE001 — the gate must never
        summary["detail"]["bench_check"] = {"error": str(e)[:200]}
    print(json.dumps(summary))


def _ds_sqlite_baseline(conn, sf, qid) -> float:
    """Measured-and-cached sqlite seconds for one TPC-DS query (same
    discipline as the TPC-H lane; key ds_sf{sf})."""
    import sqlite3
    import threading

    key = f"ds_sf{sf:g}"
    data = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            data = json.load(f)
    cached = data.get(key, {}).get("sqlite_seconds", {}).get(str(qid))
    if cached is not None:
        return cached

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from test_tpcds import _TABLES, Q22_SQLITE, Q27_SQLITE, \
        SQLITE_OVERRIDES
    from test_tpch_full import _iso, to_sqlite
    from tpcds_queries import QUERIES as DSQ
    from oracle import table_df

    db = sqlite3.connect(":memory:")
    for t in _TABLES:
        df = table_df(conn, t)
        for col, typ in conn.schema(t):
            if typ.name == "date":
                df[col] = df[col].map(_iso)
        db.execute(f"create table {t} ({', '.join(df.columns)})")
        db.executemany(
            f"insert into {t} values "
            f"({', '.join('?' * len(df.columns))})",
            df.itertuples(index=False, name=None))
    db.commit()
    sql = to_sqlite({22: Q22_SQLITE, 27: Q27_SQLITE,
                     **SQLITE_OVERRIDES}.get(qid) or DSQ[qid])
    fired = threading.Event()

    def _interrupt():
        fired.set()
        db.interrupt()

    timer = threading.Timer(SQLITE_QUERY_CAP_S, _interrupt)
    timer.start()
    t0 = time.perf_counter()
    try:
        db.execute(sql).fetchall()
        took = time.perf_counter() - t0
    except sqlite3.OperationalError as e:
        if fired.is_set() and "interrupt" in str(e).lower():
            took = SQLITE_QUERY_CAP_S
        else:
            return 0.0
    except Exception:   # noqa: BLE001 — never cache a bogus cap
        return 0.0
    finally:
        timer.cancel()
        db.close()
    try:
        if os.path.exists(BASELINE_FILE):
            with open(BASELINE_FILE) as f:
                data = json.load(f)
        data.setdefault(key, {}).setdefault(
            "sqlite_seconds", {})[str(qid)] = took
        tmp = f"{BASELINE_FILE}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, BASELINE_FILE)
    except OSError:
        pass
    return took


def _ds_child(qid: int, runs: int, warmup: int) -> None:
    """One TPC-DS query timed on the production executor path."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpcds_queries import QUERIES as DSQ

    from presto_tpu.connectors import TpcdsConnector
    from presto_tpu.exec import LocalEngine

    ds_sf = float(os.environ.get("BENCH_DS_SF", "0.1"))
    conn = TpcdsConnector(ds_sf)
    engine = LocalEngine(conn)
    base_s = _ds_sqlite_baseline(conn, ds_sf, qid)
    detail = {}
    _bench_one(engine, qid, DSQ[qid], {str(qid): base_s}, runs,
               warmup, detail, prefix="ds_q")
    print(json.dumps({"metric": f"tpcds_q{qid}", "value": 0,
                      "unit": "rows/s", "vs_baseline": 0,
                      "detail": detail}))


def _pq_child(qid: int, sf: float, runs: int, warmup: int) -> None:
    """One TPC-H query timed on the PARQUET scan path (VERDICT r4 #5:
    a lakehouse-file scan bench entry, not the in-memory generator).
    The dataset materializes once into a cached parquet directory."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.connectors.parquet import (
        ParquetConnector, materialize_connector,
    )
    from presto_tpu.exec import LocalEngine

    pq_dir = os.environ.get(
        "BENCH_PARQUET_DIR", f"/tmp/presto_tpu_parquet_sf{sf:g}")
    gen = TpchConnector(sf)
    materialize_connector(
        gen, pq_dir,
        ["region", "nation", "supplier", "customer", "part",
         "partsupp", "orders", "lineitem"])
    conn = ParquetConnector(pq_dir)
    engine = LocalEngine(conn)
    baseline = load_or_measure_baseline(gen, sf, [qid])
    detail = {}
    _bench_one(engine, qid, QUERIES[qid], baseline, runs, warmup,
               detail, prefix="pq_q")
    print(json.dumps({"metric": f"tpch_parquet_q{qid}", "value": 0,
                      "unit": "rows/s", "vs_baseline": 0,
                      "detail": detail}))


def _load_child() -> None:
    """Admission front-door round: stand up a real statement server
    over a small TPC-H cluster, drive it with the closed-loop load
    harness (3 tenants at weights 2:1:1, zipfian mix), and emit the
    accepted/rejected/shed/dropped ledger plus queue-wait percentiles
    and the dispatcher's counter snapshot as one JSON line."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    from presto_tpu.admission import (ResourceGroup,
                                      ResourceGroupManager, Selector)
    from presto_tpu.config import AdmissionConfig
    from presto_tpu.connectors import TpchConnector
    from presto_tpu.server.cluster import TpuCluster
    from presto_tpu.server.statement import StatementServer
    from presto_tpu.testing.load import LoadHarness

    statements = int(os.environ.get("BENCH_LOAD_STATEMENTS", "120"))
    clients = int(os.environ.get("BENCH_LOAD_CLIENTS", "24"))
    tenants = {"alpha": 2, "beta": 1, "gamma": 1}
    leaves = [ResourceGroup(n, hard_concurrency=4,
                            max_queued=max(statements, 64),
                            scheduling_weight=w)
              for n, w in tenants.items()]
    root = ResourceGroup("front", hard_concurrency=4, max_queued=0,
                         children=leaves)
    mgr = ResourceGroupManager(
        [root],
        [Selector(n, user_regex=n) for n in tenants]
        + [Selector("alpha")])
    cluster = TpuCluster(TpchConnector(0.01), n_workers=2,
                         resource_groups=mgr)
    srv = StatementServer(
        cluster, admission=AdmissionConfig(max_dispatch_threads=4))
    srv.start()
    try:
        harness = LoadHarness(
            srv.base, tenants, clients=clients, statements=statements,
            sql="select count(*) from nation", seed=11,
            timeout_s=float(os.environ.get("BENCH_LOAD_TIMEOUT_S",
                                           "240")))
        t0 = time.perf_counter()
        report = harness.run(dispatcher=srv.dispatcher, groups=mgr)
        wall = time.perf_counter() - t0
        out = report.to_dict()
        out["wall_s"] = round(wall, 3)
        out["statements_per_sec"] = (round(report.completed / wall, 1)
                                     if wall > 0 else 0.0)
        out["front_door"] = srv.dispatcher.snapshot()
    finally:
        srv.stop()
        cluster.stop()
    print(json.dumps({"metric": "admission_load_round", "value":
                      out["statements_per_sec"], "unit": "stmt/s",
                      "detail": {"admission": out}}))


def _serve_child() -> None:
    """Serving-tier round. Two parts:

    1. The real event-loop front door (StatementServer on
       AioHttpServer) under the closed-loop harness at increasing
       client counts (BENCH_SERVE_CLIENTS, default 200,600,1000) — a
       constant-time stub engine isolates the HTTP path: loop
       dispatch, keep-alive pooling, long-poll parks. Reports p99,
       server-side peak threads, and pooled-transport reuse per scale.
    2. A shell A/B: the same trivial App served by the aio loop and
       by the retired thread-per-connection shell, same client count —
       the thread-population contrast is the tentpole number.
    """
    import threading as _threading

    from presto_tpu.admission import (ResourceGroup,
                                      ResourceGroupManager, Selector)
    from presto_tpu.config import AdmissionConfig
    from presto_tpu.net import M_KEEPALIVE_REUSE
    from presto_tpu.server.statement import StatementServer
    from presto_tpu.testing.load import LoadHarness, percentile

    scales = [int(c) for c in os.environ.get(
        "BENCH_SERVE_CLIENTS", "200,600,1000").split(",") if c]
    stmts_env = os.environ.get("BENCH_SERVE_STATEMENTS", "")
    tenants = {"alpha": 2, "beta": 1, "gamma": 1}

    class _StubEngine:
        def execute_sql(self, sql):
            time.sleep(0.005)
            return [(1,)]

        def plan_sql(self, sql):
            raise ValueError("stub has no planner")

    rows = []
    for clients in scales:
        statements = int(stmts_env) if stmts_env else clients
        leaves = [ResourceGroup(n, hard_concurrency=32,
                                max_queued=statements + 100,
                                scheduling_weight=w)
                  for n, w in tenants.items()]
        root = ResourceGroup("front", hard_concurrency=32,
                             max_queued=0, children=leaves)
        mgr = ResourceGroupManager(
            [root],
            [Selector(n, user_regex=n) for n in tenants]
            + [Selector("alpha")])
        srv = StatementServer(
            _StubEngine(), resource_groups=mgr,
            admission=AdmissionConfig(max_dispatch_threads=8))
        srv.start()
        try:
            reuse0 = M_KEEPALIVE_REUSE.value(role="client-pool")
            t0 = time.perf_counter()
            report = LoadHarness(
                srv.base, tenants, clients=clients,
                statements=statements, seed=17,
                timeout_s=float(os.environ.get(
                    "BENCH_SERVE_TIMEOUT_S", "300"))).run()
            wall = time.perf_counter() - t0
            net = srv.httpd.stats()
            rows.append({
                "clients": clients, "statements": statements,
                "completed": report.completed,
                "dropped": report.dropped,
                "wall_s": round(wall, 3),
                "statements_per_sec":
                    round(report.completed / wall, 1) if wall else 0.0,
                "e2e_p50_s": round(report.latency()["e2e_p50_s"], 4),
                "e2e_p99_s": round(report.latency()["e2e_p99_s"], 4),
                "peak_server_threads": report.peak_server_threads,
                "keepalive_reuse":
                    int(M_KEEPALIVE_REUSE.value(role="client-pool")
                        - reuse0),
                "net": net,
            })
        finally:
            srv.stop()

    # ---- shell A/B: aio loop vs thread-per-connection ----------------
    from presto_tpu.net.aio_server import AioHttpServer, json_response
    from presto_tpu.net.threaded import ThreadedAppServer

    class _PingApp:
        def handle(self, req):
            return json_response(200, {"ok": True})

    ab_clients = int(os.environ.get("BENCH_SERVE_AB_CLIENTS", "200"))
    ab_requests = int(os.environ.get("BENCH_SERVE_AB_REQUESTS", "10"))

    def _shell_round(shell) -> dict:
        import socket as _socket
        lat, errs = [], [0]
        peak = [_threading.active_count()]
        stop = _threading.Event()

        def _sample():
            while not stop.is_set():
                peak[0] = max(peak[0], _threading.active_count())
                stop.wait(0.02)

        def _client():
            try:
                s = _socket.create_connection(
                    ("127.0.0.1", shell.port), timeout=30)
                s.settimeout(30)
                msg = b"GET /ping HTTP/1.1\r\nHost: b\r\n\r\n"
                for _ in range(ab_requests):
                    t0 = time.perf_counter()
                    s.sendall(msg)
                    buf = b""
                    while b"}" not in buf:
                        chunk = s.recv(4096)
                        if not chunk:
                            raise ConnectionError("torn")
                        buf += chunk
                    lat.append(time.perf_counter() - t0)
                s.close()
            except Exception:   # noqa: BLE001 — counted, not raised
                errs[0] += 1

        sampler = _threading.Thread(target=_sample, daemon=True)
        sampler.start()
        threads = [_threading.Thread(target=_client, daemon=True)
                   for _ in range(ab_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        wall = time.perf_counter() - t0
        stop.set()
        sampler.join(timeout=1)
        return {"impl": shell.stats()["impl"],
                "clients": ab_clients,
                "requests": ab_clients * ab_requests,
                "errors": errs[0],
                "wall_s": round(wall, 3),
                "rps": round(len(lat) / wall, 1) if wall else 0.0,
                "p50_ms": round(percentile(lat, 0.50) * 1e3, 2),
                "p99_ms": round(percentile(lat, 0.99) * 1e3, 2),
                "peak_threads": peak[0]}

    ab = {}
    for name, cls in (("aio", AioHttpServer),
                      ("threaded", ThreadedAppServer)):
        shell = cls(_PingApp(), "127.0.0.1", 0, role="bench").start()
        try:
            ab[name] = _shell_round(shell)
        finally:
            shell.shutdown()
            shell.server_close()

    out = {"scales": rows, "shell_ab": ab}
    headline = rows[-1]["statements_per_sec"] if rows else 0.0
    print(json.dumps({"metric": "serve_longpoll_round",
                      "value": headline, "unit": "stmt/s",
                      "detail": {"serve": out}}))


def _run_serve_child(timeout_s: float):
    """Run the serving-tier round in a subprocess; returns the `serve`
    detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_SERVE_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "serve", {"error": "child produced no serve entry"})


def _run_load_child(timeout_s: float):
    """Run the admission load round in a subprocess; returns the
    `admission` detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_LOAD_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "admission", {"error": "child produced no admission entry"})


def _churn_child() -> None:
    """Elastic-membership churn round: a small TPC-H cluster with a
    discovery service and `retry_policy=TASK` runs the chaos query set
    repeatedly while a seeded ChurnDriver joins, drains, and kills
    dynamic workers in the background. Emits the correctness ledger
    (rounds, failures, row mismatches vs the quiet baseline run), the
    churn schedule counters, and the coordinator's membership stats as
    one JSON line.

    BENCH_CHURN_COORD=1 raises the stakes to full control-plane chaos:
    a two-coordinator fleet over the same cluster shares one query
    journal, every query routes through the DBAPI client's rendezvous/
    failover path against the fleet, and the ChurnDriver's schedule
    gains seeded coordinator kills (coord_kill) alongside the worker
    verbs — measuring end-to-end HA, not just worker elasticity."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.protocol.transport import TransportConfig
    from presto_tpu.server.cluster import TpuCluster
    from presto_tpu.server.discovery import DiscoveryService
    from presto_tpu.testing.churn import ChurnDriver

    seed = int(os.environ.get("BENCH_CHURN_SEED", "0"))
    rounds = int(os.environ.get("BENCH_CHURN_ROUNDS", "6"))
    queries = (
        "select count(*) from lineitem",
        "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
        "from lineitem group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus",
        "select r_name, count(*) from nation, region "
        "where n_regionkey = r_regionkey group by r_name "
        "order by r_name",
    )
    coord_ha = os.environ.get("BENCH_CHURN_COORD", "0") != "0"
    chaos_tr = TransportConfig(
        retry_base_backoff_s=0.01, retry_max_backoff_s=0.2,
        retry_budget_s=5.0, breaker_failure_threshold=3,
        breaker_cooldown_s=0.3)
    disc = DiscoveryService("127.0.0.1", expiry_s=2.0).start()
    cluster = TpuCluster(
        TpchConnector(0.01), n_workers=2, discovery=disc,
        session_properties={"retry_policy": "TASK",
                            "query_max_execution_time": "120"},
        transport_config=chaos_tr)

    fleet = None
    journal_dir = None
    if coord_ha:
        import tempfile

        import presto_tpu.client as pclient
        from presto_tpu.protocol import transport as _tr
        from presto_tpu.testing.fleet import CoordinatorFleet

        # the DBAPI rides the process-global transport client; give it
        # the same chaos-tuned breaker as the cluster so a revived
        # coordinator is reachable again on the churn timescale
        _tr._DEFAULT_CLIENT = _tr.HttpClient(chaos_tr)
        journal_dir = tempfile.TemporaryDirectory()
        fleet = CoordinatorFleet(
            cluster, n=2,
            journal_path=os.path.join(journal_dir.name,
                                      "journal.jsonl")).start()
        conn = pclient.connect(fleet.bases, timeout_s=120)

        def _run(sql):
            # zero-dropped contract: clean shed / unreachable-window /
            # queue-full errors are retryable; bounded patience
            cur = conn.cursor()
            attempts = 0
            while True:
                attempts += 1
                try:
                    cur.execute(sql)
                    return [list(r) for r in cur.fetchall()]
                except (pclient.OverloadedError,
                        pclient.OperationalError):
                    if attempts >= 20:
                        raise
                    time.sleep(0.1)
                except pclient.DatabaseError as e:
                    if "QUEUE" not in str(e) or attempts >= 20:
                        raise
                    time.sleep(0.1)
    else:
        def _run(sql):
            return cluster.execute_sql(sql)

    driver = ChurnDriver(cluster, seed=seed, max_dynamic=2,
                         drain_timeout_s=30.0, coordinators=fleet)
    out = {"seed": seed, "rounds": rounds, "queries": len(queries),
           "coordinator_ha": coord_ha,
           "executed": 0, "failures": 0, "mismatches": 0}
    wall = 0.0
    intro = {}
    try:
        from presto_tpu.obs.profiler import PROFILER
        from presto_tpu.obs.wide_events import LEDGER
        LEDGER.clear()
        # quiet baseline on the static fleet = the row oracle (same
        # client path as the churn rounds so row representation
        # matches exactly)
        want = {sql: sorted(_run(sql)) for sql in queries}
        driver.start(interval_s=0.4)
        t0 = time.perf_counter()
        for _ in range(rounds):
            for sql in queries:
                try:
                    got = sorted(_run(sql))
                except Exception:
                    out["failures"] += 1
                    continue
                out["executed"] += 1
                if got != want[sql]:
                    out["mismatches"] += 1
        wall = time.perf_counter() - t0
        # wide-event ledger: exactly ONE event per cluster query
        # (baseline + churn round), summarized per query BEFORE the
        # introspection probes below append their own events
        evs = LEDGER.snapshot()
        out["wide_events"] = {
            "count": len(evs),
            "expected": (out["executed"] + out["failures"]
                         + len(queries)),
            "per_query": [
                {"query_id": e["query_id"], "state": e["state"],
                 "wall_s": e["wall_s"],
                 "result_rows": e["result_rows"],
                 "membership_epoch": e["membership"]["epoch"],
                 "stages": len(e["stages"])}
                for e in evs]}
        # introspection rides the same engine path as the bench load
        intro["tasks_by_state"] = {
            s: int(n) for s, n in cluster.execute_sql(
                "select state, count(*) from system.runtime.tasks "
                "group by state")}
        intro["nodes_by_state"] = {
            s: int(n) for s, n in cluster.execute_sql(
                "select state, count(*) from system.runtime.nodes "
                "group by state")}
        pstats = PROFILER.stats()
        intro["profiler"] = {
            "samples": pstats["samples"], "buckets": pstats["buckets"],
            "overhead": round(PROFILER.overhead_fraction(), 5)}
    finally:
        driver.close()
        if fleet is not None:
            out["ha"] = fleet.snapshot()
            fleet.close()
        cluster.stop()
        disc.stop()
        if journal_dir is not None:
            journal_dir.cleanup()
    out["wall_s"] = round(wall, 3)
    out["queries_per_sec"] = (round(out["executed"] / wall, 2)
                              if wall > 0 else 0.0)
    out["churn"] = {k: v for k, v in driver.report().items()
                    if k != "events"}
    out["membership"] = cluster.membership_snapshot()
    out["introspection"] = intro
    print(json.dumps({"metric": "elastic_churn_round",
                      "value": out["queries_per_sec"], "unit": "q/s",
                      "detail": {"churn": out}}))


def _cluster_mesh_child() -> None:
    """Cluster-mesh tier round: TPC-H q03/q18 through `TpuCluster`
    with `cluster_mesh_enabled=true` — the co-locatable plan fuses
    onto one mesh worker and its inter-stage exchanges ride ICI
    collectives — against the same queries on the plain HTTP path.
    Emits per-query walls, the ICI-vs-HTTP exchange byte split, and a
    rows-match bit between the two paths as one JSON line."""
    _ensure_host_devices()
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.server import mesh_tier
    from presto_tpu.server.cluster import TpuCluster

    sf = float(os.environ.get("BENCH_CLUSTER_MESH_SF", "0.01"))
    qids = [int(q) for q in os.environ.get(
        "BENCH_CLUSTER_MESH_QUERIES", "3,18").split(",") if q]
    conn = TpchConnector(sf)
    in_rows = sum(conn.table(t).num_rows
                  for t in ("customer", "orders", "lineitem"))
    cluster = TpuCluster(
        conn, n_workers=3,
        session_properties={"query_max_execution_time": "300",
                            "cluster_mesh_enabled": "true"})
    out = {"sf": sf, "queries": {}}
    total_wall = 0.0
    try:
        for qid in qids:
            sql = QUERIES[qid]
            # mesh path: warm (compile), then time; the tier metrics
            # bracket gives the bytes that moved over ICI collectives
            cluster.session_properties["cluster_mesh_enabled"] = "true"
            cluster.execute_sql(sql)
            ici0 = mesh_tier.ici_bytes_total()
            t0 = time.perf_counter()
            mesh_rows = cluster.execute_sql(sql)
            mesh_wall = time.perf_counter() - t0
            ici = int(mesh_tier.ici_bytes_total() - ici0)
            cm = dict(cluster.last_cluster_mesh or {})
            # HTTP control: identical query, tier off — its exchange
            # stats are the bytes the fusion replaced
            cluster.session_properties["cluster_mesh_enabled"] = "false"
            cluster.execute_sql(sql)
            t0 = time.perf_counter()
            http_rows = cluster.execute_sql(sql)
            http_wall = time.perf_counter() - t0
            exch = dict(cluster.last_exchange_stats or {})
            out["queries"][f"q{qid:02d}"] = {
                "mesh_wall_s": round(mesh_wall, 4),
                "http_wall_s": round(http_wall, 4),
                "result_rows": len(mesh_rows),
                # float tolerance: the two paths sum revenue in
                # different orders (associativity noise only)
                "rows_match_http": _mv_rows_match(
                    [list(r) for r in mesh_rows],
                    [list(r) for r in http_rows], rel=1e-6,
                    absol=1e-6),
                "ici_bytes": ici,
                "http_exchange_bytes": int(exch.get("bytes", 0)),
                "colocated_stages": int(cm.get("colocated_stages", 0)),
                "ndev": int(cm.get("ndev", 0)),
                "fallbacks": int(cm.get("fallbacks", 0)),
            }
            total_wall += mesh_wall
    finally:
        cluster.stop()
    qs = out["queries"].values()
    out["ici_bytes_total"] = sum(e["ici_bytes"] for e in qs)
    out["http_exchange_bytes_total"] = sum(
        e["http_exchange_bytes"] for e in qs)
    out["all_rows_match_http"] = all(e["rows_match_http"] for e in qs)
    out["wall_s"] = round(total_wall, 3)
    # input rows over the mesh-path wall: the lane throughput figure
    # bench_check compares round-over-round
    out["rows_per_sec"] = (round(in_rows * len(out["queries"])
                                 / total_wall, 1)
                           if total_wall > 0 else 0.0)
    print(json.dumps({"metric": "cluster_mesh_round",
                      "value": out["rows_per_sec"], "unit": "rows/s",
                      "detail": {"cluster_mesh": out}}))


def _run_cluster_mesh_child(timeout_s: float):
    """Run the cluster-mesh round in a subprocess; returns the
    `cluster_mesh` detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_CLUSTER_MESH_ONE="1",
                           BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "cluster_mesh", {"error": "child produced no cluster_mesh "
                                  "entry"})


def _run_churn_child(timeout_s: float):
    """Run the elastic churn round in a subprocess; returns the
    `churn` detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_CHURN_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "churn", {"error": "child produced no churn entry"})


def _mv_rows_match(a, b, rel=1e-9, absol=1e-6) -> bool:
    """Row-set equality with float tolerance (incremental merge and
    full recompute sum in different orders — associativity noise only)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                if abs(float(x) - float(y)) > max(
                        absol, rel * max(abs(float(x)), abs(float(y)))):
                    return False
            elif x != y:
                return False
    return True


def _mv_child() -> None:
    """Streaming-ingest + materialized-view round: a memory-connector
    lineitem grows continuously through the coordinator's
    `POST /v1/ingest` front door (seeded StreamDriver) while two
    materialized views over the same TPC-H-style aggregate are
    refreshed each round — one incrementally (watermark delta merge),
    one forced to a full recompute (drop + recreate). Emits per-round
    delta-row and wall costs, the steady-state incremental/full ratios
    the <25% acceptance gate reads, observed staleness, and an
    exactness bit (both views must agree every round)."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.exec import LocalEngine
    from presto_tpu.server.statement import StatementServer
    from presto_tpu.testing.stream import StreamDriver
    from presto_tpu.types import DOUBLE, VARCHAR

    seed = int(os.environ.get("BENCH_MV_SEED", "0"))
    seed_rows = int(os.environ.get("BENCH_MV_SEED_ROWS", "200000"))
    rounds = int(os.environ.get("BENCH_MV_ROUNDS", "5"))
    steps = int(os.environ.get("BENCH_MV_STEPS", "4"))

    flags = ("A", "N", "R")
    statuses = ("F", "O")

    def _row(rng, _ordinal):
        return (rng.choice(flags), rng.choice(statuses),
                round(rng.uniform(1.0, 50.0), 2),
                round(rng.uniform(900.0, 105000.0), 2))

    conn = MemoryConnector()
    conn.create("lineitem", [("l_returnflag", VARCHAR),
                             ("l_linestatus", VARCHAR),
                             ("l_quantity", DOUBLE),
                             ("l_extendedprice", DOUBLE)])
    import random as _random
    base_rng = _random.Random(f"{seed}:base")
    conn.append_rows("lineitem", [_row(base_rng, i)
                                  for i in range(seed_rows)])

    mv_sql = ("select l_returnflag, l_linestatus, count(*), "
              "sum(l_quantity), avg(l_extendedprice) from lineitem "
              "group by l_returnflag, l_linestatus")
    engine = LocalEngine(conn)
    srv = StatementServer(engine).start()
    driver = StreamDriver(srv.base, "lineitem", _row, seed=seed,
                          batch_min=200, batch_max=400)
    out = {"seed": seed, "seed_rows": seed_rows, "rounds": rounds,
           "per_round": [], "exact": True}
    try:
        engine.execute_sql(
            f"create materialized view bench_inc as {mv_sql}")
        engine.execute_sql("refresh materialized view bench_inc")
        mgr = engine.mv_manager

        def _stat(name):
            return next(s for s in mgr.stats() if s["name"] == name)

        for rnd in range(rounds):
            for _ in range(steps):
                driver.step()
            staleness = _stat("bench_inc")["staleness_seconds"]
            engine.execute_sql("refresh materialized view bench_inc")
            inc = _stat("bench_inc")
            # full-recompute cost of the same aggregate at the same
            # version: a fresh view's first refresh scans everything
            engine.execute_sql(
                f"create materialized view bench_full as {mv_sql}")
            engine.execute_sql("refresh materialized view bench_full")
            full = _stat("bench_full")
            if not _mv_rows_match(mgr.rows("bench_inc"),
                                  mgr.rows("bench_full")):
                out["exact"] = False
            engine.execute_sql("drop materialized view bench_full")
            out["per_round"].append({
                "round": rnd,
                "staleness_s": round(staleness, 3),
                "inc_kind": inc["last_refresh_kind"],
                "inc_delta_rows": inc["last_delta_rows"],
                "inc_wall_s": round(inc["last_refresh_duration_s"], 5),
                "full_delta_rows": full["last_delta_rows"],
                "full_wall_s": round(
                    full["last_refresh_duration_s"], 5)})
    finally:
        driver.close()
        srv.stop()
    out["ingest"] = driver.report()
    inc_rows = sum(r["inc_delta_rows"] for r in out["per_round"])
    full_rows = sum(r["full_delta_rows"] for r in out["per_round"])
    inc_wall = sum(r["inc_wall_s"] for r in out["per_round"])
    full_wall = sum(r["full_wall_s"] for r in out["per_round"])
    out["incremental_rounds"] = sum(
        1 for r in out["per_round"] if r["inc_kind"] == "incremental")
    out["rows_ratio"] = (round(inc_rows / full_rows, 4)
                         if full_rows else None)
    out["wall_ratio"] = (round(inc_wall / full_wall, 4)
                         if full_wall else None)
    # steady state = the rounds after plan/compile caches warmed (the
    # first two rounds pay one-time tracing for both refresh flavors)
    steady = out["per_round"][2:]
    s_inc_rows = sum(r["inc_delta_rows"] for r in steady)
    s_full_rows = sum(r["full_delta_rows"] for r in steady)
    s_inc_wall = sum(r["inc_wall_s"] for r in steady)
    s_full_wall = sum(r["full_wall_s"] for r in steady)
    out["steady_rows_ratio"] = (round(s_inc_rows / s_full_rows, 4)
                                if s_full_rows else None)
    out["steady_wall_ratio"] = (round(s_inc_wall / s_full_wall, 4)
                                if s_full_wall else None)
    # the acceptance gate: steady-state incremental refresh at <25% of
    # the full-recompute cost in BOTH scanned rows and wall time
    out["gate_under_25pct"] = bool(
        out["steady_rows_ratio"] is not None
        and out["steady_rows_ratio"] < 0.25
        and out["steady_wall_ratio"] is not None
        and out["steady_wall_ratio"] < 0.25)
    print(json.dumps({"metric": "mv_incremental_refresh_ratio",
                      "value": out["steady_wall_ratio"], "unit": "x",
                      "detail": {"mv": out}}))


def _run_mv_child(timeout_s: float):
    """Run the streaming-mv round in a subprocess; returns the `mv`
    detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_MV_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "mv", {"error": "child produced no mv entry"})


def _memory_child() -> None:
    """Memory-arbitration round: the same query is run unconstrained
    and then under a pool budget its static footprint cannot fit, so
    the engine must take a degraded-but-exact path — lifespan-batched
    fallback for the grouped aggregation, the Grace build-side spill
    join for the join-rooted shape. Emits per-lane wall costs (the
    price of surviving), spill/revocation counters proving the
    machinery actually fired, an exactness bit per lane, and a
    low-memory-killer demo (cluster budget blown -> biggest query dies
    with the EXCEEDED_MEMORY_LIMIT-class error)."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    import math
    import shutil
    import tempfile

    from presto_tpu.config import Session
    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine
    from presto_tpu.exec.memory import (
        ClusterMemoryManager, ExceededMemoryLimitError, MemoryPool,
    )

    sf = float(os.environ.get("BENCH_MEMORY_SF", "0.05"))
    conn = TpchConnector(sf)
    spill_dir = tempfile.mkdtemp(prefix="bench_memory_spill_")

    def _rows_close(got, want):
        if len(got) != len(want):
            return False
        for g, w in zip(sorted(got), sorted(want)):
            for gc, wc in zip(g, w):
                if isinstance(wc, float) or isinstance(gc, float):
                    if not math.isclose(gc, wc, rel_tol=1e-6,
                                        abs_tol=1e-9):
                        return False
                elif gc != wc:
                    return False
        return True

    #: (lane, sql, pool budget the footprint cannot fit)
    lanes = (
        ("fallback_agg",
         "select l_returnflag, l_linestatus, count(*), "
         "sum(l_quantity), sum(l_extendedprice) from lineitem "
         "group by l_returnflag, l_linestatus "
         "order by l_returnflag, l_linestatus",
         2 * 1024 * 1024),
        ("spill_join",
         "select n_name, r_name from nation, region "
         "where n_regionkey = r_regionkey order by 1, 2",
         6000),
    )
    out = {"sf": sf, "lanes": {}, "exact": True}
    try:
        for key, sql, budget in lanes:
            free_eng = LocalEngine(conn)
            free_eng.execute_sql(sql)              # compile warmup
            t0 = time.perf_counter()
            want = free_eng.execute_sql(sql)
            free_s = time.perf_counter() - t0

            pool = MemoryPool(budget)
            eng = LocalEngine(
                conn,
                session=Session({"spill_enabled": "true",
                                 "spill_path": spill_dir}),
                memory_pool=pool)
            eng.execute_sql(sql)                   # compile warmup
            t0 = time.perf_counter()
            got = eng.execute_sql(sql)
            pooled_s = time.perf_counter() - t0

            exact = _rows_close(got, want)
            out["exact"] = out["exact"] and exact
            entry = {
                "budget_bytes": budget,
                "rows": len(got),
                "wall_free_s": round(free_s, 4),
                "wall_pooled_s": round(pooled_s, 4),
                "slowdown": round(pooled_s / max(free_s, 1e-9), 2),
                "exact": exact,
                "pool": {"revocations": pool.revocations,
                         "revoked_bytes": pool.revoked_bytes,
                         "reserved_after": pool.reserved},
            }
            if eng.last_spill_join_stats is not None:
                entry["spill_join"] = eng.last_spill_join_stats
            if eng.last_memory_fallback_batches:
                entry["fallback_batches"] = \
                    eng.last_memory_fallback_batches
            out["lanes"][key] = entry

        # low-memory killer: node pool has headroom, the CLUSTER
        # budget is tiny; the bench query is the biggest over-budget
        # query and must die with the classified error
        pool = MemoryPool(1 << 40, revoke_threshold=1.0)
        mgr = ClusterMemoryManager([pool], budget_bytes=1000)
        eng = LocalEngine(conn, memory_pool=pool, cluster_memory=mgr)
        pool.reserve("bench_sentinel", 10)
        try:
            eng.execute_sql("select count(*) from region")
            out["killer"] = {"killed": False}
        except ExceededMemoryLimitError as e:
            out["killer"] = {"killed": True, "kills": mgr.kills,
                             "error": str(e)[:160]}
        finally:
            pool.free("bench_sentinel")
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)

    slowdowns = [v["slowdown"] for v in out["lanes"].values()
                 if v.get("slowdown", 0) > 0]
    geo = (math.exp(sum(math.log(s) for s in slowdowns)
                    / len(slowdowns)) if slowdowns else 0.0)
    out["constrained_slowdown_geomean"] = round(geo, 2)
    print(json.dumps({"metric": "memory_constrained_slowdown",
                      "value": out["constrained_slowdown_geomean"],
                      "unit": "x", "detail": {"memory": out}}))


def _run_memory_child(timeout_s: float):
    """Run the memory-arbitration round in a subprocess; returns the
    `memory` detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_MEMORY_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "memory", {"error": "child produced no memory entry"})


def _data_plane_page_blocks(n: int):
    """A lineitem-shaped wire page: 2 LONG keys, an INT line number,
    4 float64-as-LONG measures, 3 INT dates, 2 dictionary strings —
    the mixed-type shape the exchange actually ships."""
    import numpy as np

    from presto_tpu.protocol.serde import WireBlock

    rng = np.random.default_rng(11)
    blocks = [
        WireBlock("LONG_ARRAY",
                  rng.integers(0, 6_000_000, n, dtype=np.int64)),
        WireBlock("LONG_ARRAY",
                  rng.integers(0, 200_000, n, dtype=np.int64)),
        WireBlock("INT_ARRAY", rng.integers(1, 8, n, dtype=np.int32)),
    ]
    for _ in range(4):
        blocks.append(WireBlock(
            "LONG_ARRAY", rng.random(n).view(np.int64)))
    for _ in range(3):
        blocks.append(WireBlock(
            "INT_ARRAY",
            rng.integers(8000, 10600, n, dtype=np.int32)))
    d = WireBlock("VARIABLE_WIDTH",
                  np.array([b"A", b"N", b"R"], dtype=object))
    for _ in range(2):
        blocks.append(WireBlock(
            "DICTIONARY", rng.integers(0, 3, n, dtype=np.int32),
            dictionary=d))
    return blocks


def _data_plane_child() -> None:
    """Data-plane round: (1) serde encode/decode GB/s on a
    lineitem-shaped page (the zero-copy PageBuffer path), (2) spool +
    exchange drain GB/s — frames appended to a FrameFile, read back as
    memoryview ranges, every frame decoded, (3) q01/q06 at
    BENCH_DATA_PLANE_SF streamed through bounded scan runs
    (streaming_scan_rows) and checked against a direct numpy oracle
    (sqlite is infeasible at SF10)."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    import math

    import numpy as np

    from presto_tpu.protocol.serde import (
        decode_serialized_page, encode_serialized_page,
    )

    out = {}

    # ---- serde microbench -------------------------------------------
    n = int(os.environ.get("BENCH_DATA_PLANE_ROWS", "131072"))
    reps = int(os.environ.get("BENCH_DATA_PLANE_REPS", "10"))
    blocks = _data_plane_page_blocks(n)
    frame = encode_serialized_page(blocks)
    size = len(frame)
    encode_serialized_page(blocks)                 # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        encode_serialized_page(blocks)
    enc_s = (time.perf_counter() - t0) / reps
    decode_serialized_page(frame)                  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        decode_serialized_page(frame)
    dec_s = (time.perf_counter() - t0) / reps
    out["serde"] = {"rows": n, "frame_bytes": size,
                    "encode_gbps": round(size / enc_s / 1e9, 3),
                    "decode_gbps": round(size / dec_s / 1e9, 3)}

    # ---- spool + exchange drain -------------------------------------
    from presto_tpu.spool.files import FrameFile

    nframes = int(os.environ.get("BENCH_DATA_PLANE_FRAMES", "24"))
    ff = FrameFile(prefix="bench_data_plane_")
    try:
        for _ in range(nframes):
            ff.append(frame)
        total = size * nframes
        t0 = time.perf_counter()
        token, drained, pages = 0, 0, 0
        while True:
            frames, token = ff.read_range(token, 8 << 20)
            if not frames:
                break
            for fr in frames:
                decode_serialized_page(fr)
                drained += len(fr)
                pages += 1
        drain_s = time.perf_counter() - t0
        assert drained == total and pages == nframes
        out["drain"] = {"frames": nframes, "bytes": total,
                        "drain_gbps": round(total / drain_s / 1e9, 3)}
    finally:
        ff.close()

    # ---- q01/q06 at scale, streamed, oracle-exact -------------------
    from presto_tpu.config import Session
    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine
    from presto_tpu.exec.lifespan import execute_batched

    sf = float(os.environ.get("BENCH_DATA_PLANE_SF", "10"))
    run_rows = int(os.environ.get("BENCH_DATA_PLANE_RUN_ROWS",
                                  "2000000"))
    batches = int(os.environ.get("BENCH_DATA_PLANE_BATCHES", "8"))
    t0 = time.perf_counter()
    conn = TpchConnector(sf)
    t = conn.table("lineitem")
    gen_s = time.perf_counter() - t0
    nrows = int(t.num_rows)
    qty = t.arrays["l_quantity"][:nrows]
    eprice = t.arrays["l_extendedprice"][:nrows]
    disc = t.arrays["l_discount"][:nrows]
    sdate = t.arrays["l_shipdate"][:nrows]
    rf = t.arrays["l_returnflag"][:nrows]
    ls = t.arrays["l_linestatus"][:nrows]

    def close(g, w):
        return math.isclose(g, w, rel_tol=1e-6, abs_tol=1e-9)

    from presto_tpu.expr.compile import days_from_civil
    cutoff = days_from_civil(1998, 9, 2)

    # q01 oracle: grouped sums over the dictionary codes (StringDict is
    # sorted, so code order == ORDER BY 1, 2)
    keep = sdate <= cutoff
    key = rf[keep].astype(np.int64) * 64 + ls[keep]
    uniq, inv = np.unique(key, return_inverse=True)
    o_cnt = np.bincount(inv)
    o_qty = np.bincount(inv, weights=qty[keep])
    o_ep = np.bincount(inv, weights=eprice[keep])
    o_avg = np.bincount(inv, weights=disc[keep]) / o_cnt
    q01_want = [
        (t.dicts["l_returnflag"][int(k) // 64],
         t.dicts["l_linestatus"][int(k) % 64],
         o_qty[i], o_ep[i], o_avg[i], int(o_cnt[i]))
        for i, k in enumerate(uniq)]

    q06_keep = (disc >= 0.05) & (disc <= 0.07) & (qty < 24)
    q06_want = float((eprice[q06_keep] * disc[q06_keep]).sum())

    engine = LocalEngine(conn)
    session = Session({"streaming_scan_rows": str(run_rows)})
    lanes = {
        "q01": ("select l_returnflag, l_linestatus, sum(l_quantity), "
                "sum(l_extendedprice), avg(l_discount), count(*) "
                "from lineitem "
                "where l_shipdate <= date '1998-09-02' "
                "group by l_returnflag, l_linestatus order by 1, 2"),
        "q06": ("select sum(l_extendedprice * l_discount) from lineitem "
                "where l_discount between 0.05 and 0.07 "
                "and l_quantity < 24"),
    }
    out["queries"] = {"sf": sf, "lineitem_rows": nrows,
                      "gen_s": round(gen_s, 1), "batches": batches,
                      "streaming_scan_rows": run_rows, "exact": True}
    for name, sql in lanes.items():
        plan = engine.executor._resolve_subqueries(engine.plan_sql(sql))
        stats = {}
        t0 = time.perf_counter()
        page = execute_batched(conn, plan, batches, session=session,
                               stats=stats)
        wall = time.perf_counter() - t0
        got = page.to_pylist()
        if name == "q01":
            exact = len(got) == len(q01_want) and all(
                g[0] == w[0] and g[1] == w[1]
                and all(close(a, b) for a, b in zip(g[2:], w[2:]))
                for g, w in zip(got, q01_want))
        else:
            exact = close(got[0][0], q06_want)
        out["queries"]["exact"] = out["queries"]["exact"] and exact
        out["queries"][name] = {
            "wall_s": round(wall, 2), "exact": exact,
            "rows_per_sec": round(nrows / wall, 1), **stats}

    geo = math.sqrt(out["serde"]["encode_gbps"]
                    * out["serde"]["decode_gbps"])
    print(json.dumps({"metric": "data_plane_serde_gbps",
                      "value": round(geo, 3), "unit": "gb/s",
                      "detail": {"data_plane": out}}))


def _run_data_plane_child(timeout_s: float):
    """Run the data-plane round in a subprocess; returns the
    `data_plane` detail dict (or an {"error": ...} entry)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(BENCH_DATA_PLANE_ONE="1", BENCH_QUERIES=""),
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is None:
        tail = (r.stderr.splitlines() or [""])[-1]
        return {"error": f"no output (rc={r.returncode}) "
                         f"{tail[:120]}"[:200]}
    return json.loads(line).get("detail", {}).get(
        "data_plane", {"error": "child produced no data_plane entry"})


def _hbo_probe(conn, sql):
    """Adaptive-optimizer snapshot for one query: plan+execute it twice
    against ONE shared HistoryStore so the JSON shows the history-warm
    second run (run1 misses, run2 answers estimates from measurements).
    Each run uses a fresh engine — plan caches are per-engine, so run 2
    genuinely re-plans from history rather than reusing run 1's plan."""
    from presto_tpu.config import Session
    from presto_tpu.exec import LocalEngine
    from presto_tpu.plan.stats import HistoryStore

    hist = HistoryStore()
    out = {}
    for run in ("run1", "run2"):
        eng = LocalEngine(conn,
                          session=Session({"collect_stats": "true"}),
                          history=hist)
        h0 = (hist.hits, hist.misses)
        t0 = time.perf_counter()
        eng.execute_sql(sql)
        out[run] = {
            "wall_s": round(time.perf_counter() - t0, 4),
            "hbo_hits": hist.hits - h0[0],
            "hbo_misses": hist.misses - h0[1],
            "reorder_applied": eng.last_join_reorders,
            "df_lifespans_skipped": getattr(
                eng, "last_lifespan_stats", {}).get("skipped", 0),
        }
    out["history_entries"] = len(hist.rows)
    return out


def _plan_has_join(plan) -> bool:
    from presto_tpu.plan.nodes import JoinNode
    found = [False]

    def walk(n):
        if isinstance(n, JoinNode):
            found[0] = True
        for c in n.children():
            if c is not None and not found[0]:
                walk(c)
    walk(plan)
    return found[0]


def _bench_ladder(conn, engine, qid, sql, baseline, runs, warmup,
                  detail, batches, frag_first=False):
    """Fallback ladder: try execution modes in routing order until one
    produces a timing. Join-heavy plans route to the device mesh first
    (fragment-wise bounded programs over ICI exchanges beat both the
    whole-plan megaprogram and the lifespan-batched serial re-runs —
    BENCH_r03: q03 lifespan-batched ran at 0.455x sqlite); scan/agg
    shapes keep the fused lane first. An unbatchable plan shape is
    just a failed rung here, not a hard failure. The surviving entry
    records its `mode`; exhaustion emits modes_tried."""
    from presto_tpu.sql.parser import parse_sql

    key = f"q{qid:02d}"
    plan = engine.planner.plan_query(parse_sql(sql))
    ndev = _mesh_ndev()

    def fused():
        _bench_one(engine, qid, sql, baseline, runs, warmup, detail)

    def dist():
        _bench_one_dist(conn, qid, sql, baseline, runs, warmup, detail,
                        ndev)

    def batched_rung():
        _bench_one_batched(conn, qid, sql, baseline, runs, warmup,
                           detail, batches)

    rungs = [("fused", fused), (f"dist_mesh_{ndev}", dist),
             (f"lifespan_batched_{batches}", batched_rung)]
    if ndev <= 1:
        rungs = [r for r in rungs if not r[0].startswith("dist_mesh")]
    elif _plan_has_join(plan):
        rungs = [rungs[1], rungs[0], rungs[2]]
    if frag_first:
        rungs = sorted(rungs,
                       key=lambda r: not r[0].startswith("lifespan"))

    tried, errs = [], []
    for label, rung in rungs:
        try:
            rung()
        except Exception as e:  # noqa: BLE001 — fall to the next rung
            tried.append(label)
            errs.append(f"{label}: {_err(e)}")
            print(f"# {key}: {label} failed ({_err(e)}); "
                  "falling to next rung", file=sys.stderr)
            continue
        if tried:
            detail[key]["modes_tried"] = tried + [detail[key]["mode"]]
        # adaptive-optimizer visibility (ISSUE 9): two history-fed runs
        # per query; failure here must not fail a rung that timed fine
        try:
            detail[key]["hbo"] = _hbo_probe(conn, sql)
        except Exception as e:  # noqa: BLE001
            detail[key]["hbo"] = {"error": _err(e)}
        return
    detail[key] = {"error": "; ".join(errs)[:400], "modes_tried": tried}
    print(f"# {key}: ladder exhausted ({'; '.join(errs)[:200]})",
          file=sys.stderr)


def _bench_one_dist(conn, qid, sql, baseline, runs, warmup, detail,
                    ndev, prefix="q"):
    """Time the DISTRIBUTED path: the plan fragmented over an N-device
    local mesh (hash/range/broadcast exchanges as packed same-dtype
    all_to_all/all_gather collectives), each fragment a bounded
    shard_map program — the production join path (exec/dist_executor)."""
    import jax

    from presto_tpu.exec.dist_executor import DistEngine
    from presto_tpu.parallel import device_mesh

    dist = DistEngine(conn, device_mesh(ndev))
    ex = dist.executor
    plan = ex._prepare(ex._resolve_subqueries(dist.plan_sql(sql)))
    in_rows = sum(conn.table(t).num_rows
                  for t in sorted(_scan_tables(plan)))

    def once():
        out = ex._execute_prepared(plan)
        leaves = [c.values if hasattr(c, "values") else c.l3
                  for c in out.columns] + [out.num_rows]
        jax.block_until_ready(leaves)
        return out

    # Snapshot mesh stats from the FIRST execution: collective launches
    # and wire bytes are accounted at trace time, so warm re-dispatches
    # of cached programs report zeros.
    mesh = {}
    for i in range(max(warmup, 1)):
        once()
        if i == 0:
            mesh = dict(ex.last_mesh_stats or {})
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        once()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"{prefix}{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "mode": f"dist_mesh_{ndev}",
        "mesh": {k: mesh[k] for k in
                 ("fragments", "collectives", "wire_bytes",
                  "overflow_retries") if k in mesh},
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# {prefix}{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"ndev={ndev} sqlite={base_s:.2f}s "
          f"speedup={base_s / med if base_s else 0:.1f}x",
          file=sys.stderr)


def _bench_one_batched(conn, qid, sql, baseline, runs, warmup, detail,
                       batches):
    """Lifespan-batched timing: the driving scan streams in `batches`
    row-range lifespans through ONE prepared executor (grouped-execution
    shape; reference Lifespan.java). Shrinking the per-program shapes by
    `batches`x is what lets join-heavy plans compile on the remote TPU
    service at all — measured cold compile ~23 min, warm run seconds."""
    import jax

    from presto_tpu.config import Session
    from presto_tpu.exec.lifespan import BatchedRunner
    from presto_tpu.sql.analyzer import Planner
    from presto_tpu.sql.parser import parse_sql

    plan = Planner(conn).plan_query(parse_sql(sql))
    runner = BatchedRunner(
        conn, plan, batches,
        session=Session({"dynamic_filtering_enabled": "false"}))
    if not runner.batchable:
        raise RuntimeError(f"q{qid}: plan shape is not lifespan-batchable")
    in_rows = conn.table(runner.driving).num_rows
    for _ in range(warmup):
        out = runner.run()
        jax.block_until_ready(out.num_rows)
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = runner.run()
        jax.block_until_ready((out.columns[0].values if out.columns
                               else out.num_rows, out.num_rows))
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"q{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "mode": f"lifespan_batched_{batches}",
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# q{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"batches={batches} sqlite={base_s:.2f}s "
          f"speedup={base_s / med if base_s else 0:.1f}x",
          file=sys.stderr)


def _bench_one(engine, qid, sql, baseline, runs, warmup, detail,
               prefix="q"):
    """Time the production execution path (Executor.execute: fused
    whole-plan programs for scan/agg shapes, per-operator islands for
    join/window plans — exactly what a worker runs). Scans come from the
    device-resident page cache, so timed runs measure compute, not
    host->device upload."""
    import jax

    from presto_tpu.sql.parser import parse_sql

    ex = engine.executor
    plan = engine.planner.plan_query(parse_sql(sql))
    plan = ex._resolve_subqueries(plan)
    plan = ex._prepare(plan)
    in_rows = sum(
        engine.connector.table(t).num_rows
        for t in sorted(_scan_tables(plan)))

    def once():
        out = ex._execute_tree(plan)
        leaves = [c.values if hasattr(c, "values") else c.l3
                  for c in out.columns] + [out.num_rows]
        jax.block_until_ready(leaves)
        return out

    for _ in range(warmup):
        once()
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        once()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"{prefix}{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "mode": "islands" if ex._use_islands(plan) else "fused",
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# {prefix}{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"sqlite={base_s:.2f}s speedup={base_s/med if base_s else 0:.1f}x",
          file=sys.stderr)


def _scan_tables(plan) -> set:
    from presto_tpu.plan.nodes import TableScanNode
    out = set()

    def walk(n):
        if isinstance(n, TableScanNode):
            out.add(n.table)
        for c in n.children():
            if c is not None:
                walk(c)
    walk(plan)
    return out


if __name__ == "__main__":
    main()
