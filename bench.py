"""Benchmark: TPC-H Q1 throughput on the local accelerator.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Metric: lineitem rows/sec through the full jit-compiled Q1 fragment
(scan pages resident on device; filter+project+grouped aggregate+sort),
median of BENCH_RUNS timed runs after BENCH_WARMUP warmups. The reference
publishes no absolute numbers (BASELINE.md) — vs_baseline is measured
against the recorded Java single-node rows/sec when BASELINE_ROWS_PER_SEC
is set, else reported as 0.0 (unknown).

Env knobs: BENCH_SF (default 1.0), BENCH_RUNS (5), BENCH_WARMUP (2).
"""

import json
import os
import statistics
import sys
import time


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "5"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))

    import jax

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine
    from presto_tpu.sql.parser import parse_sql
    from __graft_entry__ import Q1

    engine = LocalEngine(TpchConnector(sf))
    plan = engine.planner.plan_query(parse_sql(Q1))

    caps = {}
    fn, scans, _watch = engine.executor._lower(plan, caps)
    pages = [engine.executor._fetch(s) for s in scans]
    in_rows = sum(int(p.num_rows) for p in pages)
    jitted = jax.jit(fn)

    for _ in range(warmup):
        out, _needed = jitted(pages)
        jax.block_until_ready(out.num_rows)

    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out, _needed = jitted(pages)
        jax.block_until_ready((out.columns[0].values, out.num_rows))
        times.append(time.perf_counter() - t0)

    med = statistics.median(times)
    rows_per_sec = in_rows / med
    base = float(os.environ.get("BASELINE_ROWS_PER_SEC", "0") or 0)
    vs = rows_per_sec / base if base > 0 else 0.0
    print(json.dumps({
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 3),
    }))
    print(f"# device={jax.devices()[0].platform} rows={in_rows} "
          f"median_s={med:.4f} groups={int(out.num_rows)} runs={times}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
