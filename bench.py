"""Benchmark: TPC-H throughput on the local accelerator, vs a measured
sqlite baseline over the IDENTICAL generated data.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline metric: geomean rows/s over the full 22-query TPC-H suite
(scan pages resident on device), per-query median of BENCH_RUNS timed
runs after warmup; `detail` carries every query's median/rows-per-sec/
vs_baseline. Scan/agg shapes run as one fused program; join/window
plans run as per-operator islands (exec/executor.py) — the same paths a
worker uses.

Baseline: the reference publishes no absolute numbers (BASELINE.md), and
no JVM exists in this environment, so the measured proxy is sqlite3
executing the same SQL over the same rows (the test suite's correctness
oracle, standing in for H2QueryRunner). It is measured once and cached in
BASELINE_MEASURED.json (keyed by scale factor) because loading SF1 into
sqlite takes minutes; delete the file to re-measure. Roofline context: Q1
touches ~7 of 16 lineitem columns ~= 0.4 GB at SF1; at v5e HBM bandwidth
(~820 GB/s) one pass is ~0.5 ms, so wall time is dominated by how few
passes the compiled fragment makes, not FLOPs.

Env knobs: BENCH_SF (default 1.0), BENCH_RUNS (5), BENCH_WARMUP (2),
BENCH_QUERIES (comma list or "all", the default), BENCH_FRAG_QUERIES
(comma list run lifespan-batched instead, default none),
BENCH_QUERY_TIMEOUT (s, default 2400).
"""

import json
import os
import statistics
import sys
import time
from typing import Optional

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")


def _err(e) -> str:
    """Errors ride the final JSON line the driver parses — keep them
    short (a full axon compiler log once made the line unparseable)."""
    return f"{type(e).__name__}: {e}"[:200]


def _sqlite_db(conn):
    """Load the generated tables into sqlite once (minutes at SF1)."""
    import sqlite3

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from oracle import table_df

    db = sqlite3.connect(":memory:")
    tables = ["region", "nation", "supplier", "customer", "part",
              "partsupp", "orders", "lineitem"]
    for t in tables:
        df = table_df(conn, t)
        # DATE ints -> ISO strings for sqlite comparability
        for col in df.columns:
            if conn.table(t).types[col].name == "date":
                import datetime
                epoch = datetime.date(1970, 1, 1)
                df[col] = df[col].map(
                    lambda d: (epoch + datetime.timedelta(days=int(d))
                               ).isoformat())
        df.to_sql(t, db, index=False)
    return db


#: cap per sqlite query: index-less nested-loop joins can run for hours;
#: an interrupted query records the cap as a FLOOR (our vs_baseline then
#: understates the speedup — the honest direction)
SQLITE_QUERY_CAP_S = float(os.environ.get("BENCH_SQLITE_CAP", "900"))


def measure_sqlite_baseline(conn, sf, qids, db=None):
    """Wall time per query in sqlite3 over the same generated rows."""
    import threading

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from test_tpch_full import to_sqlite  # dialect bridge
    from tpch_queries import QUERIES

    own = db is None
    if own:
        db = _sqlite_db(conn)
    out = {}
    for qid in qids:
        sql = to_sqlite(QUERIES[qid])
        timer = threading.Timer(SQLITE_QUERY_CAP_S, db.interrupt)
        timer.start()
        t0 = time.perf_counter()
        try:
            db.execute(sql).fetchall()
            out[str(qid)] = time.perf_counter() - t0
        except Exception:   # noqa: BLE001 — interrupted: cap = floor
            out[str(qid)] = SQLITE_QUERY_CAP_S
            print(f"# sqlite q{qid}: interrupted at "
                  f"{SQLITE_QUERY_CAP_S:.0f}s (baseline is a floor)",
                  file=sys.stderr)
        finally:
            timer.cancel()
    if own:
        db.close()
    return out


def load_or_measure_baseline(conn, sf, qids):
    key = f"sf{sf:g}"
    data = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            data = json.load(f)
    missing = [q for q in qids
               if str(q) not in data.get(key, {}).get("sqlite_seconds", {})]
    if missing:
        # measure AND save one query at a time (single shared db load):
        # heavy sqlite joins at SF1 take many minutes each, and a
        # timeout mid-way must not discard the queries already measured
        db = _sqlite_db(conn)
        run_measured = {}       # survives a failed/raced file write
        for qid in missing:
            run_measured.update(
                measure_sqlite_baseline(conn, sf, [qid], db=db))
            if os.path.exists(BASELINE_FILE):
                with open(BASELINE_FILE) as f:
                    data = json.load(f)
            entry = data.setdefault(key, {}).setdefault(
                "sqlite_seconds", {})
            entry.update(run_measured)
            data[key]["note"] = (
                "sqlite3 :memory: wall seconds on identical generated "
                "data; measured on this machine, cached (delete file "
                "to re-measure)")
            try:
                tmp = f"{BASELINE_FILE}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump(data, f, indent=1, sort_keys=True)
                os.replace(tmp, BASELINE_FILE)
            except OSError:
                pass
    return data[key]["sqlite_seconds"]


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "5"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    spec = os.environ.get("BENCH_QUERIES", "all")
    qids = (list(range(1, 23)) if spec == "all"
            else [int(q) for q in spec.split(",")])
    frag_qids = {int(q) for q in os.environ.get(
        "BENCH_FRAG_QUERIES", "").split(",") if q}
    if os.environ.get("BENCH_CHILD") != "1":
        return _main_orchestrator(sf, qids)

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:  # functional testing off-TPU (e.g. BENCH_PLATFORM=cpu)
        import jax
        jax.config.update("jax_platforms", plat)

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.connectors import TpchConnector
    from presto_tpu.exec import LocalEngine

    conn = TpchConnector(sf)
    engine = LocalEngine(conn)
    baseline = load_or_measure_baseline(conn, sf, qids)

    batched = int(os.environ.get("BENCH_LIFESPAN_BATCHES", "8"))
    detail = {}
    for qid in qids:
        try:
            if qid in frag_qids:
                _bench_one_batched(conn, qid, QUERIES[qid], baseline,
                                   runs, warmup, detail, batched)
            else:
                _bench_one(engine, qid, QUERIES[qid], baseline, runs,
                           warmup, detail)
        except Exception as e:  # noqa: BLE001 — a failed query must not
            # take down the whole benchmark report
            detail[f"q{qid:02d}"] = {"error": _err(e)}
            print(f"# q{qid:02d}: FAILED {_err(e)}", file=sys.stderr)

    head_name, head = _headline(detail)
    print(json.dumps({
        "metric": f"tpch_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }))


def _headline(detail):
    """Suite geomean over every query that ran (rows/s and
    vs_baseline); a single query's failure lowers coverage but cannot
    zero the report. Falls back to q01 when fewer than 3 queries
    succeeded (e.g. a smoke run)."""
    import math

    clean = {k: v for k, v in detail.items()
             if "error" not in v and v.get("rows_per_sec", 0) > 0}
    if len(clean) >= 3:
        rps = [v["rows_per_sec"] for v in clean.values()]
        vsb = [v["vs_baseline"] for v in clean.values()
               if v.get("vs_baseline", 0) > 0]
        geo = math.exp(sum(math.log(x) for x in rps) / len(rps))
        geo_vs = (math.exp(sum(math.log(x) for x in vsb) / len(vsb))
                  if vsb else 0.0)
        return f"geomean{len(clean)}q", {
            "rows_per_sec": round(geo, 1),
            "vs_baseline": round(geo_vs, 3)}
    for pref in ("q01", "q06"):
        if pref in clean:
            return pref, clean[pref]
    if clean:
        k = sorted(clean)[0]
        return k, clean[k]
    k = sorted(detail)[0] if detail else "none"
    return k, {"rows_per_sec": 0.0, "vs_baseline": 0.0}


def _probe_device(timeout_s: float) -> Optional[str]:
    """Compile-and-run a trivial program on the default backend in a
    subprocess. Returns None when healthy, else a short error string.
    Guards the whole report: a wedged accelerator tunnel otherwise eats
    every per-query timeout back to back."""
    import subprocess

    plat = os.environ.get("BENCH_PLATFORM")
    pre = (f"import jax; jax.config.update('jax_platforms', {plat!r}); "
           if plat else "import jax; ")
    code = (pre + "import jax.numpy as jnp;"
            "print('PROBE', int(jax.jit(lambda a, b: a + b)"
            "(jnp.int32(2), jnp.int32(3))), jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s,
                           env=dict(os.environ, BENCH_CHILD="1"))
    except subprocess.TimeoutExpired:
        return f"device probe timed out after {timeout_s:.0f}s"
    if "PROBE 5" not in r.stdout:
        tail = (r.stderr.splitlines() or [""])[-1]
        return f"device probe failed (rc={r.returncode}) {tail}"[:200]
    return None


def _main_orchestrator(sf, qids) -> None:
    """Run each query in its own subprocess with a hard timeout: a wedged
    accelerator tunnel or a compiler crash on one query must not take
    down the whole benchmark report (the driver consumes the final JSON
    line unconditionally)."""
    import subprocess

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "600"))
    err = _probe_device(probe_timeout)
    if err is not None:
        print(f"# device probe: {err}", file=sys.stderr)
        print(json.dumps({
            "metric": f"tpch_q01_sf{sf:g}_rows_per_sec",
            "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            "detail": {"error": err},
        }))
        return

    # Per-query budget: warm (cached) queries run in seconds; a cold
    # island-program compile through the remote service takes minutes.
    timeout_s = float(os.environ.get("BENCH_QUERY_TIMEOUT", "2400"))
    detail = {}
    for qid in qids:
        env = dict(os.environ, BENCH_CHILD="1", BENCH_QUERIES=str(qid))
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=timeout_s)
            sys.stderr.write(r.stderr.splitlines()[-1] + "\n"
                             if r.stderr.splitlines() else "")
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is None:
                tail = (r.stderr.splitlines() or [""])[-1][:120]
                detail[f"q{qid:02d}"] = {
                    "error": f"no output (rc={r.returncode}) {tail}"[:200]}
            else:
                detail.update(json.loads(line).get("detail", {}))
        except subprocess.TimeoutExpired:
            detail[f"q{qid:02d}"] = {
                "error": f"timeout after {timeout_s:.0f}s"}
            print(f"# q{qid:02d}: TIMEOUT after {timeout_s:.0f}s",
                  file=sys.stderr)
    # whole-plan q1 can hit remote-compile stalls; retry it
    # lifespan-batched (small programs) before giving up on a number
    if 1 in qids and "error" in detail.get("q01", {}):
        print("# q01: retrying lifespan-batched", file=sys.stderr)
        env = dict(os.environ, BENCH_CHILD="1", BENCH_QUERIES="1",
                   BENCH_FRAG_QUERIES="1")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=join_timeout_s)
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is not None:
                got = json.loads(line).get("detail", {})
                if "error" not in got.get("q01", {"error": 1}):
                    detail.update(got)
        except subprocess.TimeoutExpired:
            print("# q01 batched retry: TIMEOUT", file=sys.stderr)

    head_name, head = _headline(detail)
    print(json.dumps({
        "metric": f"tpch_{head_name}_sf{sf:g}_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "detail": detail,
    }))


def _bench_one_batched(conn, qid, sql, baseline, runs, warmup, detail,
                       batches):
    """Lifespan-batched timing: the driving scan streams in `batches`
    row-range lifespans through ONE prepared executor (grouped-execution
    shape; reference Lifespan.java). Shrinking the per-program shapes by
    `batches`x is what lets join-heavy plans compile on the remote TPU
    service at all — measured cold compile ~23 min, warm run seconds."""
    import jax

    from presto_tpu.config import Session
    from presto_tpu.exec.lifespan import BatchedRunner
    from presto_tpu.sql.analyzer import Planner
    from presto_tpu.sql.parser import parse_sql

    plan = Planner(conn).plan_query(parse_sql(sql))
    runner = BatchedRunner(
        conn, plan, batches,
        session=Session({"dynamic_filtering_enabled": "false"}))
    if not runner.batchable:
        raise RuntimeError(f"q{qid}: plan shape is not lifespan-batchable")
    in_rows = conn.table(runner.driving).num_rows
    for _ in range(warmup):
        out = runner.run()
        jax.block_until_ready(out.num_rows)
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = runner.run()
        jax.block_until_ready((out.columns[0].values if out.columns
                               else out.num_rows, out.num_rows))
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"q{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "mode": f"lifespan_batched_{batches}",
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# q{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"batches={batches} sqlite={base_s:.2f}s "
          f"speedup={base_s / med if base_s else 0:.1f}x",
          file=sys.stderr)


def _bench_one(engine, qid, sql, baseline, runs, warmup, detail):
    """Time the production execution path (Executor.execute: fused
    whole-plan programs for scan/agg shapes, per-operator islands for
    join/window plans — exactly what a worker runs). Scans come from the
    device-resident page cache, so timed runs measure compute, not
    host->device upload."""
    import jax

    from presto_tpu.sql.parser import parse_sql

    ex = engine.executor
    plan = engine.planner.plan_query(parse_sql(sql))
    plan = ex._resolve_subqueries(plan)
    plan = ex._prepare(plan)
    in_rows = sum(
        engine.connector.table(t).num_rows
        for t in sorted(_scan_tables(plan)))

    def once():
        out = ex._execute_tree(plan)
        leaves = [c.values if hasattr(c, "values") else c.hi
                  for c in out.columns] + [out.num_rows]
        jax.block_until_ready(leaves)
        return out

    for _ in range(warmup):
        once()
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        once()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    base_s = baseline.get(str(qid), 0.0)
    detail[f"q{qid:02d}"] = {
        "median_s": round(med, 4),
        "rows_per_sec": round(in_rows / med, 1),
        "input_rows": in_rows,
        "sqlite_baseline_s": round(base_s, 4),
        "vs_baseline": round(base_s / med, 3) if base_s else 0.0,
    }
    print(f"# q{qid:02d}: median={med:.4f}s rows={in_rows} "
          f"sqlite={base_s:.2f}s speedup={base_s/med if base_s else 0:.1f}x",
          file=sys.stderr)


def _scan_tables(plan) -> set:
    from presto_tpu.plan.nodes import TableScanNode
    out = set()

    def walk(n):
        if isinstance(n, TableScanNode):
            out.add(n.table)
        for c in n.children():
            if c is not None:
                walk(c)
    walk(plan)
    return out


if __name__ == "__main__":
    main()
