"""presto_tpu — a TPU-native distributed SQL execution framework.

A ground-up re-design of the capabilities of Presto (reference:
/root/reference, see SURVEY.md) for TPU hardware:

- columnar Pages are fixed-capacity padded device arrays (Column = values +
  null mask; strings are codes into *sorted* host-side dictionaries), so every
  operator is a statically-shaped XLA program — no recompilation storms
  (SURVEY.md §7.3 hard part #1);
- operators (scan/filter/project, grouped aggregation, joins, sort/topN,
  window) are jit-compiled whole-fragment kernels rather than the reference's
  pull-based Operator.getOutput/addInput driver loop
  (reference: presto-main-base/.../operator/Driver.java:70);
- the repartitioned exchange (reference:
  presto-main-base/.../operator/repartition/PartitionedOutputOperator.java:57)
  is a hash-partitioned `all_to_all` over a `jax.sharding.Mesh` (ICI) inside a
  multi-chip worker, and Presto's pull-based HTTP SerializedPage protocol
  across hosts (DCN);
- the coordinator-facing protocol (PlanFragment / TaskUpdateRequest /
  TaskInfo; reference: presto-main-base/.../server/TaskUpdateRequest.java:37)
  is implemented as plain dataclasses + JSON codec so the worker grafts onto
  an unmodified Java coordinator exactly like presto-native-execution's C++
  worker (reference: presto-native-execution/presto_cpp/main/TaskResource.cpp).
"""

import os as _os

import jax

# Inheritable platform pin: this environment's sitecustomize registers the
# remote-TPU platform *programmatically*, so the JAX_PLATFORMS env var alone
# is ignored by child processes. Subprocesses we spawn (CLI under test, bench
# children, cluster workers) honor PRESTO_TPU_PLATFORM instead — set before
# any backend initializes, so a wedged TPU tunnel can't hang a child that
# was meant to run on CPU.
_plat = _os.environ.get("PRESTO_TPU_PLATFORM")
if _plat:
    try:
        jax.config.update("jax_platforms", _plat)
    except Exception:   # noqa: BLE001 — backend already initialized
        pass

# SQL semantics need exact 64-bit integers (BIGINT) and doubles. TPU emulates
# f64/i64; the hot paths (filter masks, hashes, group codes) stay in 32-bit.
jax.config.update("jax_enable_x64", True)

# XLA's CPU compiler recurses deeply on large fragment programs (multi-join
# TPC-H fragments segfault at the default 8 MiB stack); the main-thread
# stack grows on demand up to RLIMIT_STACK, so raise it to the hard limit.
try:
    import resource

    _soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
    if _soft != resource.RLIM_INFINITY:
        resource.setrlimit(resource.RLIMIT_STACK, (_hard, _hard))
except (ImportError, ValueError, OSError):  # non-POSIX or locked down
    pass

# Persistent compilation cache: TPU compiles of big fragment programs run
# minutes through the remote-compile service (Q1's direct-aggregation
# program: ~18 min cold); cached executables load in <1 s, so a process
# restart (bench per-query subprocesses, worker restarts) doesn't repay
# the compile. Reference role: the JVM's C2-warmed operator factories
# simply persist in-process; here the cache file is the analog.
# Opt out with PRESTO_TPU_NO_COMPILE_CACHE=1.
if not _os.environ.get("PRESTO_TPU_NO_COMPILE_CACHE"):
    _cache_dir = _os.environ.get(
        "PRESTO_TPU_COMPILE_CACHE",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      _os.pardir, ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          5.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:   # noqa: BLE001 — cache is best-effort
        pass

from presto_tpu.types import (  # noqa: E402
    BOOLEAN, TINYINT, SMALLINT, INTEGER, BIGINT, REAL, DOUBLE, VARCHAR, DATE,
    TIMESTAMP, DecimalType, Type,
)
from presto_tpu.data.column import Column, Page  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "BOOLEAN", "TINYINT", "SMALLINT", "INTEGER", "BIGINT", "REAL", "DOUBLE",
    "VARCHAR", "DATE", "TIMESTAMP", "DecimalType", "Type", "Column", "Page",
]
