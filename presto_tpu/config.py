"""Session properties + engine configuration.

Reference roles: SystemSessionProperties (presto-main-base/.../
SystemSessionProperties.java — 305 typed, per-query-overridable knobs in
one registry) and the native worker's SystemConfig
(presto_cpp/main/common/Configs.h:162). Scoped to the knobs this engine
actually consumes; each property declares a type and default, values
parse from strings exactly like session properties on the wire
(SessionRepresentation.systemProperties).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes", "on")


def _parse_bytes(s: str) -> int:
    s = s.strip().upper()
    for suffix, mult in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10),
                         ("B", 1)):
        if s.endswith(suffix):
            return int(float(s[:-len(suffix)]) * mult)
    return int(s)


@dataclasses.dataclass(frozen=True)
class Property:
    name: str
    description: str
    parse: Callable[[str], Any]
    default: Any


# The registry — one row per knob, like SystemSessionProperties' list.
PROPERTIES = [
    Property("query_max_memory_per_node",
             "Static plan-footprint limit per query; exceeding it raises "
             "MemoryLimitExceeded (or triggers lifespan batching)",
             _parse_bytes, None),
    Property("lifespan_batches",
             "Row-range lifespans to stream the driving scan in "
             "(0 = single shot)", int, 0),
    Property("streaming_scan_rows",
             "Bound the rows a driving leaf scan materializes at once: "
             "each lifespan streams through the partial plan in scan "
             "runs of at most this many rows (0 = whole-split "
             "materialization; the SF10 scale-ladder knob)", int, 0),
    Property("group_count_hint",
             "Default aggregation output-capacity hint when the planner "
             "has no estimate", int, 65536),
    Property("merge_join_enabled",
             "Use the sort-merge join fast path for unique build keys",
             _parse_bool, True),
    Property("execution_mode",
             "Plan lowering granularity: 'auto' splits join/window/"
             "union-bearing plans into per-operator fusion islands "
             "(bounded XLA program size — the remote TPU compile "
             "service OOMs on fused whole-plan join programs), 'fused' "
             "always lowers one whole-plan program, 'island' always "
             "splits", str, "auto"),
    Property("direct_agg_max_bins",
             "Max mixed-radix bins for the scatter-free small-domain "
             "aggregation path", int, 64),
    Property("exchange_chunk_factor",
             "Per-peer exchange chunk = factor * capacity / n_devices",
             int, 2),
    Property("capacity_annealing_enabled",
             "Shrink learned capacities back toward the observed "
             "high-water mark after a converged run (costs one recompile "
             "at the smaller bucket, then every later run executes the "
             "smaller program)", _parse_bool, True),
    Property("collect_stats",
             "Record per-node output row counts for EXPLAIN ANALYZE",
             _parse_bool, False),
    Property("cte_materialization_enabled",
             "Execute WITH subqueries referenced more than once into "
             "temp tables instead of inlining per reference (reference: "
             "PhysicalCteOptimizer / cte_materialization_strategy)",
             _parse_bool, False),
    Property("spill_enabled",
             "Offload accumulated lifespan partials out of device HBM "
             "(reference: spiller/ + revocable memory): host RAM by "
             "default, disk when spill_path is set",
             _parse_bool, True),
    Property("spill_path",
             "Directory for spill files (FileSingleStreamSpiller role; "
             "empty = host-RAM offload only)", str.strip, ""),
    Property("broadcast_join_threshold_rows",
             "Estimated build-side rows under which a join replicates "
             "its build instead of hash-exchanging both sides "
             "(reference: join_distribution_type AUTOMATIC + "
             "join_max_broadcast_table_size)", int, 50_000),
    Property("dynamic_filtering_enabled",
             "Prune driving-scan lifespans whose join-key range cannot "
             "match the build side (reference: "
             "enable_dynamic_filtering / DynamicFilterSourceOperator)",
             _parse_bool, True),
    Property("dynamic_filter_wait_ms",
             "Upper bound (milliseconds) a probe-side stage waits for a "
             "tiny build fragment's key domain before scheduling its "
             "scans unfiltered (cross-exchange dynamic filtering; "
             "reference: experimental.dynamic-filtering max blocking "
             "wait)", int, 400),
    Property("join_reordering_enabled",
             "Commute inner equi-joins so the smaller estimated side "
             "becomes the hash build (plan/iterative.ReorderJoins, "
             "history-first estimates; reference: "
             "join_reordering_strategy AUTOMATIC)", _parse_bool, True),
    Property("join_distribution_type",
             "AUTOMATIC (cost-based broadcast-vs-repartition) | "
             "PARTITIONED (always hash exchanges) | BROADCAST (force "
             "replicated builds where legal); reference: "
             "SystemSessionProperties.JOIN_DISTRIBUTION_TYPE",
             str.strip, "AUTOMATIC"),
    Property("query_max_execution_time",
             "Wall-clock budget per query in seconds (0 = unlimited); "
             "exceeded -> the query FAILS (reference: "
             "QUERY_MAX_EXECUTION_TIME + QueryTracker enforcement)",
             float, 0.0),
    Property("hash_partition_count",
             "Tasks per hash-partitioned intermediate stage in the "
             "cluster (0 = one per worker; reference: "
             "SystemSessionProperties.HASH_PARTITION_COUNT)", int, 0),
    Property("exchange_compression_codec",
             "Compress exchange pages: none | zlib | gzip | lz4 "
             "(LZ4 block format in the native C++ codec; reference: "
             "exchange_compression_codec, PagesSerdeFactory + "
             "CompressionCodec.java:16)", str.strip, "none"),
    Property("fragment_result_cache_enabled",
             "Worker-side fragment result caching for eligible leaf "
             "fragments, keyed on semantic plan fingerprint + table "
             "versions + splits (reference: fragment_result_caching_"
             "enabled, Presto@Meta VLDB'23 worker result cache)",
             _parse_bool, False),
    Property("retry_policy",
             "Mid-query fault handling: NONE (a worker death fails the "
             "query, whole-query retry only) | TASK (task outputs spool "
             "to disaggregated storage and only the lost tasks re-plan "
             "onto survivors as attempt N+1; reference: retry-policy "
             "TASK, Presto@Meta VLDB'23 §3 / Project Tardigrade)",
             lambda s: s.strip().upper(), "NONE"),
    Property("cluster_mesh_enabled",
             "Route eligible cluster task fragments (join/agg-bearing, "
             "mesh-lowerable) through the worker device-mesh execution "
             "tier (server/mesh_tier.py), and let the coordinator fuse "
             "co-locatable stages onto one mesh worker so the "
             "repartition exchange rides ICI collectives instead of "
             "HTTP page pulls; any lowering failure falls back to the "
             "generic executor + HTTP path byte-for-byte",
             _parse_bool, False),
]

_BY_NAME = {p.name: p for p in PROPERTIES}


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Intra-cluster HTTP transport knobs (reference: the reference
    engine's HttpClientConfig / ExchangeClientConfig — request timeouts,
    backoff schedule, failure-detector thresholds — one config object
    instead of per-call-site literals). Per-request-class timeouts and
    retry counts live here; `protocol/transport.py` builds its policy
    table from this registry."""

    # per-request-class (timeout seconds, attempts incl. the first try)
    probe_timeout_s: float = 2.0           # /v1/info liveness probes
    probe_attempts: int = 1                # a probe IS the retry
    control_timeout_s: float = 10.0        # ack / abort / delete / info
    control_attempts: int = 2
    page_fetch_timeout_s: float = 30.0     # results GETs (long-poll)
    page_fetch_attempts: int = 5           # ExchangeClient.java:322 role
    status_poll_timeout_s: float = 30.0    # task status long-polls
    status_poll_attempts: int = 3
    task_post_timeout_s: float = 60.0      # TaskUpdateRequest POSTs
    task_post_attempts: int = 4            # at-least-once update protocol
    announce_timeout_s: float = 5.0        # discovery announcements
    announce_attempts: int = 1             # the announcer loop re-tries
    statement_timeout_s: float = 30.0      # client statement protocol
    statement_attempts: int = 3
    remote_function_timeout_s: float = 60.0
    remote_function_attempts: int = 3

    # exponential backoff + full jitter between retryable failures
    retry_base_backoff_s: float = 0.05
    retry_max_backoff_s: float = 2.0
    # total time a single logical request may spend retrying
    retry_budget_s: float = 15.0

    # per-worker circuit breaker (HeartbeatFailureDetector role):
    # consecutive failures to OPEN, then a cooldown before ONE
    # half-open probe may test whether the worker recovered
    breaker_failure_threshold: int = 3
    breaker_cooldown_s: float = 5.0

    # cap on a server-advised Retry-After sleep (overload responses,
    # 429 / 503 + Retry-After header); the retry budget still applies
    retry_after_max_s: float = 30.0


#: process defaults; tests construct their own with tighter windows
DEFAULT_TRANSPORT = TransportConfig()


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Serving-tier knobs (reference: the reference engine's
    HttpServerConfig — acceptor/selector threads, max request header
    size, idle connection timeout — plus HttpClientConfig's connection
    pool sizing). One per process; `net/aio_server.AioHttpServer` and
    the keep-alive pool in `protocol/transport.py` are built from
    this."""

    # -- server (event-loop front door) ------------------------------
    #: bounded executor threads for CPU/blocking handler dispatch —
    #: the only per-server thread growth (no thread-per-connection)
    executor_workers: int = 8
    #: slowloris guard: a connection that has not delivered complete
    #: request headers within this window is closed
    header_timeout_s: float = 10.0
    #: close a keep-alive connection idle (between requests) this long
    idle_timeout_s: float = 60.0
    #: cap on concurrently open server connections; beyond it new
    #: accepts are closed immediately (pool exhaustion is load-shed at
    #: the door, not queued into memory)
    max_connections: int = 4096
    #: event-loop lag heartbeat cadence: a timer fires at this interval
    #: and the observed overshoot lands in
    #: `net_event_loop_lag_seconds` — blocked-loop detection
    loop_lag_tick_s: float = 0.25
    #: spooled result ranges at least this large go out via
    #: `os.sendfile` instead of read+write (small ranges aren't worth
    #: the extra syscalls)
    sendfile_min_bytes: int = 4096

    # -- client (keep-alive connection pool) -------------------------
    #: idle pooled connections kept per destination host:port
    pool_per_host: int = 8
    #: evict a pooled connection idle longer than this (must stay
    #: under typical server idle_timeout_s so we rarely pick up a
    #: connection the server is about to close)
    pool_idle_ttl_s: float = 30.0


#: process defaults; tests construct their own with tighter windows
DEFAULT_NET = NetConfig()


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Fragment-result-cache knobs (reference: FragmentCacheStats +
    fragment-result-cache config in the native worker; Presto@Meta
    VLDB'23 §4.2). One per worker process — the task manager builds its
    `FragmentResultCache` from this."""

    #: master switch for the worker-side store (the session property
    #: `fragment_result_cache_enabled` additionally gates per query)
    enabled: bool = True
    #: byte budget for cached pages on one worker
    budget_bytes: int = 256 << 20
    #: refuse entries larger than this (one giant scan must not wipe
    #: the whole cache); 0 = budget_bytes
    max_entry_bytes: int = 32 << 20
    #: mirror cached bytes into the node MemoryPool so cache residency
    #: competes with execution reservations
    account_in_memory_pool: bool = False

    def entry_cap(self) -> int:
        return self.max_entry_bytes or self.budget_bytes


#: process defaults
DEFAULT_CACHE = CacheConfig()


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (reference: the Prometheus exporter config in
    the native worker + the coordinator's tracing/event-listener
    enablement). One per process; `obs/metrics.py` instruments and the
    cluster's trace sampling consult it."""

    #: master switch for metric collection (endpoints still respond,
    #: counters simply stay at their last value when off)
    metrics_enabled: bool = True
    #: master switch for span recording / trace propagation
    tracing_enabled: bool = True
    #: fraction of cluster queries that carry a trace (1.0 = all);
    #: unsampled queries send no X-Presto-Trace header, so workers open
    #: no spans for them
    trace_sample_rate: float = 1.0
    #: per-trace span cap forwarded to utils/tracing.Tracer — beyond it
    #: spans are counted as dropped instead of accumulating
    max_spans_per_trace: int = 2048
    #: wall-time histogram buckets (seconds)
    time_buckets_s: tuple = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0,
                             30.0, 120.0)
    #: row-count histogram buckets
    rows_buckets: tuple = (1.0, 100.0, 10_000.0, 100_000.0,
                           1_000_000.0, 10_000_000.0, 100_000_000.0)
    #: wide-event query log sink (obs/wide_events.py): JSONL path the
    #: coordinator appends one QueryCompletedEvent to per cluster query;
    #: None keeps the in-memory ledger only. PRESTO_TPU_EVENT_LOG
    #: overrides at sink-install time.
    event_log_path: Optional[str] = None
    #: rotate the event log when it exceeds this many bytes
    event_log_max_bytes: int = 16 << 20
    #: rotated generations kept (event_log.1 .. event_log.N)
    event_log_max_files: int = 3
    #: always-on sampling profiler (obs/profiler.py) master switch
    profiler_enabled: bool = True
    #: profiler sampling frequency (Hz); the sampler self-throttles
    #: whenever its own cost exceeds `profiler_max_overhead`
    profiler_hz: float = 97.0
    #: retained stack buckets per (role, purpose, query) key
    profiler_top_k: int = 64
    #: frames kept per sampled stack (deepest-callee end)
    profiler_max_depth: int = 24
    #: self-time budget as a fraction of wall time — above it the
    #: sampler doubles its sleep until it is back under budget
    profiler_max_overhead: float = 0.01

    # -- telemetry history (obs/tsdb.py) -----------------------------
    #: master switch for the in-process time-series store + scraper
    tsdb_enabled: bool = True
    #: history retention window (seconds): points older than this are
    #: dropped from every series (ring-buffer bound, per series)
    tsdb_retention_s: float = 900.0
    #: minimum spacing between stored points per series (the write
    #: chokepoint drops anything closer than this to the series'
    #: newest point)
    tsdb_resolution_s: float = 0.05
    #: minimum spacing between heartbeat-path scrape SWEEPS — pump
    #: loops and probers may call check_workers() at tens of Hz, but a
    #: full sweep (registry render + one HTTP fetch per live worker +
    #: parse) runs at most this often; query-bracket sweeps bypass
    #: this throttle (force=True) but fetch no workers
    tsdb_sweep_interval_s: float = 2.0
    #: series cap: beyond it new series are dropped (counted in
    #: `obs_scrape_points_dropped_total`) instead of growing unbounded
    tsdb_max_series: int = 16384
    #: hard cap on retained points per series (rings are bounded by
    #: BOTH retention_s and this count)
    tsdb_max_points: int = 2048
    #: scraper self-time budget as a fraction of wall time — the same
    #: methodology as profiler_max_overhead: when cumulative scrape
    #: self-time exceeds this fraction, scrapes are skipped until the
    #: ratio is back under budget (<1% overhead by construction)
    tsdb_max_overhead: float = 0.01

    # -- alerting (obs/alerts.py) ------------------------------------
    #: master switch for alert-rule evaluation (rules stay registered,
    #: evaluation is skipped when off)
    alerts_enabled: bool = True
    #: default evaluation window (seconds) for rules that do not set
    #: their own — thresholds look at the latest sample in the window,
    #: burn-rate rules at the counter increase across it
    alert_window_s: float = 60.0
    #: default pending->firing dwell (seconds) for rules that do not
    #: set their own `for_s`
    alert_for_s: float = 10.0
    #: alert-transition history ring capacity (system.runtime.alerts
    #: and the wide-event sink both read from it)
    alert_history_cap: int = 256

    def sampled(self, rng_value: float) -> bool:
        """Decide sampling from a caller-supplied uniform [0,1) draw
        (kept injectable for deterministic tests)."""
        return self.tracing_enabled \
            and rng_value < self.trace_sample_rate


#: process defaults
DEFAULT_OBS = ObsConfig()


@dataclasses.dataclass(frozen=True)
class SpoolConfig:
    """Spooled-exchange knobs (reference: the exchange-manager /
    exchange.base-directories config behind Presto's TASK retry policy —
    Presto@Meta VLDB'23 §3, Trino Project Tardigrade). One per process;
    `spool/store.SpoolStore` is built from this. The shared `base_dir`
    plays the role of disaggregated storage: every node of a cluster
    must see the same directory."""

    #: master switch for the worker-side spool store (the session
    #: property `retry_policy=TASK` additionally gates per query)
    enabled: bool = False
    #: shared spool root; None = the store creates its own temp root
    base_dir: Optional[str] = None
    #: SerializedPage frame compression for spooled pages
    codec: str = "lz4"
    #: sweep committed/partial spools left by dead processes when a
    #: store opens over an existing base_dir
    sweep_on_start: bool = True
    #: only sweep orphans older than this many seconds (0 = any age)
    orphan_ttl_s: float = 0.0


#: process defaults — off: spooling costs a disk write per output page
DEFAULT_SPOOL = SpoolConfig()


@dataclasses.dataclass(frozen=True)
class ExchangeConfig:
    """Concurrent-exchange knobs (reference: ExchangeClientConfig behind
    operator/ExchangeClient.java — maxBufferedBytes, maxResponseSize,
    concurrentRequestMultiplier). One per process; every
    `protocol/exchange.ExchangeClient` is built from this."""

    #: total decoded-chunk bytes (accounted by wire size) the client may
    #: hold in its in-flight buffer before fetchers park — the true
    #: backpressure bound (ExchangeClient.java maxBufferedBytes). An
    #: empty buffer always admits one chunk even if it alone exceeds
    #: the cap, so the effective bound is
    #: max(max_buffered_bytes, one chunk) and progress never deadlocks.
    max_buffered_bytes: int = 32 << 20
    #: per-GET response cap sent as X-Presto-Max-Size (ExchangeClient's
    #: maxResponseSize): one pull round never materializes more than
    #: this per stream
    max_response_bytes: int = 4 << 20
    #: simultaneous in-flight GETs across all of a client's streams
    #: (concurrentRequestMultiplier role); 0 = one per stream,
    #: unbounded across streams
    max_concurrent_fetchers: int = 16
    #: X-Presto-Max-Wait long-poll window per GET
    max_wait: str = "1s"


#: process defaults
DEFAULT_EXCHANGE = ExchangeConfig()


@dataclasses.dataclass(frozen=True)
class MeshTierConfig:
    """Cluster mesh execution tier knobs (server/mesh_tier.py): the
    worker-side device-mesh task runner plus the coordinator's
    co-location policy. Mirrors the reference's native-worker swap
    (PAPER.md L6a TaskExecutor / L7 exchange): the execution tier
    changes, the coordinator protocol does not."""

    #: worker side: advertise a mesh slice and accept mesh-lowered
    #: task fragments (per query still gated by the session property
    #: `cluster_mesh_enabled`)
    enabled: bool = True
    #: devices in this worker's mesh slice; 0 = every visible device
    ndev: int = 0
    #: ICI domain id — co-location requires producer and consumer to
    #: share one group (single-host default: every worker sees the
    #: same device set, so one group)
    mesh_group: str = "local"
    #: coordinator side: fuse co-locatable producer/consumer stages
    #: onto one mesh worker so the exchange rides ICI collectives
    colocate: bool = True
    #: refuse to fuse plans wider than this many HTTP-path fragments
    #: (a very wide plan concentrated on one worker loses more to lost
    #: scan parallelism than it gains from ICI exchange)
    max_colocate_fragments: int = 8


#: process defaults
DEFAULT_MESH_TIER = MeshTierConfig()


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Statement front-door knobs (reference: dispatcher/
    DispatchManager + query-manager config — max-queued-queries,
    dispatcher concurrency — plus the resource-group manager's queue
    limits). One per coordinator; `admission/DispatchManager` and its
    `LoadShedder` are built from this."""

    #: bounded execution pool: how many statements run concurrently
    #: (replaces the old unbounded thread-per-query path)
    max_dispatch_threads: int = 8
    #: pool-thread housekeeping interval — queue-timeout eviction and
    #: memory-quota re-checks happen at least this often while idle
    dispatch_tick_s: float = 0.25
    #: default per-group queue timeout applied when a group does not
    #: set its own (None = wait forever, bounded by the client)
    default_queue_timeout_s: Optional[float] = None

    # -- load shedding thresholds ------------------------------------
    #: refuse new statements when this many are queued across all
    #: resource groups
    shed_max_queued: int = 256
    #: refuse when memory-pool reserved/budget reaches this fraction
    shed_heap_fraction: float = 0.95
    #: refuse when the recent p99 admission queue wait reaches this
    shed_queue_wait_p99_s: float = 20.0
    #: Retry-After interval advertised on shed responses
    retry_after_s: float = 1.0
    #: recent queue-wait samples kept for the p99 shedding signal and
    #: the /v1/status percentiles
    wait_window: int = 1024


#: process defaults
DEFAULT_ADMISSION = AdmissionConfig()


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic-cluster knobs (reference: the graceful-shutdown handler
    in the native worker — PrestoServer's shutdown sequence drains
    tasks before exiting — plus Presto@Meta VLDB'23 §3's fluid worker
    membership). One per process; the worker's drain path and the
    coordinator's query journal are built from this."""

    #: upper bound a draining worker waits for its running tasks to
    #: finish before shutting down anyway (tasks past the deadline are
    #: left to TASK-retry recovery on the coordinator)
    drain_timeout_s: float = 30.0
    #: poll interval while waiting for running tasks to drain
    drain_poll_s: float = 0.05
    #: write-ahead query journal location; None = journaling off (the
    #: statement server keeps no crash-recoverable query log)
    journal_path: Optional[str] = None
    #: compact the journal (rewrite live records only) once the dead-
    #: record count crosses this threshold
    journal_compact_threshold: int = 256
    #: how long a coordinator restart keeps absorbing journaled RUNNING
    #: queries before declaring them failed (0 = re-run immediately)
    recover_grace_s: float = 0.0
    #: crash-recovery re-queue cap: a journaled query that has already
    #: been re-queued this many times by coordinator restarts is
    #: abandoned with a terminal FAILED record instead of re-running —
    #: under repeated coordinator crashes an unbounded recovery storm
    #: would otherwise clog admission with orphaned re-executions
    recover_max_requeues: int = 3


#: process defaults — journaling off: tests opt in with a tmp path
DEFAULT_ELASTIC = ElasticConfig()


@dataclasses.dataclass(frozen=True)
class MemoryConfig:
    """Memory-arbitration knobs (reference: NodeMemoryConfig +
    MemoryManagerConfig — query.max-memory-per-node and
    query.max-memory — plus the MemoryRevokingScheduler's
    revoking-threshold). One per process; each worker's
    `TaskManager` builds its node `MemoryPool` from this and the
    coordinator derives the cluster budget for the low-memory
    killer."""

    #: per-node pool budget (query.max-memory-per-node role): the sum
    #: of static plan footprints admitted on one worker; 0 disables
    #: arbitration (tasks run unpooled, the pre-PR-14 behavior)
    pool_bytes: int = 0
    #: fraction of the pool at which revocation hooks fire BEFORE a
    #: reservation can fail (memory-revoking-threshold role)
    revoke_threshold: float = 0.8
    #: cluster-wide query-memory budget for the low-memory killer
    #: (query.max-memory role); 0 derives it from the sum of worker
    #: pool budgets
    cluster_bytes: int = 0
    #: master switch for the coordinator's low-memory killer sweep —
    #: with it off an over-budget cluster only refuses new admissions
    kill_enabled: bool = True

    def cluster_budget(self, n_workers: int) -> int:
        if self.cluster_bytes:
            return self.cluster_bytes
        return self.pool_bytes * max(n_workers, 1)


#: process defaults — arbitration off: tests and benches opt in
DEFAULT_MEMORY = MemoryConfig()


@dataclasses.dataclass(frozen=True)
class MVConfig:
    """Materialized-view maintenance knobs (presto_tpu/mv/; reference:
    the incrementally maintained MV half of Presto@Meta's VLDB'23
    data-freshness story). One per MV manager."""

    #: byte budget of the pinned accumulator-state cache; MV state is
    #: pinned (never LRU-evicted) inside a FragmentResultCache, so this
    #: bounds total pinned bytes across all views
    state_budget_bytes: int = 64 << 20
    #: background refresher: a view whose base tables moved and whose
    #: last refresh is older than this gets re-refreshed by the
    #: mv-refresh admission tenant
    staleness_target_s: float = 5.0
    #: background refresher poll cadence
    refresh_tick_s: float = 0.5
    #: bounded full recompute: refuse a full-recompute refresh when the
    #: base tables hold more rows than this (the incremental path has
    #: no such bound — its cost scales with the delta, not the table)
    max_full_recompute_rows: int = 200_000_000
    #: MV definition journal location; None derives it from the
    #: elastic query-journal path (+ ".mv") when one is configured
    journal_path: Optional[str] = None
    #: compact the MV journal once dead records cross this threshold
    journal_compact_threshold: int = 64


DEFAULT_MV = MVConfig()


class Session:
    """One query session: defaults overridden by string-typed properties
    (the wire form). Unknown properties are rejected loudly, like the
    coordinator does."""

    def __init__(self, properties: Optional[Dict[str, str]] = None,
                 user: str = "user", catalog: str = "tpch",
                 schema: str = "default"):
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.values: Dict[str, Any] = {
            p.name: p.default for p in PROPERTIES}
        for name, raw in (properties or {}).items():
            prop = _BY_NAME.get(name)
            if prop is None:
                raise KeyError(f"unknown session property {name!r}")
            self.values[name] = prop.parse(raw)

    def __getitem__(self, name: str):
        return self.values[name]

    def get(self, name: str, default=None):
        return self.values.get(name, default)

    @staticmethod
    def describe() -> str:
        """SHOW SESSION analog."""
        out = []
        for p in PROPERTIES:
            out.append(f"{p.name} (default {p.default!r}): "
                       f"{p.description}")
        return "\n".join(out)
