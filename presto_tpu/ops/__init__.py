from presto_tpu.ops.keys import sort_perm, hash_columns, SortKey
from presto_tpu.ops.aggregate import grouped_aggregate, AggSpec
from presto_tpu.ops.join import hash_join
from presto_tpu.ops.sort import sort_page, top_n, limit_page

__all__ = ["sort_perm", "hash_columns", "SortKey", "grouped_aggregate",
           "AggSpec", "hash_join", "sort_page", "top_n", "limit_page"]
