"""Mark-distinct: flag the first occurrence of each key combination.

Reference: operator/MarkDistinctOperator.java + MarkDistinctHash — used
to plan MIXED plain/DISTINCT aggregates (count(x), count(DISTINCT x) in
one SELECT): the distinct aggregate becomes a plain aggregate masked by
the marker (MultipleDistinctAggregationToMarkDistinct rule).

TPU shape: one multi-operand lax.sort by the key lanes carrying every
page column as payload (the compact()/sort_page idiom — no random
gathers), then marker[i] = keys[i] != keys[i-1]. Row order changes,
which is immaterial to the aggregation consuming the marker."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops.keys import group_values, values_equal
from presto_tpu.types import BOOLEAN


def mark_distinct(page: Page, key_fields: Sequence[int],
                  marker_name: str = "_distinct") -> Page:
    """Page -> same rows (reordered) + trailing BOOLEAN marker column,
    True on the first row of each (key...) combination. Padding rows are
    ordered last and never marked. NULL keys form their own group (SQL
    DISTINCT treats NULLs as equal)."""
    cap = page.capacity
    pad_last = (~page.row_valid()).astype(jnp.int8)
    key_ops = [pad_last]
    for f in key_fields:
        c = page.columns[f]
        key_ops.append(c.nulls.astype(jnp.int8))
        key_ops.append(group_values(c))
    operands = tuple(key_ops)
    for c in page.columns:
        operands += (c.values, c.nulls)
    out = jax.lax.sort(operands, num_keys=len(key_ops), is_stable=False)

    # first-occurrence detection over the sorted key lanes
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for ki in range(1, len(key_ops)):
        lane = out[ki]
        prev = jnp.concatenate([lane[:1], lane[:-1]])
        first = first | ~values_equal(lane, prev)
    first = first & (out[0] == 0)          # padding rows unmarked

    pos = len(key_ops)
    cols = []
    for c in page.columns:
        cols.append(Column(out[pos], out[pos + 1], c.type, c.dictionary))
        pos += 2
    marker = Column(first, jnp.zeros(cap, dtype=bool), BOOLEAN, None)
    return Page(tuple(cols) + (marker,), page.num_rows,
                page.names + (marker_name,))
