"""Mark-distinct: flag the first occurrence of each key combination.

Reference: operator/MarkDistinctOperator.java + MarkDistinctHash — used
to plan MIXED plain/DISTINCT aggregates (count(x), count(DISTINCT x) in
one SELECT): the distinct aggregate becomes a plain aggregate masked by
the marker (MultipleDistinctAggregationToMarkDistinct rule).

TPU shape: one multi-operand lax.sort by the key lanes carrying every
page column as payload (the compact()/sort_page idiom — no random
gathers), then marker[i] = keys[i] != keys[i-1]. Row order changes,
which is immaterial to the aggregation consuming the marker."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops.keys import group_values, values_equal
from presto_tpu.types import BOOLEAN


def mark_distinct(page: Page, key_fields: Sequence[int],
                  marker_name: str = "_distinct") -> Page:
    """Page -> same rows (reordered) + trailing BOOLEAN marker column,
    True on the first row of each (key...) combination. Padding rows are
    ordered last and never marked. NULL keys form their own group (SQL
    DISTINCT treats NULLs as equal)."""
    from presto_tpu.data.column import gather_page
    from presto_tpu.ops.keys import lex_perm

    cap = page.capacity
    pad_last = (~page.row_valid()).astype(jnp.int8)
    key_ops = [pad_last]
    for f in key_fields:
        c = page.columns[f]
        key_ops.append(c.nulls.astype(jnp.int8))
        key_ops.append(group_values(c))
    # permutation over key lanes only; payload moves by gather (wide
    # variadic sorts explode compile cost on this stack)
    perm = lex_perm(key_ops)
    s_lanes = [lane[perm] for lane in key_ops]

    # first-occurrence detection over the sorted key lanes
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for lane in s_lanes[1:]:
        prev = jnp.concatenate([lane[:1], lane[:-1]])
        first = first | ~values_equal(lane, prev)
    first = first & (s_lanes[0] == 0)      # padding rows unmarked

    out = gather_page(page, perm)
    marker = Column(first, jnp.zeros(cap, dtype=bool), BOOLEAN, None)
    return Page(out.columns + (marker,), page.num_rows,
                page.names + (marker_name,))
