"""Joins — sorted-build, searchsorted-probe, vectorized pair expansion.

Reference roles: HashBuilderOperator/LookupJoinOperator
(presto-main-base/.../operator/HashBuilderOperator.java:55,
LookupJoinOperator.java:52 over PagesHash/JoinProbe), HashSemiJoinOperator,
NestedLoopJoinOperator. TPU-first redesign: no pointer-chasing hash table —
the build side is sorted by a 64-bit key hash (one argsort), probes binary-
search the sorted hashes (jnp.searchsorted is vectorized), and the variable
match fan-out is materialized by a prefix-sum pair expansion into a page of
*static* capacity. Hash-equal-but-key-unequal pairs (collisions, multi-key)
are masked by an exact key comparison on the expanded pairs.

Capacity contract: like aggregation, `out_capacity` bounds the join output;
`total_pairs` (traced) lets the executor detect overflow and retry at a
larger bucket.

NULL join keys never match (SQL semantics), enforced by tagging null-key
rows with disjoint sentinel hashes on each side.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.expr.compile import align_string_columns
from presto_tpu.ops.keys import group_values, hash_columns, \
    values_equal


def _aligned_keys(probe: Page, build: Page, probe_fields, build_fields):
    """Pull key columns, aligning string dictionaries across sides."""
    pcols, bcols = [], []
    for pf, bf in zip(probe_fields, build_fields):
        pc, bc = probe.columns[pf], build.columns[bf]
        if pc.type.is_string and bc.type.is_string:
            pc, bc = align_string_columns(pc, bc)
        pcols.append(pc)
        bcols.append(bc)
    return pcols, bcols


def merge_join(probe: Page, build: Page,
               probe_fields: Sequence[int], build_fields: Sequence[int],
               join_type: str = "inner",
               ) -> Tuple[Page, jnp.ndarray]:
    """Sort-merge join for UNIQUE build keys (+ semi/anti, where
    duplicates cannot change the answer). The TPU-native replacement for
    the searchsorted probe: binary search with millions of queries and
    random pair-expansion gathers both serialize on TPU, while this path
    is two multi-operand sorts plus blocked fill-forward scans.

      1. Co-sort build+probe rows by the actual key values, build rows
         first within a key run.
      2. Blocked fill-forward (ops/scan.py) propagates each build row's
         payload and key to the probe slots after it — a probe slot
         matches iff the propagated key equals its own.
      3. A second sort restores probe order carrying only the per-probe
         results; probe columns never move at all.

    Returns (page, dup_count, match) where dup_count > 0 means the build
    side had duplicate live keys: for inner/left/full the caller must
    fall back to the expansion join (hash_join); semi/anti results stay
    valid. `match` is the per-probe-row match flag in probe order for
    left/full (None otherwise) — outer-join residual filters need it to
    demote failed matches to null-extensions. Output layout matches
    hash_join: probe cols ++ build cols (inner/left/full; full appends
    the unmatched build rows null-extended on the probe side), or probe
    cols ++ match flag (semi/anti/anti_exists).

    Reference roles: MergeJoinNode / sorted-exchange MergeOperator
    (presto-main-base/.../operator/MergeOperator.java) fused with the
    LookupJoin contract (LookupJoinOperator.java:52).
    """
    from presto_tpu.ops.scan import fill_forward

    pcap, bcap = probe.capacity, build.capacity
    cap = bcap + pcap
    pcols, bcols = _aligned_keys(probe, build, probe_fields, build_fields)

    p_null = jnp.zeros((pcap,), dtype=bool)
    for c in pcols:
        p_null = p_null | c.nulls
    b_null = jnp.zeros((bcap,), dtype=bool)
    for c in bcols:
        b_null = b_null | c.nulls

    b_present = build.row_valid() & ~b_null
    p_live = probe.row_valid()

    def cat(b, p):
        return jnp.concatenate([b, p])

    # Sort PERMUTATION via ops/keys.lex_perm (composed 2-operand stable
    # argsorts): per key column (nulls, values), then build-before-probe
    # tag least significant. NO wide variadic sort — on this stack
    # lax.sort compile cost explodes with operand count (a ~20-operand
    # sort at SF1 shapes never finishes compiling), while argsort +
    # gather compiles in seconds and gathers run at memory bandwidth.
    # Dead rows need no sort lane: propagation only flows from `present`
    # build rows, and matches mask on the gathered null/live flags.
    from presto_tpu.ops.keys import lex_perm
    tag = cat(jnp.zeros((bcap,), jnp.int8), jnp.ones((pcap,), jnp.int8))
    lanes = []
    for pc, bc in zip(pcols, bcols):
        lanes.append(cat(bc.nulls, pc.nulls))
        lanes.append(cat(group_values(bc), group_values(pc)))
    lanes.append(tag)
    perm = lex_perm(lanes)

    present = cat(b_present, jnp.zeros((pcap,), bool))
    s_present = present[perm]
    # Propagate (build source index + 1) forward: one scan yields both
    # the candidate build row and the seen flag for every sorted slot.
    src1 = cat(jnp.arange(1, bcap + 1, dtype=jnp.int32),
               jnp.zeros((pcap,), jnp.int32))
    ff = fill_forward(jnp.where(s_present, src1[perm], 0), s_present)

    # Duplicate live build keys: adjacent present build rows, equal keys.
    prev_present = jnp.roll(s_present, 1).at[0].set(False)
    same_key = jnp.ones((cap,), bool)
    s_kv = []     # sorted key lanes (value, null) per key — reused below
    for pc, bc in zip(pcols, bcols):
        kv = cat(group_values(bc), group_values(pc))[perm]
        kn = cat(bc.nulls, pc.nulls)[perm]
        s_kv.append((kv, kn))
        same_key = same_key & values_equal(kv, jnp.roll(kv, 1)) & ~kn \
            & ~jnp.roll(kn, 1)
    dup_count = jnp.sum(s_present & prev_present & same_key
                        ).astype(jnp.int64)

    # Restore probe order by inverting the permutation: probe row j sits
    # at sorted slot inv[bcap + j].
    inv = jnp.argsort(perm)
    q = inv[bcap:]                               # [pcap]
    ffq = ff[q]
    bidx = jnp.maximum(ffq - 1, 0)               # candidate build row
    match_p = (ffq > 0) & p_live & ~p_null
    for pc, bc in zip(pcols, bcols):
        bv = group_values(bc)[bidx]
        bn = bc.nulls[bidx]
        match_p = match_p & values_equal(group_values(pc), bv) & ~bn

    # FULL outer also needs per-BUILD-row matched flags: a present build
    # row is matched iff its key run contains a live non-null-key probe
    # row. Runs are contiguous after the sort, so count probes per run
    # with blocked scans — no gathers.
    b_matched = None
    if join_type == "full":
        from presto_tpu.ops.scan import cumsum as bl_cumsum
        from presto_tpu.ops.scan import fill_backward

        is_probe = tag[perm].astype(bool)
        any_key_null = jnp.zeros((cap,), bool)
        run_start = jnp.zeros((cap,), bool).at[0].set(True)
        for kv, kn in s_kv:
            any_key_null = any_key_null | kn
            same = (values_equal(kv, jnp.roll(kv, 1))
                    & ~kn & ~jnp.roll(kn, 1)) \
                | (kn & jnp.roll(kn, 1))
            run_start = run_start | ~same
        run_start = run_start.at[0].set(True)
        s_live = cat(build.row_valid(), p_live)[perm]
        probe_contrib = (is_probe & s_live & ~any_key_null
                         ).astype(jnp.int32)
        cs_p = bl_cumsum(probe_contrib)
        before_run = fill_forward(
            jnp.where(run_start, cs_p - probe_contrib, 0), run_start)
        run_end = jnp.roll(run_start, -1).at[-1].set(True)
        at_run_end = fill_backward(jnp.where(run_end, cs_p, 0), run_end)
        probes_in_run = at_run_end - before_run
        b_matched_cat = s_present & (probes_in_run > 0)
        b_matched = b_matched_cat[inv[:bcap]]    # build original order

    if join_type in ("semi", "anti", "anti_exists"):
        if join_type == "semi":
            flag = match_p
        elif join_type == "anti_exists":
            flag = ~match_p & p_live
        else:
            b_has_null = jnp.any(b_null & build.row_valid())
            flag = ~match_p & ~p_null & ~b_has_null & p_live
        col = Column(flag, jnp.zeros((pcap,), bool), _bool_type(), None)
        out = Page(probe.columns + (col,), probe.num_rows, ())
        return out, dup_count, None

    # Build payload lands by direct gather in probe order — nothing is
    # carried through the sorts at all.
    out_cols = list(probe.columns)
    for c in build.columns:
        out_cols.append(c.gather(bidx, match_p))

    if join_type == "left":
        return Page(tuple(out_cols), probe.num_rows, ()), dup_count, \
            match_p
    if join_type == "full":
        page = Page(tuple(out_cols), probe.num_rows, ())
        unmatched = build.row_valid() & ~b_matched
        out = full_outer_append(page, probe, build, unmatched)
        return out, dup_count, match_p
    # inner: keep only matched probe rows.
    from presto_tpu.data.column import compact
    page = Page(tuple(out_cols), probe.num_rows, ())
    return compact(page, match_p), dup_count, None


def full_outer_append(left_page: Page, probe: Page, build: Page,
                      unmatched_build: jnp.ndarray) -> Page:
    """Append unmatched build rows (probe side null) to a left-join page.
    Output capacity = pcap + bcap, survivors compacted with one sort."""
    from presto_tpu.data.column import compact

    pcap, bcap = probe.capacity, build.capacity
    cols = []
    for i, c in enumerate(left_page.columns):
        if i < len(probe.columns):
            t = probe.columns[i].type
            pad_v = jnp.full((bcap,), t.null_sentinel(), dtype=c.values.dtype)
            vals = jnp.concatenate([c.values, pad_v])
            nulls = jnp.concatenate([c.nulls, jnp.ones((bcap,), bool)])
        else:
            b = build.columns[i - len(probe.columns)]
            vals = jnp.concatenate([c.values, b.values])
            nulls = jnp.concatenate([c.nulls, b.nulls])
        cols.append(Column(vals, nulls, c.type, c.dictionary))
    keep = jnp.concatenate([
        jnp.arange(pcap, dtype=jnp.int32) < left_page.num_rows,
        unmatched_build])
    n = jnp.sum(keep).astype(jnp.int32)
    page = Page(tuple(cols), jnp.asarray(pcap + bcap, jnp.int32), ())
    out = compact(page, keep)
    return Page(out.columns, n, ())


def hash_join(probe: Page, build: Page,
              probe_fields: Sequence[int], build_fields: Sequence[int],
              out_capacity: int, join_type: str = "inner",
              ) -> Tuple[Page, jnp.ndarray]:
    """Join probe x build. Output columns = probe columns ++ build columns
    (for semi/anti: probe columns only). Returns (page, total_pairs) where
    total_pairs > out_capacity indicates overflow (host retries bigger).

    join_type: inner | left | semi | anti. ("left" = probe-outer, matching
    the planner's probe/build orientation, cf. JoinNode probe=left child.)
    """
    pcap, bcap = probe.capacity, build.capacity
    if probe_fields:
        pcols, bcols = _aligned_keys(probe, build, probe_fields,
                                     build_fields)
        ph = hash_columns(pcols)
        bh = hash_columns(bcols)
    else:
        # cross join: constant key — every live row pairs with every live row
        pcols, bcols = [], []
        ph = jnp.zeros((pcap,), dtype=jnp.int64)
        bh = jnp.zeros((bcap,), dtype=jnp.int64)

    p_null = jnp.zeros((pcap,), dtype=bool)
    for c in pcols:
        p_null = p_null | c.nulls
    b_null = jnp.zeros((bcap,), dtype=bool)
    for c in bcols:
        b_null = b_null | c.nulls

    # Disjoint sentinels so null/padding keys can never pair up.
    p_live = probe.row_valid() & ~p_null
    b_live = build.row_valid() & ~b_null
    ph = jnp.where(p_live, ph, jnp.int64(-1))
    bh = jnp.where(b_live, bh, jnp.int64(-2))

    order = jnp.argsort(bh, stable=True)
    bh_sorted = bh[order]

    lo = jnp.searchsorted(bh_sorted, ph, side="left")
    hi = jnp.searchsorted(bh_sorted, ph, side="right")
    counts = jnp.where(p_live, hi - lo, 0).astype(jnp.int64)

    if join_type in ("semi", "anti", "anti_exists"):
        # Need >=1 *true* match; verify keys over the candidate window via a
        # bounded scan on the max bucket width (collision windows are tiny).
        matched = _window_any_match(pcols, bcols, order, lo, counts)
        if join_type == "semi":
            flag = matched
        elif join_type == "anti_exists":
            # NOT EXISTS: null keys simply never match; non-matching rows
            # survive (no three-valued NOT IN poisoning).
            flag = ~matched
        else:
            # SQL NOT IN: if the build side contains ANY null key, every
            # non-match is UNKNOWN -> anti join emits nothing; a null probe
            # key is likewise never anti-matched.
            b_has_null = jnp.any(b_null & build.row_valid())
            flag = ~matched & ~p_null & ~b_has_null
        col = Column(flag, jnp.zeros((pcap,), dtype=bool), _bool_type(), None)
        out = Page(probe.columns + (col,), probe.num_rows, ())
        return out, jnp.sum(counts)

    if join_type == "left":
        counts = jnp.where(p_live | (probe.row_valid() & ~p_live),
                           jnp.maximum(counts, jnp.where(
                               probe.row_valid(), 1, 0)), counts)
        # rows with no candidates still emit one (null-extended) pair
    from presto_tpu.ops.scan import cumsum as blocked_cumsum
    cum = blocked_cumsum(counts)     # jnp.cumsum at 8M is pathological
    total = cum[-1] if pcap > 0 else jnp.int64(0)

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    pair_valid = j < total
    pidx = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    pidx_c = jnp.clip(pidx, 0, pcap - 1)
    start = cum[pidx_c] - counts[pidx_c]
    offset = j - start
    bpos = (lo[pidx_c] + offset).astype(jnp.int32)
    real_candidate = (offset < (hi[pidx_c] - lo[pidx_c])) & p_live[pidx_c]
    bidx = order[jnp.clip(bpos, 0, bcap - 1)]

    # Exact key equality on expanded pairs (kills hash collisions).
    key_eq = jnp.ones((out_capacity,), dtype=bool)
    for pc, bc in zip(pcols, bcols):
        pv = group_values(pc)[pidx_c]
        bv = group_values(bc)[bidx]
        key_eq = key_eq & values_equal(pv, bv)
    match = pair_valid & real_candidate & key_eq

    if join_type == "inner":
        keep = match
        build_valid = match
    else:  # left: non-candidate expansion rows become null-extended rows
        keep = pair_valid
        build_valid = match

    out_cols = [c.gather(pidx_c, keep) for c in probe.columns]
    out_cols += [c.gather(bidx, build_valid) for c in build.columns]

    # Compact survivors to the front.
    cap = out_capacity
    order_key = jnp.where(keep, 0, cap) + jnp.arange(cap, dtype=jnp.int64)
    perm = jnp.argsort(order_key)
    n = jnp.sum(keep).astype(jnp.int32)
    valid = jnp.arange(cap, dtype=jnp.int64) < n
    out_cols = tuple(c.gather(perm, valid & jnp.ones_like(valid))
                     for c in out_cols)
    return Page(out_cols, n, ()), total


_UNROLLED_BUCKET_SCAN = 4  # unrolled fast path for typical window widths


def _window_any_match(pcols, bcols, order, lo, counts):
    """For each probe row: any true key match within its hash window.

    The first few slots are unrolled (equal-hash windows are almost always
    a handful of duplicate keys); the remainder — wide duplicate runs or a
    collision pileup — is scanned exactly by a fori_loop whose trip count
    is the *traced* max window width, so arbitrarily wide windows are
    correct, not just "vanishingly unlikely to be wrong"."""
    import jax

    pcap = pcols[0].capacity
    bcap = bcols[0].capacity
    pvals = [group_values(pc) for pc in pcols]
    pnulls = [pc.nulls for pc in pcols]
    # Gather build keys into hash-sorted order once; slot k of probe row i
    # is then sorted position lo[i]+k.
    bvals = [group_values(bc)[order] for bc in bcols]
    bnulls = [bc.nulls[order] for bc in bcols]

    def slot_match(k, matched):
        in_win = k < counts
        bpos = jnp.clip(lo + k, 0, bcap - 1).astype(jnp.int32)
        eq = in_win
        for pv, pn, bv, bn in zip(pvals, pnulls, bvals, bnulls):
            eq = eq & values_equal(pv, bv[bpos]) & ~pn & ~bn[bpos]
        return matched | eq

    matched = jnp.zeros((pcap,), dtype=bool)
    for k in range(_UNROLLED_BUCKET_SCAN):
        matched = slot_match(k, matched)

    # Early exit: a row needs further slots only while it is unmatched and
    # its window extends past k — so a million duplicates of one build key
    # stop after their probe rows match at slot 0 instead of serializing
    # the scan for the whole page.
    def cond(state):
        k, matched = state
        return jnp.any(~matched & (counts > k))

    def body(state):
        k, matched = state
        return k + 1, slot_match(k, matched)

    _, matched = jax.lax.while_loop(
        cond, body, (jnp.int64(_UNROLLED_BUCKET_SCAN), matched))
    return matched


def _bool_type():
    from presto_tpu.types import BOOLEAN
    return BOOLEAN
