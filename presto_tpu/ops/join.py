"""Joins — sorted-build, searchsorted-probe, vectorized pair expansion.

Reference roles: HashBuilderOperator/LookupJoinOperator
(presto-main-base/.../operator/HashBuilderOperator.java:55,
LookupJoinOperator.java:52 over PagesHash/JoinProbe), HashSemiJoinOperator,
NestedLoopJoinOperator. TPU-first redesign: no pointer-chasing hash table —
the build side is sorted by a 64-bit key hash (one argsort), probes binary-
search the sorted hashes (jnp.searchsorted is vectorized), and the variable
match fan-out is materialized by a prefix-sum pair expansion into a page of
*static* capacity. Hash-equal-but-key-unequal pairs (collisions, multi-key)
are masked by an exact key comparison on the expanded pairs.

Capacity contract: like aggregation, `out_capacity` bounds the join output;
`total_pairs` (traced) lets the executor detect overflow and retry at a
larger bucket.

NULL join keys never match (SQL semantics), enforced by tagging null-key
rows with disjoint sentinel hashes on each side.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.expr.compile import align_string_columns
from presto_tpu.ops.keys import group_values, hash_columns


def _aligned_keys(probe: Page, build: Page, probe_fields, build_fields):
    """Pull key columns, aligning string dictionaries across sides."""
    pcols, bcols = [], []
    for pf, bf in zip(probe_fields, build_fields):
        pc, bc = probe.columns[pf], build.columns[bf]
        if pc.type.is_string and bc.type.is_string:
            pc, bc = align_string_columns(pc, bc)
        pcols.append(pc)
        bcols.append(bc)
    return pcols, bcols


def hash_join(probe: Page, build: Page,
              probe_fields: Sequence[int], build_fields: Sequence[int],
              out_capacity: int, join_type: str = "inner",
              ) -> Tuple[Page, jnp.ndarray]:
    """Join probe x build. Output columns = probe columns ++ build columns
    (for semi/anti: probe columns only). Returns (page, total_pairs) where
    total_pairs > out_capacity indicates overflow (host retries bigger).

    join_type: inner | left | semi | anti. ("left" = probe-outer, matching
    the planner's probe/build orientation, cf. JoinNode probe=left child.)
    """
    pcap, bcap = probe.capacity, build.capacity
    if probe_fields:
        pcols, bcols = _aligned_keys(probe, build, probe_fields,
                                     build_fields)
        ph = hash_columns(pcols)
        bh = hash_columns(bcols)
    else:
        # cross join: constant key — every live row pairs with every live row
        pcols, bcols = [], []
        ph = jnp.zeros((pcap,), dtype=jnp.int64)
        bh = jnp.zeros((bcap,), dtype=jnp.int64)

    p_null = jnp.zeros((pcap,), dtype=bool)
    for c in pcols:
        p_null = p_null | c.nulls
    b_null = jnp.zeros((bcap,), dtype=bool)
    for c in bcols:
        b_null = b_null | c.nulls

    # Disjoint sentinels so null/padding keys can never pair up.
    p_live = probe.row_valid() & ~p_null
    b_live = build.row_valid() & ~b_null
    ph = jnp.where(p_live, ph, jnp.int64(-1))
    bh = jnp.where(b_live, bh, jnp.int64(-2))

    order = jnp.argsort(bh, stable=True)
    bh_sorted = bh[order]

    lo = jnp.searchsorted(bh_sorted, ph, side="left")
    hi = jnp.searchsorted(bh_sorted, ph, side="right")
    counts = jnp.where(p_live, hi - lo, 0).astype(jnp.int64)

    if join_type in ("semi", "anti", "anti_exists"):
        # Need >=1 *true* match; verify keys over the candidate window via a
        # bounded scan on the max bucket width (collision windows are tiny).
        matched = _window_any_match(pcols, bcols, order, lo, counts)
        if join_type == "semi":
            flag = matched
        elif join_type == "anti_exists":
            # NOT EXISTS: null keys simply never match; non-matching rows
            # survive (no three-valued NOT IN poisoning).
            flag = ~matched
        else:
            # SQL NOT IN: if the build side contains ANY null key, every
            # non-match is UNKNOWN -> anti join emits nothing; a null probe
            # key is likewise never anti-matched.
            b_has_null = jnp.any(b_null & build.row_valid())
            flag = ~matched & ~p_null & ~b_has_null
        col = Column(flag, jnp.zeros((pcap,), dtype=bool), _bool_type(), None)
        out = Page(probe.columns + (col,), probe.num_rows, ())
        return out, jnp.sum(counts)

    if join_type == "left":
        counts = jnp.where(p_live | (probe.row_valid() & ~p_live),
                           jnp.maximum(counts, jnp.where(
                               probe.row_valid(), 1, 0)), counts)
        # rows with no candidates still emit one (null-extended) pair
    cum = jnp.cumsum(counts)
    total = cum[-1] if pcap > 0 else jnp.int64(0)

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    pair_valid = j < total
    pidx = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    pidx_c = jnp.clip(pidx, 0, pcap - 1)
    start = cum[pidx_c] - counts[pidx_c]
    offset = j - start
    bpos = (lo[pidx_c] + offset).astype(jnp.int32)
    real_candidate = (offset < (hi[pidx_c] - lo[pidx_c])) & p_live[pidx_c]
    bidx = order[jnp.clip(bpos, 0, bcap - 1)]

    # Exact key equality on expanded pairs (kills hash collisions).
    key_eq = jnp.ones((out_capacity,), dtype=bool)
    for pc, bc in zip(pcols, bcols):
        pv = group_values(pc)[pidx_c]
        bv = group_values(bc)[bidx]
        key_eq = key_eq & (pv == bv)
    match = pair_valid & real_candidate & key_eq

    if join_type == "inner":
        keep = match
        build_valid = match
    else:  # left: non-candidate expansion rows become null-extended rows
        keep = pair_valid
        build_valid = match

    out_cols = [c.gather(pidx_c, keep) for c in probe.columns]
    out_cols += [c.gather(bidx, build_valid) for c in build.columns]

    # Compact survivors to the front.
    cap = out_capacity
    order_key = jnp.where(keep, 0, cap) + jnp.arange(cap, dtype=jnp.int64)
    perm = jnp.argsort(order_key)
    n = jnp.sum(keep).astype(jnp.int32)
    valid = jnp.arange(cap, dtype=jnp.int64) < n
    out_cols = tuple(c.gather(perm, valid & jnp.ones_like(valid))
                     for c in out_cols)
    return Page(out_cols, n, ()), total


_UNROLLED_BUCKET_SCAN = 4  # unrolled fast path for typical window widths


def _window_any_match(pcols, bcols, order, lo, counts):
    """For each probe row: any true key match within its hash window.

    The first few slots are unrolled (equal-hash windows are almost always
    a handful of duplicate keys); the remainder — wide duplicate runs or a
    collision pileup — is scanned exactly by a fori_loop whose trip count
    is the *traced* max window width, so arbitrarily wide windows are
    correct, not just "vanishingly unlikely to be wrong"."""
    import jax

    pcap = pcols[0].capacity
    bcap = bcols[0].capacity
    pvals = [group_values(pc) for pc in pcols]
    pnulls = [pc.nulls for pc in pcols]
    # Gather build keys into hash-sorted order once; slot k of probe row i
    # is then sorted position lo[i]+k.
    bvals = [group_values(bc)[order] for bc in bcols]
    bnulls = [bc.nulls[order] for bc in bcols]

    def slot_match(k, matched):
        in_win = k < counts
        bpos = jnp.clip(lo + k, 0, bcap - 1).astype(jnp.int32)
        eq = in_win
        for pv, pn, bv, bn in zip(pvals, pnulls, bvals, bnulls):
            eq = eq & (pv == bv[bpos]) & ~pn & ~bn[bpos]
        return matched | eq

    matched = jnp.zeros((pcap,), dtype=bool)
    for k in range(_UNROLLED_BUCKET_SCAN):
        matched = slot_match(k, matched)

    # Early exit: a row needs further slots only while it is unmatched and
    # its window extends past k — so a million duplicates of one build key
    # stop after their probe rows match at slot 0 instead of serializing
    # the scan for the whole page.
    def cond(state):
        k, matched = state
        return jnp.any(~matched & (counts > k))

    def body(state):
        k, matched = state
        return k + 1, slot_match(k, matched)

    _, matched = jax.lax.while_loop(
        cond, body, (jnp.int64(_UNROLLED_BUCKET_SCAN), matched))
    return matched


def _bool_type():
    from presto_tpu.types import BOOLEAN
    return BOOLEAN
