"""ORDER BY / TopN / Limit.

Reference roles: OrderByOperator (PagesIndex sort), TopNOperator
(presto-main-base/.../operator/TopNOperator.java:32), LimitOperator.
TPU-first: ONE multi-key multi-operand lax.sort — sort keys are
lexicographic key operands (padding rank, then per-key null rank + value),
and every page column rides along as a payload operand. No argsort+gather:
random index gathers serialize on TPU (~25 ns/row measured on v5e) while
the sorting network moves payload lanes together.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops.keys import SortKey, _orderable_lanes


def _sort_key_operands(page: Page, keys: Sequence[SortKey]) -> List:
    """Lexicographic key operands for lax.sort: padding rows last, then
    per-SortKey (null rank, order-transformed value lanes — Decimal128
    sums contribute two exact limb lanes, ops/keys._orderable_lanes)."""
    cap = page.capacity
    ops: List = [
        (jnp.arange(cap, dtype=jnp.int32) >= page.num_rows).astype(jnp.int8)]
    for k in keys:
        col = page.columns[k.field]
        null_rank = jnp.where(col.nulls,
                              jnp.int8(0 if k.nulls_sort_first else 1),
                              jnp.int8(1 if k.nulls_sort_first else 0))
        ops.append(null_rank)
        for v in _orderable_lanes(col):
            if not k.ascending:
                v = -v.astype(jnp.int64) if not jnp.issubdtype(
                    v.dtype, jnp.floating) else -v
            ops.append(v)
    return ops


def sort_page(page: Page, keys: Sequence[SortKey]) -> Page:
    from presto_tpu.data.column import Decimal128Column, NestedColumn
    key_ops = _sort_key_operands(page, keys)
    operands = tuple(key_ops)
    for c in page.columns:
        if isinstance(c, NestedColumn):
            # nested payload rides as row-wise lanes; child buffers are
            # position-addressed and never move
            operands += (c.starts, c.lengths, c.nulls)
        elif isinstance(c, Decimal128Column):
            operands += tuple(c.row_lanes())
        else:
            operands += (c.values, c.nulls)
    out = jax.lax.sort(operands, num_keys=len(key_ops), is_stable=True)
    pos = len(key_ops)
    cols = []
    for c in page.columns:
        if isinstance(c, NestedColumn):
            cols.append(NestedColumn(out[pos], out[pos + 1], out[pos + 2],
                                     c.children, c.type))
            pos += 3
        elif isinstance(c, Decimal128Column):
            k = len(c.row_lanes())
            cols.append(c.from_lanes(list(out[pos:pos + k])))
            pos += k
        else:
            cols.append(Column(out[pos], out[pos + 1], c.type,
                               c.dictionary))
            pos += 2
    return Page(tuple(cols), page.num_rows, page.names)


def top_n(page: Page, keys: Sequence[SortKey], n: int) -> Page:
    out = sort_page(page, keys)
    return Page(out.columns, jnp.minimum(out.num_rows, n), out.names)


def limit_page(page: Page, n: int) -> Page:
    return Page(page.columns, jnp.minimum(page.num_rows, n), page.names)
