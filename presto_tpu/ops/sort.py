"""ORDER BY / TopN / Limit.

Reference roles: OrderByOperator (PagesIndex sort), TopNOperator
(presto-main-base/.../operator/TopNOperator.java:32), LimitOperator.
TPU-first: one fused multi-key argsort (ops/keys.py) + gather; TopN is the
same sort with a clamped row count (XLA's sort is already O(n log n)
vectorized; a separate heap structure would be slower on this hardware).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from presto_tpu.data.column import Page
from presto_tpu.ops.keys import SortKey, sort_perm


def sort_page(page: Page, keys: Sequence[SortKey]) -> Page:
    perm = sort_perm(page, keys)
    valid = jnp.arange(page.capacity, dtype=jnp.int32) < page.num_rows
    cols = tuple(c.gather(perm, valid) for c in page.columns)
    return Page(cols, page.num_rows, page.names)


def top_n(page: Page, keys: Sequence[SortKey], n: int) -> Page:
    out = sort_page(page, keys)
    return Page(out.columns, jnp.minimum(out.num_rows, n), out.names)


def limit_page(page: Page, n: int) -> Page:
    return Page(page.columns, jnp.minimum(page.num_rows, n), page.names)
