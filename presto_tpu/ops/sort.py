"""ORDER BY / TopN / Limit.

Reference roles: OrderByOperator (PagesIndex sort), TopNOperator
(presto-main-base/.../operator/TopNOperator.java:32), LimitOperator.
TPU-first: ONE multi-key multi-operand lax.sort — sort keys are
lexicographic key operands (padding rank, then per-key null rank + value),
and every page column rides along as a payload operand. No argsort+gather:
random index gathers serialize on TPU (~25 ns/row measured on v5e) while
the sorting network moves payload lanes together.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops.keys import SortKey, sort_perm


def sort_page(page: Page, keys: Sequence[SortKey]) -> Page:
    """Sort via ops/keys.sort_perm (composed 2-operand stable argsorts
    over the key lanes — THE shared lexicographic-permutation
    implementation) + one gather per column; never a wide variadic
    lax.sort (compile cost explodes with operand count on this
    stack)."""
    from presto_tpu.data.column import gather_page
    return gather_page(page, sort_perm(page, keys))


def top_n(page: Page, keys: Sequence[SortKey], n: int) -> Page:
    out = sort_page(page, keys)
    return Page(out.columns, jnp.minimum(out.num_rows, n), out.names)


def limit_page(page: Page, n: int) -> Page:
    return Page(page.columns, jnp.minimum(page.num_rows, n), page.names)
