"""ORDER BY / TopN / Limit.

Reference roles: OrderByOperator (PagesIndex sort), TopNOperator
(presto-main-base/.../operator/TopNOperator.java:32), LimitOperator.
TPU-first: ONE multi-key multi-operand lax.sort — sort keys are
lexicographic key operands (padding rank, then per-key null rank + value),
and every page column rides along as a payload operand. No argsort+gather:
random index gathers serialize on TPU (~25 ns/row measured on v5e) while
the sorting network moves payload lanes together.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops.keys import SortKey, _orderable_lanes


def _sort_key_operands(page: Page, keys: Sequence[SortKey]) -> List:
    """Lexicographic key operands for lax.sort: padding rows last, then
    per-SortKey (null rank, order-transformed value lanes — Decimal128
    sums contribute two exact limb lanes, ops/keys._orderable_lanes)."""
    cap = page.capacity
    ops: List = [
        (jnp.arange(cap, dtype=jnp.int32) >= page.num_rows).astype(jnp.int8)]
    for k in keys:
        col = page.columns[k.field]
        null_rank = jnp.where(col.nulls,
                              jnp.int8(0 if k.nulls_sort_first else 1),
                              jnp.int8(1 if k.nulls_sort_first else 0))
        ops.append(null_rank)
        for v in _orderable_lanes(col):
            if not k.ascending:
                v = -v.astype(jnp.int64) if not jnp.issubdtype(
                    v.dtype, jnp.floating) else -v
            ops.append(v)
    return ops


def sort_page(page: Page, keys: Sequence[SortKey]) -> Page:
    """Sort via ops/keys.lex_perm (composed 2-operand argsorts over the
    key lanes) + one gather per column — never a wide variadic lax.sort
    (compile cost explodes with operand count on this stack)."""
    from presto_tpu.data.column import gather_page
    from presto_tpu.ops.keys import lex_perm
    perm = lex_perm(_sort_key_operands(page, keys))
    return gather_page(page, perm)


def top_n(page: Page, keys: Sequence[SortKey], n: int) -> Page:
    out = sort_page(page, keys)
    return Page(out.columns, jnp.minimum(out.num_rows, n), out.names)


def limit_page(page: Page, n: int) -> Page:
    return Page(page.columns, jnp.minimum(page.num_rows, n), page.names)
