"""UNNEST — flatten ARRAY/MAP columns into rows, jit-compiled.

Reference semantics: operator/unnest/UnnestOperator.java with
ArrayUnnester/MapUnnester — row i expands to max(cardinality) output
rows across the unnest channels; shorter channels null-pad; replicate
channels repeat; WITH ORDINALITY appends the 1-based position.

TPU shape: everything is static-capacity. Output row j finds its parent
row with one searchsorted over the cumulative row lengths, then gathers
replicate lanes at the parent and element lanes at start+within — no
data-dependent control flow, so XLA fuses the whole flatten into a few
vector ops. Overflow rides the executor's watch/retry counters: the
kernel returns the true total so the caller re-lowers at a bigger
bucket when out_cap truncates.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from presto_tpu.data.column import Column, NestedColumn, Page
from presto_tpu.types import BIGINT


def unnest_page(page: Page, replicate_fields: Tuple[int, ...],
                unnest_fields: Tuple[int, ...], out_cap: int,
                with_ordinality: bool,
                out_names: Tuple[str, ...]) -> Tuple[Page, jnp.ndarray]:
    """Returns (output page, true total rows needed)."""
    cap = page.capacity
    valid = page.row_valid()
    nested = [page.columns[f] for f in unnest_fields]
    for nc in nested:
        if not isinstance(nc, NestedColumn):
            raise TypeError("UNNEST over a non-nested column")
    # per-row expansion count = max over channels (0 for NULL rows)
    rowlen = jnp.zeros(cap, jnp.int32)
    for nc in nested:
        ln = jnp.where(nc.nulls | ~valid, 0, nc.lengths)
        rowlen = jnp.maximum(rowlen, ln)
    cum = jnp.cumsum(rowlen)                       # [cap]
    total = (cum[-1] if cap else jnp.asarray(0, jnp.int32))
    j = jnp.arange(out_cap, dtype=jnp.int32)
    parent = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    parent_c = jnp.clip(parent, 0, max(cap - 1, 0))
    prev = jnp.where(parent_c > 0,
                     jnp.take(cum, parent_c - 1, mode="clip"), 0)
    within = j - prev
    out_valid = j < total

    cols = []
    for f in replicate_fields:
        cols.append(page.columns[f].gather(parent_c, valid=out_valid))
    for nc in nested:
        ln = jnp.take(nc.lengths, parent_c, mode="clip")
        null_row = jnp.take(nc.nulls, parent_c, mode="clip")
        entry_ok = out_valid & (within < ln) & ~null_row
        eidx = jnp.take(nc.starts, parent_c, mode="clip") + within
        for child in nc.children:
            cols.append(child.gather(eidx, valid=entry_ok))
    if with_ordinality:
        ordv = jnp.where(out_valid, (within + 1).astype(jnp.int64),
                         jnp.asarray(BIGINT.null_sentinel(), jnp.int64))
        cols.append(Column(ordv, ~out_valid, BIGINT, None))
    out = Page(tuple(cols), total.astype(jnp.int32), tuple(out_names))
    return out, total.astype(jnp.int64)
