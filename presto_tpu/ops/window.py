"""Window functions — permutation sort + blocked scans + gathers.

Reference role: WindowOperator (presto-main-base/.../operator/
WindowOperator.java:68 over PagesIndex sort + per-frame evaluation;
frames/offsets: presto-main-base/.../operator/window/*.java). TPU-first
redesign: a sort PERMUTATION over (partition keys, order keys) via
composed 2-operand argsorts (ops/keys.lex_perm — wide variadic sorts
explode compile cost on this stack); partition/peer boundaries from
adjacent compares; ranks, running aggregates and frames are blocked
scans (ops/scan.py) plus index-arithmetic gathers; the inverse
permutation restores original row order.

Supported: row_number, rank, dense_rank, ntile, lag/lead (offset +
default), first_value/last_value/nth_value, and sum/count/avg/min/max
with frames:
  - default  : RANGE UNBOUNDED PRECEDING..CURRENT ROW (peer-aware) with
               ORDER BY, whole partition without (SQL default)
  - ROWS     : any BETWEEN of UNBOUNDED/N PRECEDING/CURRENT/N FOLLOWING
               (min/max over a both-bounded frame uses a sparse-table
               range-extreme: log2(n) doubling levels, 2 gathers/row)
  - RANGE    : UNBOUNDED/CURRENT bounds (value-offset RANGE rejected)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops import scan as pscan
from presto_tpu.ops.keys import SortKey, _orderable_lanes, \
    group_values, values_equal
from presto_tpu.types import Type


@dataclasses.dataclass(frozen=True)
class Frame:
    """Window frame (reference: spi/plan/WindowNode.Frame). Bound types:
    unbounded_preceding | preceding | current | following |
    unbounded_following; N for the bounded types sits in start_n/end_n
    (constant — SQL frame offsets are literals in every TPC query)."""
    mode: str = "range"                   # "range" | "rows"
    start_type: str = "unbounded_preceding"
    start_n: Optional[int] = None
    end_type: str = "current"
    end_n: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """One window function: kind in {row_number, rank, dense_rank, ntile,
    lag, lead, first_value, last_value, nth_value, sum, count,
    count_star, avg, min, max}. `field` is the argument column; `param`
    is the lag/lead offset, ntile bucket count or nth_value position;
    `default` the lag/lead default literal (python value)."""
    kind: str
    field: Optional[int]
    output_type: Type
    param: Optional[int] = None
    default: object = None
    frame: Optional[Frame] = None


_OFFSET_KINDS = {"lag", "lead"}
_VALUE_KINDS = {"first_value", "last_value", "nth_value"}
_AGG_KINDS = {"sum", "count", "count_star", "avg", "min", "max"}

_fill_backward = pscan.fill_backward


def _frame_bounds(frame: Optional[Frame], has_order: bool, idx,
                  part_start_idx, part_end_idx, peer_start_idx,
                  peer_end_idx):
    """Per-row inclusive [lo, hi] frame bounds in sorted coordinates.
    Returns (lo, hi, start_unbounded, end_unbounded) — the unbounded
    flags let min/max pick a scan direction."""
    if frame is None:
        frame = Frame()                      # SQL default frame
        if not has_order:
            frame = Frame(end_type="unbounded_following")
    if frame.mode == "rows":
        st, en = frame.start_type, frame.end_type
        if st == "unbounded_preceding":
            lo = part_start_idx
        elif st == "preceding":
            lo = idx - int(frame.start_n)
        elif st == "current":
            lo = idx
        elif st == "following":
            lo = idx + int(frame.start_n)
        else:
            raise NotImplementedError(f"frame start {st}")
        if en == "unbounded_following":
            hi = part_end_idx
        elif en == "following":
            hi = idx + int(frame.end_n)
        elif en == "current":
            hi = idx
        elif en == "preceding":
            hi = idx - int(frame.end_n)
        else:
            raise NotImplementedError(f"frame end {en}")
        lo = jnp.maximum(lo, part_start_idx)
        hi = jnp.minimum(hi, part_end_idx)
        return (lo, hi, st == "unbounded_preceding",
                en == "unbounded_following")
    # RANGE: UNBOUNDED/CURRENT bounds only (peer-aware)
    st, en = frame.start_type, frame.end_type
    if st == "unbounded_preceding":
        lo = part_start_idx
    elif st == "current":
        lo = peer_start_idx
    else:
        raise NotImplementedError(f"RANGE frame start {st}")
    if en == "unbounded_following":
        hi = part_end_idx
    elif en == "current":
        hi = peer_end_idx
    else:
        raise NotImplementedError(f"RANGE frame end {en}")
    return lo, hi, st == "unbounded_preceding", en == "unbounded_following"


def window_page(page: Page, partition_fields: Sequence[int],
                order_keys: Sequence[SortKey],
                specs: Sequence[WindowSpec]) -> Page:
    """Append one column per spec to `page` (original row order kept)."""
    cap = page.capacity
    valid = page.row_valid()
    idx = jnp.arange(cap, dtype=jnp.int32)

    # ---- sort lanes: (valid, partition keys, order keys)
    key_ops = [(~valid).astype(jnp.int8)]
    for f in partition_fields:
        c = page.columns[f]
        key_ops.append(c.nulls.astype(jnp.int8))
        key_ops.append(group_values(c))
    null_rank_of_null = []   # per order key: the rank value NULL rows get
    order_lane_counts = []   # per order key: value lanes (Decimal128 = 2)
    order_ops_start = 1 + 2 * len(partition_fields)
    for k in order_keys:
        c = page.columns[k.field]
        nr = jnp.int8(0 if k.nulls_sort_first else 1)
        null_rank_of_null.append(int(0 if k.nulls_sort_first else 1))
        key_ops.append(jnp.where(c.nulls, nr, jnp.int8(1) - nr))
        lanes = _orderable_lanes(c)
        order_lane_counts.append(len(lanes))
        for v in lanes:
            if not k.ascending:
                v = -v.astype(jnp.int64) if not jnp.issubdtype(
                    v.dtype, jnp.floating) else -v
            key_ops.append(v)

    arg_fields = sorted({s.field for s in specs if s.field is not None})
    # permutation over the key lanes only (ops/keys.lex_perm); arg lanes
    # move by gather — wide variadic sorts explode compile cost
    from presto_tpu.ops.keys import lex_perm
    perm = lex_perm(key_ops)
    s = [lane[perm] for lane in key_ops]
    s_idx = idx[perm]
    s_valid = valid[perm]
    s_args = {f: (jnp.take(page.columns[f].values, perm, mode="clip"),
                  jnp.take(page.columns[f].nulls, perm, mode="clip"))
              for f in arg_fields}

    # ---- partition / peer boundaries from adjacent key compares.
    # The rank operand encodes nulls as `null_rank` (0 when nulls sort
    # first, else 1) — decode before comparing.
    def changed(ops_start: int, lane_counts, null_ranks) -> jnp.ndarray:
        ch = jnp.zeros((cap,), bool).at[0].set(True)
        pos = ops_start
        for nlanes, nrank in zip(lane_counts, null_ranks):
            n = s[pos] == nrank
            same_v = jnp.ones((cap,), bool)
            for j in range(nlanes):
                v = s[pos + 1 + j]
                same_v = same_v & values_equal(v, jnp.roll(v, 1))
            same = (same_v & ~n & ~jnp.roll(n, 1)) | (n & jnp.roll(n, 1))
            ch = ch | ~same
            pos += 1 + nlanes
        return ch.at[0].set(True)

    part_start = changed(1, [1] * len(partition_fields),
                         [1] * len(partition_fields)) \
        if partition_fields else jnp.zeros((cap,), bool).at[0].set(True)
    # a validity change is always a partition boundary: invalid rows sort
    # last (the most-significant lane) and must never sit inside a valid
    # partition's frame (first/last_value gather at frame edges).
    part_start = part_start | (~s_valid & jnp.roll(s_valid, 1))
    peer_start = part_start | (
        changed(order_ops_start, order_lane_counts, null_rank_of_null)
        if order_keys else jnp.zeros((cap,), bool))
    has_order = bool(order_keys)

    part_start_idx = pscan.fill_forward(
        jnp.where(part_start, idx, 0), part_start)
    peer_start_idx = pscan.fill_forward(
        jnp.where(peer_start, idx, 0), peer_start)
    peer_end = jnp.roll(peer_start, -1).at[-1].set(True)
    part_end = jnp.roll(part_start, -1).at[-1].set(True)
    part_end_idx = _fill_backward(jnp.where(part_end, idx, 0), part_end)
    peer_end_idx = _fill_backward(jnp.where(peer_end, idx, 0), peer_end)

    def clipi(a):
        return jnp.clip(a, 0, cap - 1)

    out_cols = []
    for spec in specs:
        kind = spec.kind
        t = spec.output_type
        if kind == "row_number":
            w = (idx - part_start_idx + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind == "rank":
            w = (peer_start_idx - part_start_idx + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind == "dense_rank":
            cs_peer = pscan.cumsum(peer_start.astype(jnp.int32))
            at_part = pscan.fill_forward(
                jnp.where(part_start, cs_peer, 0), part_start)
            w = (cs_peer - at_part + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind == "ntile":
            # SQL remainder rule: the first (psize mod buckets) buckets
            # get one extra row (NOT an even spread)
            buckets = jnp.int64(int(spec.param))
            psize = (part_end_idx - part_start_idx + 1).astype(jnp.int64)
            rn = (idx - part_start_idx).astype(jnp.int64)
            base = psize // buckets
            rem = psize - base * buckets
            big = rem * (base + 1)          # rows in the larger buckets
            w = jnp.where(
                rn < big,
                rn // jnp.maximum(base + 1, 1) + 1,
                rem + (rn - big) // jnp.maximum(base, 1) + 1)
            wn = jnp.zeros((cap,), bool)
        elif kind in _OFFSET_KINDS:
            vals, nulls = s_args[spec.field]
            k = int(spec.param if spec.param is not None else 1)
            j = idx - k if kind == "lag" else idx + k
            inb = (j >= part_start_idx) & (j <= part_end_idx)
            jc = clipi(j)
            w = jnp.take(vals, jc, mode="clip")
            wn = jnp.take(nulls, jc, mode="clip") | ~inb
            if spec.default is not None:
                dv = jnp.asarray(spec.default, dtype=vals.dtype)
                w = jnp.where(inb, w, dv)
                wn = jnp.where(inb, wn, False)
        elif kind in _VALUE_KINDS:
            vals, nulls = s_args[spec.field]
            lo, hi, _su, _eu = _frame_bounds(
                spec.frame, has_order, idx, part_start_idx, part_end_idx,
                peer_start_idx, peer_end_idx)
            if kind == "first_value":
                pos = lo
            elif kind == "last_value":
                pos = hi
            else:
                pos = lo + int(spec.param) - 1
            empty = (lo > hi) | (pos < lo) | (pos > hi)
            pc = clipi(pos)
            w = jnp.take(vals, pc, mode="clip")
            wn = jnp.take(nulls, pc, mode="clip") | empty
        elif kind in _AGG_KINDS:
            if spec.field is not None:
                vals, nulls = s_args[spec.field]
                live = s_valid & ~nulls
            else:
                vals = jnp.ones((cap,), jnp.int64)
                live = s_valid
            lo, hi, start_unb, end_unb = _frame_bounds(
                spec.frame, has_order, idx, part_start_idx, part_end_idx,
                peer_start_idx, peer_end_idx)
            empty = lo > hi
            loc, hic = clipi(lo), clipi(hi)
            # live count over the frame: prefix-count + two gathers
            cnt = pscan.cumsum(live.astype(jnp.int64))
            c_hi = jnp.take(cnt, hic, mode="clip")
            c_lom1 = jnp.where(lo > 0,
                               jnp.take(cnt, clipi(lo - 1), mode="clip"),
                               0)
            n = jnp.where(empty, 0, c_hi - c_lom1)
            if kind in ("sum", "count", "count_star", "avg"):
                acc = jnp.float64 if (t.is_floating or kind == "avg") \
                    else jnp.int64
                contrib = jnp.where(live, vals, 0).astype(acc)
                cs = pscan.cumsum(contrib)
                s_hi = jnp.take(cs, hic, mode="clip")
                s_lom1 = jnp.where(
                    lo > 0, jnp.take(cs, clipi(lo - 1), mode="clip"),
                    jnp.zeros((), acc))
                total = jnp.where(empty, jnp.zeros((), acc),
                                  s_hi - s_lom1)
                if kind in ("count", "count_star"):
                    w, wn = n, jnp.zeros((cap,), bool)
                elif kind == "sum":
                    w, wn = total, n == 0
                else:  # avg — DECIMAL args are unscaled ints: descale
                    w = total / jnp.maximum(n, 1)
                    if spec.field is not None:
                        arg_t = page.columns[spec.field].type
                        if arg_t.is_decimal:
                            w = w / (10 ** arg_t.scale)
                    wn = n == 0
            else:  # min / max over a frame with one unbounded side
                v = vals
                if jnp.issubdtype(v.dtype, jnp.floating):
                    ident = jnp.inf if kind == "min" else -jnp.inf
                else:
                    if v.dtype == jnp.bool_:
                        v = v.astype(jnp.int32)
                    info = jnp.iinfo(v.dtype)
                    ident = info.max if kind == "min" else info.min
                masked = jnp.where(live, v, ident)
                binop = jnp.minimum if kind == "min" else jnp.maximum
                if start_unb:
                    # running extreme from partition start, read at hi
                    run = pscan.seg_scan(masked, part_start, binop, ident)
                    w = jnp.take(run, hic, mode="clip")
                elif end_unb:
                    # reversed running extreme, read at lo
                    rrun = pscan.seg_scan(
                        jnp.flip(masked), jnp.flip(part_end), binop,
                        ident)
                    run = jnp.flip(rrun)
                    w = jnp.take(run, loc, mode="clip")
                else:
                    # both-bounded frame: sparse-table range extreme
                    # (the RMQ construction) — doubling levels built
                    # once, every row's [lo, hi] answered with two
                    # gathers at its level. Queries never cross
                    # partitions (frame bounds are intra-partition and
                    # each lookup spans <= frame length). The frame's
                    # STATIC offsets bound the longest query, so only
                    # log2(max frame length) levels exist — not
                    # log2(cap).
                    f = spec.frame
                    max_ln = int(cap)
                    if f is not None and f.start_n is not None \
                            and f.end_n is not None:
                        span = 0
                        span += (int(f.start_n)
                                 if f.start_type == "preceding"
                                 else -int(f.start_n))
                        span += (int(f.end_n)
                                 if f.end_type == "following"
                                 else -int(f.end_n))
                        max_ln = max(span + 1, 1)
                    elif f is not None and (
                            f.start_type == "current"
                            or f.end_type == "current"):
                        n_side = f.end_n if f.start_type == "current" \
                            else f.start_n
                        if n_side is not None:
                            max_ln = int(n_side) + 1
                    max_ln = min(max_ln, int(cap))
                    L = max(int(max_ln - 1).bit_length(), 1)
                    levels = [masked]
                    for j in range(1, L + 1):
                        prev = levels[-1]
                        off = 1 << (j - 1)
                        shifted = jnp.concatenate(
                            [prev[off:],
                             jnp.full((off,), ident, prev.dtype)])
                        levels.append(binop(prev, shifted))
                    table = jnp.stack(levels)          # [L+1, cap]
                    ln = jnp.maximum(hi - lo + 1, 1)
                    k = jnp.zeros_like(ln)
                    for j in range(1, L + 1):
                        k = k + (ln >= (1 << j)).astype(ln.dtype)
                    pow_k = jnp.left_shift(
                        jnp.asarray(1, ln.dtype), k)
                    left = table[k, loc]
                    right = table[k, clipi(hi - pow_k + 1)]
                    w = binop(left, right)
                wn = n == 0
                w = jnp.where(wn, ident, w)
        else:
            raise NotImplementedError(f"window function {kind}")
        out_cols.append((w, wn | ~s_valid))

    # ---- restore original row order via the inverse permutation (one
    # argsort), gathering only the window outputs
    inv = jnp.argsort(s_idx)
    cols = list(page.columns)
    for i, spec in enumerate(specs):
        w = out_cols[i][0][inv]
        wn = out_cols[i][1][inv]
        t = spec.output_type
        # value-kind outputs over strings are dictionary codes (code
        # order == lexicographic); the output keeps the dictionary.
        dictionary = (page.columns[spec.field].dictionary
                      if spec.field is not None and t.is_string else None)
        sent = jnp.asarray(t.null_sentinel(), dtype=t.dtype)
        vals = jnp.where(wn, sent, w.astype(t.dtype))
        cols.append(Column(vals, wn, t, dictionary))
    return Page(tuple(cols), page.num_rows,
                page.names + tuple(f"_w{i}" for i in range(len(specs))))
