"""Window functions — sort-carry + blocked scans, no gathers.

Reference role: WindowOperator (presto-main-base/.../operator/
WindowOperator.java:68 over PagesIndex sort + per-frame evaluation).
TPU-first redesign: ONE multi-operand lax.sort by (partition keys, order
keys) carrying every column plus the original row index; partition/peer
boundaries come from adjacent compares; ranks and running aggregates are
blocked fill-forward/backward scans (ops/scan.py); a second sort restores
the original row order carrying only the computed window columns.

Supported: row_number, rank, dense_rank, and sum/count/avg/min/max over
the partition — cumulative (peer-aware RANGE UNBOUNDED PRECEDING ..
CURRENT ROW, the SQL default when ORDER BY is present) or whole-partition
(no ORDER BY).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.ops import scan as pscan
from presto_tpu.ops.keys import SortKey, _orderable_lanes, \
    group_values, values_equal
from presto_tpu.types import BIGINT, DOUBLE, Type


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """One window function: kind in {row_number, rank, dense_rank, sum,
    count, count_star, avg, min, max}. `field` is the argument column."""
    kind: str
    field: Optional[int]
    output_type: Type


_fill_backward = pscan.fill_backward


def window_page(page: Page, partition_fields: Sequence[int],
                order_keys: Sequence[SortKey],
                specs: Sequence[WindowSpec]) -> Page:
    """Append one column per spec to `page` (original row order kept)."""
    cap = page.capacity
    valid = page.row_valid()
    idx = jnp.arange(cap, dtype=jnp.int32)

    # ---- sort by (valid, partition keys, order keys), carrying inputs
    key_ops = [(~valid).astype(jnp.int8)]
    n_part_ops = 0
    for f in partition_fields:
        c = page.columns[f]
        key_ops.append(c.nulls.astype(jnp.int8))
        key_ops.append(group_values(c))
        n_part_ops += 2
    n_order_ops = 0
    null_rank_of_null = []   # per order key: the rank value NULL rows get
    order_lane_counts = []   # per order key: value lanes (Decimal128 = 2)
    for k in order_keys:
        c = page.columns[k.field]
        nr = jnp.int8(0 if k.nulls_sort_first else 1)
        null_rank_of_null.append(int(0 if k.nulls_sort_first else 1))
        key_ops.append(jnp.where(c.nulls, nr, jnp.int8(1) - nr))
        lanes = _orderable_lanes(c)
        order_lane_counts.append(len(lanes))
        for v in lanes:
            if not k.ascending:
                v = -v.astype(jnp.int64) if not jnp.issubdtype(
                    v.dtype, jnp.floating) else -v
            key_ops.append(v)
        n_order_ops += 1 + len(lanes)

    arg_fields = sorted({s.field for s in specs if s.field is not None})
    operands = tuple(key_ops) + (idx, valid)
    for f in arg_fields:
        operands += (page.columns[f].values, page.columns[f].nulls)
    s = jax.lax.sort(operands, num_keys=len(key_ops), is_stable=True)
    nk = len(key_ops)
    s_idx = s[nk]
    s_valid = s[nk + 1]
    s_args = {f: (s[nk + 2 + 2 * i], s[nk + 3 + 2 * i])
              for i, f in enumerate(arg_fields)}

    # ---- partition / peer boundaries from adjacent key compares.
    # The rank operand encodes nulls as `null_rank` (0 when nulls sort
    # first, else 1) — decode before comparing.
    def changed(ops_start: int, lane_counts, null_ranks) -> jnp.ndarray:
        ch = jnp.zeros((cap,), bool).at[0].set(True)
        pos = ops_start
        for nlanes, nrank in zip(lane_counts, null_ranks):
            n = s[pos] == nrank
            same_v = jnp.ones((cap,), bool)
            for j in range(nlanes):
                v = s[pos + 1 + j]
                same_v = same_v & values_equal(v, jnp.roll(v, 1))
            same = (same_v & ~n & ~jnp.roll(n, 1)) | (n & jnp.roll(n, 1))
            ch = ch | ~same
            pos += 1 + nlanes
        return ch.at[0].set(True)

    part_start = changed(1, [1] * len(partition_fields),
                         [1] * len(partition_fields)) \
        if n_part_ops else jnp.zeros((cap,), bool).at[0].set(True)
    peer_start = part_start | (
        changed(1 + n_part_ops, order_lane_counts, null_rank_of_null)
        if n_order_ops else jnp.zeros((cap,), bool))
    has_order = bool(order_keys)

    part_start_idx = pscan.fill_forward(
        jnp.where(part_start, idx, 0), part_start)
    peer_start_idx = pscan.fill_forward(
        jnp.where(peer_start, idx, 0), peer_start)
    # last row of my peer group / partition (for running + totals)
    peer_end = jnp.roll(peer_start, -1).at[-1].set(True)
    part_end = jnp.roll(part_start, -1).at[-1].set(True)

    out_cols = []
    for spec in specs:
        kind = spec.kind
        t = spec.output_type
        if kind == "row_number":
            w = (idx - part_start_idx + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind == "rank":
            w = (peer_start_idx - part_start_idx + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind == "dense_rank":
            cs_peer = pscan.cumsum(peer_start.astype(jnp.int32))
            at_part = pscan.fill_forward(
                jnp.where(part_start, cs_peer, 0), part_start)
            w = (cs_peer - at_part + 1).astype(jnp.int64)
            wn = jnp.zeros((cap,), bool)
        elif kind in ("sum", "count", "count_star", "avg"):
            if spec.field is not None:
                vals, nulls = s_args[spec.field]
                live = s_valid & ~nulls
            else:
                vals = jnp.ones((cap,), jnp.int64)
                live = s_valid
            acc = jnp.float64 if (t.is_floating or kind == "avg") \
                else jnp.int64
            contrib = jnp.where(live, vals, 0).astype(acc)
            cs = pscan.cumsum(contrib)
            cnt = pscan.cumsum(live.astype(jnp.int64))
            before_part = pscan.fill_forward(
                jnp.where(part_start, cs - contrib, 0), part_start)
            cnt_before = pscan.fill_forward(
                jnp.where(part_start, cnt - live.astype(jnp.int64), 0),
                part_start)
            if has_order:   # cumulative to the end of my peer group
                upto = _fill_backward(jnp.where(peer_end, cs, 0), peer_end)
                n_upto = _fill_backward(jnp.where(peer_end, cnt, 0),
                                        peer_end)
            else:           # whole partition
                upto = _fill_backward(jnp.where(part_end, cs, 0), part_end)
                n_upto = _fill_backward(jnp.where(part_end, cnt, 0),
                                        part_end)
            total = upto - before_part
            n = n_upto - cnt_before
            if kind in ("count", "count_star"):
                w, wn = n, jnp.zeros((cap,), bool)
            elif kind == "sum":
                w, wn = total, n == 0
            else:  # avg — DECIMAL args are unscaled ints: descale
                w = total / jnp.maximum(n, 1)
                if spec.field is not None:
                    arg_t = page.columns[spec.field].type
                    if arg_t.is_decimal:
                        w = w / (10 ** arg_t.scale)
                wn = n == 0
        elif kind in ("min", "max"):
            if has_order:
                raise NotImplementedError(
                    f"running {kind} window (frame with ORDER BY)")
            vals, nulls = s_args[spec.field]
            live = s_valid & ~nulls
            v = vals
            if jnp.issubdtype(v.dtype, jnp.floating):
                ident = jnp.inf if kind == "min" else -jnp.inf
            else:
                info = jnp.iinfo(v.dtype) if v.dtype != jnp.bool_ else None
                v = v.astype(jnp.int32) if info is None else v
                info = jnp.iinfo(v.dtype)
                ident = info.max if kind == "min" else info.min
            masked = jnp.where(live, v, ident)
            # extra sort keyed (partition run id via part_start cumsum,
            # value) puts the winner at each partition start
            pid = pscan.cumsum(part_start.astype(jnp.int32))
            sort_v = masked if kind == "min" else (
                -masked if jnp.issubdtype(masked.dtype, jnp.floating)
                else -masked.astype(jnp.int64))
            s2 = jax.lax.sort((pid, sort_v, masked, live.astype(jnp.int8)),
                              num_keys=2, is_stable=False)
            win = pscan.fill_forward(
                jnp.where(part_start, s2[2], 0), part_start)
            any_live = pscan.fill_forward(
                jnp.where(part_start, s2[3], 0), part_start) > 0
            w, wn = win, ~any_live
        else:
            raise NotImplementedError(f"window function {kind}")
        out_cols.append((w, wn | ~s_valid))

    # ---- restore original row order, carrying only the window outputs
    back = ((1 - s_valid.astype(jnp.int8)), s_idx)
    for w, wn in out_cols:
        back += (w, wn)
    b = jax.lax.sort(back, num_keys=2, is_stable=False)
    cols = list(page.columns)
    for i, spec in enumerate(specs):
        w = b[2 + 2 * i]
        wn = b[3 + 2 * i]
        t = spec.output_type
        # min/max over strings operate on dictionary codes (code order ==
        # lexicographic); the output column must keep the dictionary.
        dictionary = (page.columns[spec.field].dictionary
                      if spec.field is not None and t.is_string else None)
        sent = jnp.asarray(t.null_sentinel(), dtype=t.dtype)
        vals = jnp.where(wn, sent, w.astype(t.dtype))
        cols.append(Column(vals, wn, t, dictionary))
    return Page(tuple(cols), page.num_rows,
                page.names + tuple(f"_w{i}" for i in range(len(specs))))
