"""Key normalization, multi-key sort permutations, and vectorized hashing.

These are the shared primitives under grouping, joins, sorting and the
partitioned exchange — the roles the reference implements with
MultiChannelGroupByHash (presto-main-base/.../operator/MultiChannelGroupByHash.java:55),
PagesIndex sorting (.../operator/PagesIndex.java) and
InterpretedHashGenerator (.../operator/InterpretedHashGenerator.java).
TPU-first design: everything is a statically-shaped argsort / gather /
bit-mix — no open-addressing probe loops on device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page


@dataclasses.dataclass(frozen=True)
class SortKey:
    field: int
    ascending: bool = True
    # Presto default: nulls are "larger than any value" — last for ASC,
    # first for DESC (reference: presto-common/.../SortOrder.java).
    nulls_first: Optional[bool] = None

    @property
    def nulls_sort_first(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return not self.ascending


def _orderable_values(col: Column) -> jnp.ndarray:
    """Per-type array whose ascending order == SQL ascending order.
    Strings are already codes into a sorted dictionary."""
    v = col.values
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int32)
    return v


def group_values(col: Column) -> jnp.ndarray:
    """Per-type int64 array where equality == SQL group equality.
    Floats are bit-canonicalized (-0.0 == 0.0, all NaNs equal)."""
    v = col.values
    if v.dtype == jnp.float64 or v.dtype == jnp.float32:
        v64 = v.astype(jnp.float64)
        v64 = jnp.where(v64 == 0.0, 0.0, v64)          # -0.0 -> +0.0
        v64 = jnp.where(jnp.isnan(v64), jnp.nan, v64)  # canonical NaN
        return jax_bitcast_f64_i64(v64)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int64)
    return v.astype(jnp.int64)


def jax_bitcast_f64_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Bit-exact f64 -> i64 via an i32-pair bitcast. A direct s64
    bitcast-convert is unimplemented in the TPU backend's X64-rewriting
    pass ("While rewriting computation to not contain X64 element
    types..."); bitcasting to the next-smaller type adds a minor [2]
    dimension of i32 lanes, which rewrites fine, and the i64 recombine is
    ordinary (emulated) arithmetic."""
    import jax
    pair = jax.lax.bitcast_convert_type(x, jnp.int32)   # [..., 2]
    lo = pair[..., 0].astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
    hi = pair[..., 1].astype(jnp.int64)
    return (hi << jnp.int64(32)) | lo


def sort_perm(page: Page, keys: Sequence[SortKey]) -> jnp.ndarray:
    """Permutation that stably sorts valid rows by `keys` with SQL null
    ordering; padding rows always sort last. Implemented as composed stable
    argsorts, least-significant key first."""
    cap = page.capacity
    perm = jnp.arange(cap, dtype=jnp.int32)
    for k in reversed(list(keys)):
        col = page.columns[k.field]
        v = _orderable_values(col)[perm]
        if not k.ascending:
            # Descending: sort on rank under reversed order. Negate where
            # safe; for unsigned-ish codes negation is fine in int64.
            v = -v.astype(jnp.int64) if v.dtype != jnp.float64 \
                and v.dtype != jnp.float32 else -v
        # Null placement: stable two-pass — first values, then null bucket.
        s = jnp.argsort(v, stable=True)
        perm = perm[s]
        n = col.nulls[perm]
        null_key = jnp.where(n, 0, 1) if k.nulls_sort_first else \
            n.astype(jnp.int32)
        perm = perm[jnp.argsort(null_key, stable=True)]
    # Padding rows last (most-significant).
    pad = (jnp.arange(cap, dtype=jnp.int32) >= page.num_rows)[perm]
    perm = perm[jnp.argsort(pad.astype(jnp.int32), stable=True)]
    return perm


def new_group_flags(page: Page, fields: Sequence[int],
                    perm: jnp.ndarray) -> jnp.ndarray:
    """After sorting by `fields`, True where a row starts a new group
    (row 0 is always a start). Null == null for grouping."""
    cap = page.capacity
    flags = jnp.zeros((cap,), dtype=bool).at[0].set(True)
    for f in fields:
        col = page.columns[f]
        v = group_values(col)[perm]
        n = col.nulls[perm]
        prev_v = jnp.roll(v, 1)
        prev_n = jnp.roll(n, 1)
        same = ((v == prev_v) & ~n & ~prev_n) | (n & prev_n)
        flags = flags | ~same
    return flags.at[0].set(True)


# -- hashing ---------------------------------------------------------------

_SPLITMIX_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_C2 = jnp.uint64(0x94D049BB133111EB)
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint64(30))) * _SPLITMIX_C1
    x = (x ^ (x >> jnp.uint64(27))) * _SPLITMIX_C2
    return x ^ (x >> jnp.uint64(31))


def hash_columns(cols: Sequence[Column]) -> jnp.ndarray:
    """Combined 64-bit hash of the key columns per row (splitmix64 mixing).
    NULL hashes to a fixed tag so null==null grouping/partitioning works;
    join ops must still exclude null keys explicitly (SQL: null != null).

    The reference role: InterpretedHashGenerator / HashGenerationOptimizer's
    precomputed $hash channel."""
    h = jnp.zeros((cols[0].capacity,), dtype=jnp.uint64)
    for c in cols:
        v = group_values(c).astype(jnp.uint64)
        v = jnp.where(c.nulls, jnp.uint64(0x5BD1E995), v)
        h = _mix64(h ^ (v + _GOLDEN + (h << jnp.uint64(6))
                        + (h >> jnp.uint64(2))))
    return h.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF)
