"""Key normalization, multi-key sort permutations, and vectorized hashing.

These are the shared primitives under grouping, joins, sorting and the
partitioned exchange — the roles the reference implements with
MultiChannelGroupByHash (presto-main-base/.../operator/MultiChannelGroupByHash.java:55),
PagesIndex sorting (.../operator/PagesIndex.java) and
InterpretedHashGenerator (.../operator/InterpretedHashGenerator.java).
TPU-first design: everything is a statically-shaped argsort / gather /
bit-mix — no open-addressing probe loops on device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page


@dataclasses.dataclass(frozen=True)
class SortKey:
    field: int
    ascending: bool = True
    # Presto default: nulls are "larger than any value" — last for ASC,
    # first for DESC (reference: presto-common/.../SortOrder.java).
    nulls_first: Optional[bool] = None

    @property
    def nulls_sort_first(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return not self.ascending


def _orderable_values(col: Column) -> jnp.ndarray:
    """Per-type array whose ascending order == SQL ascending order.
    Strings are already codes into a sorted dictionary. Decimal128
    columns order by their float64 image — exact to 2^53; ORDER BY
    uses `_orderable_lanes` instead for exact 128-bit ordering."""
    from presto_tpu.data.column import Decimal128Column
    if isinstance(col, Decimal128Column):
        img = (col.l3.astype(jnp.float64) * float(2 ** 96)
               + col.l2.astype(jnp.float64) * float(2 ** 64)
               + col.l1.astype(jnp.float64) * float(2 ** 32)
               + col.l0.astype(jnp.float64))
        if col.count is not None:
            img = img / jnp.maximum(col.count, 1).astype(jnp.float64)
        return img
    v = col.values
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int32)
    return v


def _orderable_lanes(col: Column):
    """Sort-key lanes, most-significant first; lexicographic comparison
    of the lanes == SQL ascending order. Decimal128 values/SUMS sort
    exactly: normalize carries up the four limb lanes (l2/l1/l0
    accumulate unsigned 32-bit limbs, so each lane's overflow carries
    into the next), then (l3, l2, l1, l0) lexicographic IS value order
    because the lower lanes land in [0, 2^32) and l3 keeps the sign.
    Averages (count set) keep the float64 image of sum/count — a ratio
    has no per-row sort key that is exact without division."""
    from presto_tpu.data.column import Decimal128Column
    if isinstance(col, Decimal128Column) and col.count is None:
        m = jnp.int64(0xFFFFFFFF)
        t0 = col.l0
        n0 = t0 & m
        t1 = col.l1 + (t0 >> 32)
        n1 = t1 & m
        t2 = col.l2 + (t1 >> 32)
        n2 = t2 & m
        t3 = col.l3 + (t2 >> 32)
        return [t3, n2, n1, n0]
    return [_orderable_values(col)]


def group_values(col: Column) -> jnp.ndarray:
    """Per-type array where equality/order == SQL group equality/order.
    Floats stay raw f64 — NO canonicalization and NO 64-bit bitcasts
    (the TPU backend's X64-rewriting pass cannot lower bitcast-convert
    on 64-bit element types in either direction). Float keys sort and
    compare as floats: XLA's sort is total-order with every NaN last,
    IEEE == already treats -0.0 == +0.0, and equality sites must use
    `values_equal` for NaN == NaN; `f64_hash_lanes` collapses NaN/zero
    classes itself for hashing."""
    v = col.values
    if v.dtype == jnp.float64 or v.dtype == jnp.float32:
        # no bit-canonicalization needed: -0.0 == 0.0 under IEEE ==,
        # values_equal handles NaN == NaN, and f64_hash_lanes collapses
        # every NaN/zero to one hash itself
        return v.astype(jnp.float64)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int64)
    return v.astype(jnp.int64)


def values_equal(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Group-key equality over group_values outputs: NaN == NaN (SQL
    grouping semantics). `x != x` is False for every non-float dtype, so
    this is a no-op for ints."""
    return (a == b) | ((a != a) & (b != b))


def f64_hash_lanes(v: jnp.ndarray) -> jnp.ndarray:
    """Deterministic u64 hash input for f64 values without bitcasting:
    SCALE-AWARE exponent + top-32-mantissa-bit lanes extracted
    arithmetically (log2/exp2), so entropy survives at every magnitude
    (a fixed-point trunc/frac split would collapse everything below
    2^-32 absolute). Values equal to ~32 significant bits collide —
    callers use it for bucketing/partitioning only, never equality."""
    is_nan = jnp.isnan(v)
    is_inf = jnp.isinf(v)
    safe = jnp.where(is_nan | is_inf, 1.0, v)
    ae = jnp.maximum(jnp.abs(safe), 1e-300)
    # floor(log2): ±1 ulp of log2 can misplace the boundary by one —
    # that only shifts which 32 mantissa bits we sample, still distinct
    e = jnp.floor(jnp.log2(ae))
    norm = ae * jnp.exp2(-e)                       # ~[1, 2)
    mant = (jnp.clip(norm - 1.0, 0.0, 1.0)
            * (2.0 ** 32)).astype(jnp.uint64)
    eb = (e.astype(jnp.int64) + 2048).astype(jnp.uint64)
    h = eb * _GOLDEN ^ mant
    h = jnp.where(v < 0, h ^ jnp.uint64(0xA5A5A5A5DEADBEEF), h)
    h = jnp.where(v == 0.0, jnp.uint64(0x5E5E0000), h)   # ±0 hash equal
    h = jnp.where(is_nan, jnp.uint64(0x7FF8000000000001), h)
    h = jnp.where(is_inf & (v > 0), jnp.uint64(0x7FF0000000000000), h)
    h = jnp.where(is_inf & (v < 0), jnp.uint64(0xFFF0000000000000), h)
    return h


def lex_perm(lanes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Permutation sorting rows lexicographically by `lanes` (most
    significant first, each ascending), via composed STABLE argsorts —
    2-operand sorts only. On this stack a wide variadic lax.sort's
    compile cost explodes with operand count (20 operands at SF1 shapes
    never finishes compiling through the remote compile service), while
    argsort + gather compiles in seconds per lane and gathers run at
    memory bandwidth; every operator therefore sorts via this helper and
    gathers its payload by the permutation."""
    perm = None
    for lane in reversed(list(lanes)):
        if perm is None:
            perm = jnp.argsort(lane, stable=True)
        else:
            perm = perm[jnp.argsort(lane[perm], stable=True)]
    return perm


def sort_perm(page: Page, keys: Sequence[SortKey]) -> jnp.ndarray:
    """Permutation that stably sorts valid rows by `keys` with SQL null
    ordering; padding rows always sort last. Implemented as composed stable
    argsorts, least-significant key first."""
    cap = page.capacity
    perm = jnp.arange(cap, dtype=jnp.int32)
    for k in reversed(list(keys)):
        col = page.columns[k.field]
        # Multi-lane keys (Decimal128): least-significant lane first,
        # each pass a stable argsort, composing to lexicographic order.
        for lane in reversed(_orderable_lanes(col)):
            v = lane[perm]
            if not k.ascending:
                # Descending: sort on rank under reversed order. Negate
                # where safe; codes/limbs negate fine in int64.
                v = -v.astype(jnp.int64) if v.dtype != jnp.float64 \
                    and v.dtype != jnp.float32 else -v
            perm = perm[jnp.argsort(v, stable=True)]
        # Null placement: stable two-pass — values first, then null bucket.
        n = col.nulls[perm]
        null_key = jnp.where(n, 0, 1) if k.nulls_sort_first else \
            n.astype(jnp.int32)
        perm = perm[jnp.argsort(null_key, stable=True)]
    # Padding rows last (most-significant).
    pad = (jnp.arange(cap, dtype=jnp.int32) >= page.num_rows)[perm]
    perm = perm[jnp.argsort(pad.astype(jnp.int32), stable=True)]
    return perm


def new_group_flags(page: Page, fields: Sequence[int],
                    perm: jnp.ndarray) -> jnp.ndarray:
    """After sorting by `fields`, True where a row starts a new group
    (row 0 is always a start). Null == null for grouping."""
    cap = page.capacity
    flags = jnp.zeros((cap,), dtype=bool).at[0].set(True)
    for f in fields:
        col = page.columns[f]
        v = group_values(col)[perm]
        n = col.nulls[perm]
        prev_v = jnp.roll(v, 1)
        prev_n = jnp.roll(n, 1)
        same = (values_equal(v, prev_v) & ~n & ~prev_n) | (n & prev_n)
        flags = flags | ~same
    return flags.at[0].set(True)


# -- hashing ---------------------------------------------------------------

_SPLITMIX_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_C2 = jnp.uint64(0x94D049BB133111EB)
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint64(30))) * _SPLITMIX_C1
    x = (x ^ (x >> jnp.uint64(27))) * _SPLITMIX_C2
    return x ^ (x >> jnp.uint64(31))


def hash_columns(cols: Sequence[Column]) -> jnp.ndarray:
    """Combined 64-bit hash of the key columns per row (splitmix64 mixing).
    NULL hashes to a fixed tag so null==null grouping/partitioning works;
    join ops must still exclude null keys explicitly (SQL: null != null).

    The reference role: InterpretedHashGenerator / HashGenerationOptimizer's
    precomputed $hash channel."""
    h = jnp.zeros((cols[0].capacity,), dtype=jnp.uint64)
    for c in cols:
        g = group_values(c)
        if jnp.issubdtype(g.dtype, jnp.floating):
            v = f64_hash_lanes(g)     # arithmetic lanes, no bitcast
        else:
            v = g.astype(jnp.uint64)
        v = jnp.where(c.nulls, jnp.uint64(0x5BD1E995), v)
        h = _mix64(h ^ (v + _GOLDEN + (h << jnp.uint64(6))
                        + (h >> jnp.uint64(2))))
    return h.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF)
