"""Grouped aggregation — sort-based, fully vectorized.

The engine's analogue of HashAggregationOperator
(presto-main-base/.../operator/HashAggregationOperator.java:56,413 over
MultiChannelGroupByHash.java:55). TPU-first redesign: instead of an
open-addressing hash table probed row-at-a-time, we sort by the group keys
(one fused multi-key argsort), detect group boundaries, and reduce with
segment ops — every step is a statically-shaped XLA op that maps onto the
vector units; no data-dependent control flow.

Partial/final split (the distributed pattern, reference
AggregationNode.Step): `grouped_aggregate` evaluates any step; AVG carries
(sum, count) through partials exactly like the reference's accumulator
states.

Capacity contract: the output page has static capacity `out_capacity`
(default: input capacity). If the true group count exceeds it, num_rows is
clamped and `overflowed(page)` lets the host re-run at a bigger bucket —
the engine's recompile-and-retry answer to dynamic cardinalities
(SURVEY.md §7.3 #1).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp

from presto_tpu.data.column import Column, Page
from presto_tpu.types import BIGINT, DOUBLE, Type


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind in {sum,count,count_star,min,max,avg,
    sum_partial,count_partial,avg_partial,avg_final,...}.

    Step handling (mirrors AggregationNode.Step PARTIAL/FINAL/SINGLE):
      - SINGLE: kind as-is over raw input.
      - PARTIAL: avg -> emits two columns (sum, count); others emit their
        partial state (sum/count/min/max).
      - FINAL: count -> sum of partial counts; avg -> sum(sums)/sum(counts).
    The *planner* rewrites kinds for partial/final; this op just evaluates
    what it is given.
    """
    kind: str
    field: Optional[int]          # input column (None for count_star)
    output_type: Type
    field2: Optional[int] = None  # second state input (avg_final: count)
    mask_field: Optional[int] = None  # FILTER / mask channel (bool column)
    param: Optional[float] = None  # extra literal (approx_percentile p)


# ---------------------------------------------------------------------------
# HyperLogLog pieces (approx_distinct)
#
# Reference: operator/aggregation/ApproximateCountDistinctAggregation +
# airlift HLL (dense). TPU-first shape: never a per-row register-table
# scatter — registers materialize as (register, rank) pairs carried through
# the SAME multi-operand sorts the rest of the aggregation uses; the max
# rank per register is whoever sorts first in its (group, register) run.
# Default precision matches Presto's 2.3% standard error tier.
# ---------------------------------------------------------------------------

_HLL_P = 11
_HLL_M = 1 << _HLL_P
_HLL_ALPHA = 0.7213 / (1.0 + 1.079 / _HLL_M)


def _hll_reg_rank(vals: jnp.ndarray):
    """Per-row (register id int32, rank int32). rank = leading-zero count
    of the hash's top 64-p bits, + 1."""
    import jax

    from presto_tpu.ops.keys import _GOLDEN, _mix64

    if jnp.issubdtype(vals.dtype, jnp.floating):
        # scale-aware arithmetic lanes (no 64-bit bitcasts on TPU);
        # values equal to ~32 significant bits collide, slightly
        # undercounting only when a column has >2^32-fine distinctions
        from presto_tpu.ops.keys import f64_hash_lanes
        bits = f64_hash_lanes(vals.astype(jnp.float64))
    else:
        bits = vals.astype(jnp.uint64)
    h = _mix64(bits + _GOLDEN)
    reg = (h & jnp.uint64(_HLL_M - 1)).astype(jnp.int32)
    w = h >> jnp.uint64(_HLL_P)
    # floor(log2(w)) via f32 frexp — f64 frexp would need a 64-bit
    # bitcast, which the TPU X64-rewriting pass cannot lower. The f32
    # rounding can bump w across a power of two for ~2^-24 of values,
    # nudging one rank — noise far below the sketch's 2.3% error.
    _mant, exp = jnp.frexp(w.astype(jnp.float32))
    rank = jnp.where(w == 0, 64 - _HLL_P + 1,
                     (64 - _HLL_P) - (exp - 1)).astype(jnp.int32)
    return reg, rank


def _hll_estimate(present_sum: jnp.ndarray, zeros: jnp.ndarray):
    """Registers -> cardinality: raw harmonic-mean estimate with the
    standard linear-counting small-range correction."""
    m = float(_HLL_M)
    zeros_f = zeros.astype(jnp.float64)
    raw = _HLL_ALPHA * m * m / jnp.maximum(
        present_sum + zeros_f, 1e-12)
    small = m * jnp.log(m / jnp.maximum(zeros_f, 1.0))
    use_small = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_small, small, raw)


# Direct (sort-free, scatter-free) grouping.
#
# When every group key has a small static domain (dictionary-coded strings,
# booleans) the group id is a mixed-radix code computed per row, and each
# aggregate becomes a handful of masked whole-array reductions — one per
# bin — instead of a per-row scatter-add. On TPU this matters twice over:
# no argsorts (the general path's grouping mechanism) and no scatters
# (which XLA serializes row-by-row for colliding indices: measured ~0.65 s
# per scatter over 8M rows vs ~0.06 s for the fused masked reductions).
# The TPU counterpart of MultiChannelGroupByHash's dictionary fast path.

_DIRECT_MAX_BINS = 64


def _direct_domains(page: Page, group_fields: Sequence[int],
                    max_bins: int = _DIRECT_MAX_BINS):
    """Per-key domain sizes if the direct path applies, else None."""
    domains = []
    for f in group_fields:
        c = page.columns[f]
        if c.type.is_string and c.dictionary is not None:
            domains.append(len(c.dictionary))
        elif c.values.dtype == jnp.bool_:
            domains.append(2)
        else:
            return None
    prod = 1
    for d in domains:
        prod *= d + 1                      # +1: per-key NULL bin
        if prod > max_bins:
            return None
    return domains, prod


def _direct_grouped_aggregate(page: Page, group_fields: Sequence[int],
                              aggs: Sequence[AggSpec], out_cap: int,
                              valid: jnp.ndarray, domains, prod: int,
                              min_groups: int = 0):
    cap = page.capacity
    code = jnp.zeros((cap,), jnp.int32)
    for f, dom in zip(group_fields, domains):
        c = page.columns[f]
        v = jnp.clip(c.values.astype(jnp.int32), 0, dom - 1)
        v = jnp.where(c.nulls, dom, v)     # NULL sorts after all codes
        code = code * (dom + 1) + v

    # Per-bin row masks; XLA fuses these into a few passes over the page.
    masks = [valid & (code == b) for b in range(prod)]
    counts = jnp.stack([jnp.sum(m) for m in masks])          # [prod]
    nonempty = counts > 0
    num_groups = jnp.maximum(jnp.sum(nonempty), min_groups).astype(jnp.int32)

    # Compact non-empty bins to the front; raw bin order == sorted key
    # order (sorted dictionaries), nulls last per key.
    order_key = jnp.where(nonempty, 0, prod) + jnp.arange(prod,
                                                          dtype=jnp.int32)
    bin_perm = jnp.argsort(order_key)                        # [prod] tiny
    width = min(out_cap, prod)
    take = bin_perm[:width]
    out_valid_w = jnp.arange(width, dtype=jnp.int32) < num_groups

    def widen(bins_arr, t: Type, nulls_w, dictionary=None):
        """Place per-bin results [prod] into an out_cap column."""
        v = bins_arr[take].astype(t.dtype)
        sent = jnp.asarray(t.null_sentinel(), dtype=t.dtype)
        v = jnp.where(nulls_w | ~out_valid_w, sent, v)
        nl = nulls_w | ~out_valid_w
        if width < out_cap:
            pad = out_cap - width
            v = jnp.concatenate([v, jnp.full((pad,), sent, dtype=t.dtype)])
            nl = jnp.concatenate([nl, jnp.ones((pad,), bool)])
        return Column(v, nl, t, dictionary)

    cols = []
    # Group keys: decode the mixed-radix bin index statically.
    stride = prod
    for f, dom in zip(group_fields, domains):
        c = page.columns[f]
        stride //= (dom + 1)
        key_code = (jnp.arange(prod, dtype=jnp.int32) // stride) % (dom + 1)
        knull = key_code == dom
        cols.append(widen(jnp.where(knull, 0, key_code), c.type,
                          knull[take], c.dictionary))

    false_w = jnp.zeros((width,), bool)
    for a in aggs:
        if a.kind in ("sum128_merge", "avg128_merge"):
            # FINAL over Decimal128 partial states (global/no-GROUP-BY
            # distributed DECIMAL aggregation routes here): sum the
            # limb lanes independently per bin.
            from presto_tpu.data.column import Decimal128Column
            pc = page.columns[a.field]
            live_m = [m & ~pc.nulls for m in masks]
            n_per = jnp.stack([jnp.sum(lv) for lv in live_m])
            lane_b = [jnp.stack([jnp.sum(jnp.where(lv, lane, 0))
                                 for lv in live_m])
                      for lane in pc.value_lanes]
            count_b = None
            if a.kind == "avg128_merge":
                cc = page.columns[a.field2]
                cl = [m & ~cc.nulls for m in masks]
                count_b = jnp.stack(
                    [jnp.sum(jnp.where(lv2, cc.values, 0))
                     for lv2 in cl]).astype(jnp.int64)
            is_null = (n_per == 0)[take] | ~out_valid_w

            def lane128(bins_arr, fill=0):
                v = jnp.where(is_null, fill, bins_arr[take])
                if width < out_cap:
                    v = jnp.concatenate(
                        [v, jnp.full((out_cap - width,), fill,
                                     dtype=v.dtype)])
                return v
            nl = is_null
            if width < out_cap:
                nl = jnp.concatenate(
                    [nl, jnp.ones((out_cap - width,), bool)])
            cols.append(Decimal128Column(
                *[lane128(b) for b in lane_b], nl, a.output_type,
                count=(lane128(count_b) if count_b is not None
                       else None)))
            continue
        vals, nulls = _agg_inputs(a, page)
        dictionary = (page.columns[a.field].dictionary
                      if a.field is not None and a.output_type.is_string
                      else None)
        t = a.output_type
        kind = a.kind
        if (a.field is not None
                and not hasattr(page.columns[a.field], "values")
                and kind not in ("sum128", "avg128", "count",
                                 "min", "max")):
            # vals is only the l0 limb for wide inputs — anything that
            # would consume it as a value must reject, not mis-compute
            raise NotImplementedError(f"{kind} over DECIMAL(38) input")
        live = [m & ~nulls for m in masks]
        n_per = jnp.stack([jnp.sum(lv) for lv in live])
        if kind == "count_star":
            cols.append(widen(counts.astype(jnp.int64), t, false_w))
        elif kind == "count":
            cols.append(widen(n_per.astype(jnp.int64), t, false_w))
        elif kind in ("sum", "avg", "avg_partial", "avg_final"):
            acc = jnp.float64 if (t.is_floating or kind != "sum") \
                else jnp.int64
            zero = jnp.asarray(0, dtype=acc)
            s = jnp.stack([jnp.sum(jnp.where(lv, vals, zero).astype(acc))
                           for lv in live])
            if acc == jnp.int64:
                # checked SUM (BigintOperators-style): an int64 total that
                # wrapped is ~2^64 away from the float64 shadow sum, far
                # beyond float rounding error (~n * 2^11 at n=10^7)
                from presto_tpu.expr import errors as E
                fs = jnp.stack([jnp.sum(
                    jnp.where(lv, vals, zero).astype(jnp.float64))
                    for lv in live])
                code = E.OVF_DECIMAL if t.is_decimal else E.OVF_SUM
                E.record(code, jnp.any(
                    jnp.abs(fs - s.astype(jnp.float64)) > 2.0 ** 62))
            if kind == "avg_final":
                c2 = page.columns[a.field2]
                c2v = jnp.where(c2.nulls, 0, c2.values)
                n2 = jnp.stack([jnp.sum(jnp.where(m, c2v, 0))
                                for m in masks])
                cols.append(widen(s / jnp.maximum(n2, 1), t,
                                  (n2 == 0)[take]))
            elif kind == "sum":
                cols.append(widen(s, t, (n_per == 0)[take]))
            elif kind == "avg":
                cols.append(widen(s / jnp.maximum(n_per, 1), t,
                                  (n_per == 0)[take]))
            else:  # avg_partial -> (sum double, count bigint)
                cols.append(widen(s, DOUBLE, (n_per == 0)[take]))
                cols.append(widen(n_per.astype(jnp.int64), BIGINT, false_w))
        elif kind in ("sum128", "avg128"):
            # DECIMAL(38): four 32-bit limb sums per bin (int64 inputs
            # decompose device-side; wide inputs already carry lanes);
            # exact recombination happens host-side
            # (Decimal128Column.value_at)
            from presto_tpu.data.column import Decimal128Column
            pc = page.columns[a.field]
            in_lanes = (pc.value_lanes if isinstance(pc, Decimal128Column)
                        else Decimal128Column.decompose_int64(vals))
            live_s = jnp.stack(live)
            lane_b = [jnp.sum(jnp.where(live_s, x.astype(jnp.int64), 0),
                              axis=1) for x in in_lanes]
            nulls_w = (n_per == 0)[take]
            is_null = nulls_w | ~out_valid_w

            def lane(bins_arr, fill=0):
                v = jnp.where(is_null, fill, bins_arr[take])
                if width < out_cap:
                    pad = out_cap - width
                    v = jnp.concatenate(
                        [v, jnp.full((pad,), fill, dtype=v.dtype)])
                return v
            nl = is_null
            if width < out_cap:
                nl = jnp.concatenate(
                    [nl, jnp.ones((out_cap - width,), bool)])
            cols.append(Decimal128Column(
                *[lane(b) for b in lane_b], nl, t,
                count=(lane(n_per.astype(jnp.int64))
                       if kind == "avg128" else None)))
        elif kind in ("min", "max"):
            pc = page.columns[a.field] if a.field is not None else None
            if pc is not None and not hasattr(pc, "values"):
                # DECIMAL(p>18): exact lexicographic min/max over the
                # carry-normalized limb lanes — narrow the live mask
                # lane by lane (most-significant first); 4 masked
                # reductions, no 128-bit compare needed
                from presto_tpu.data import int128 as I
                from presto_tpu.data.column import Decimal128Column
                norm = I.normalize(pc.value_lanes)
                win_lanes = []
                masks_nar = [lv for lv in live]
                for li, lane_v in enumerate(norm):
                    ident = (jnp.iinfo(jnp.int64).max if kind == "min"
                             else jnp.iinfo(jnp.int64).min)
                    red = jnp.min if kind == "min" else jnp.max
                    w = jnp.stack([red(jnp.where(m, lane_v, ident))
                                   for m in masks_nar])
                    masks_nar = [m & (lane_v == w[bi])
                                 for bi, m in enumerate(masks_nar)]
                    win_lanes.append(w)
                is_null = (n_per == 0)[take] | ~out_valid_w

                def lane_mm(bins_arr, fill=0):
                    v2 = jnp.where(is_null, fill, bins_arr[take])
                    if width < out_cap:
                        v2 = jnp.concatenate(
                            [v2, jnp.full((out_cap - width,), fill,
                                          dtype=v2.dtype)])
                    return v2
                nl2 = is_null
                if width < out_cap:
                    nl2 = jnp.concatenate(
                        [nl2, jnp.ones((out_cap - width,), bool)])
                cols.append(Decimal128Column(
                    *[lane_mm(w) for w in win_lanes], nl2, t))
                continue
            v = vals.astype(jnp.int32) if vals.dtype == jnp.bool_ else vals
            if jnp.issubdtype(v.dtype, jnp.floating):
                ident = jnp.inf if kind == "min" else -jnp.inf
            else:
                info = jnp.iinfo(v.dtype)
                ident = info.max if kind == "min" else info.min
            red = jnp.min if kind == "min" else jnp.max
            r = jnp.stack([red(jnp.where(lv, v, ident)) for lv in live])
            cols.append(widen(r, t, (n_per == 0)[take], dictionary))
        elif kind in ("bool_or", "bool_and"):
            if kind == "bool_or":
                r = jnp.stack([jnp.any(lv & vals.astype(bool))
                               for lv in live])
            else:
                r = jnp.stack([jnp.all(~lv | vals.astype(bool))
                               for lv in live])
            cols.append(widen(r, t, (n_per == 0)[take]))
        elif kind == "approx_distinct":
            import jax

            live_all = valid & ~nulls
            reg, rank = _hll_reg_rank(vals)
            # sort (bin, register, rank desc): the first row of each
            # (bin, register) run holds that register's max rank
            code_s = jnp.where(live_all, code, prod)
            s_ops = jax.lax.sort((code_s, reg, -rank, rank),
                                 num_keys=3, is_stable=False)
            sc, sreg, _nr, srank = s_ops
            first = (jnp.roll(sc, 1) != sc) | (jnp.roll(sreg, 1) != sreg)
            first = first.at[0].set(True)
            first = first & (sc < prod)
            contrib = jnp.where(first,
                                jnp.exp2(-srank.astype(jnp.float64)), 0.0)
            present = jnp.stack([
                jnp.sum(jnp.where(first & (sc == b), contrib, 0.0))
                for b in range(prod)])
            dregs = jnp.stack([jnp.sum(first & (sc == b))
                               for b in range(prod)])
            est = _hll_estimate(present, _HLL_M - dregs)
            est = jnp.where(n_per == 0, 0, jnp.round(est))
            cols.append(widen(est.astype(jnp.int64), t, false_w))
        elif kind == "approx_percentile":
            import jax

            from presto_tpu.ops.keys import _orderable_values

            frac = float(a.param if a.param is not None else 0.5)
            src_t = (page.columns[a.field].type
                     if a.field is not None else t)
            live_all = valid & ~nulls
            ov = _orderable_values(Column(vals, nulls, src_t, dictionary))
            if ov.dtype == jnp.bool_:
                ov = ov.astype(jnp.int32)
            code_s = jnp.where(live_all, code, prod)
            s_ops = jax.lax.sort((code_s, ov, vals), num_keys=2,
                                 is_stable=False)
            svals = s_ops[2]
            live_counts = jnp.stack([jnp.sum(live_all & (code == b))
                                     for b in range(prod)])
            bin_starts = jnp.cumsum(live_counts) - live_counts
            idx = bin_starts + jnp.floor(
                frac * jnp.maximum(live_counts - 1, 0)
                .astype(jnp.float64)).astype(live_counts.dtype)
            picked = jnp.take(svals, jnp.clip(idx, 0, cap - 1),
                              mode="clip")
            cols.append(widen(picked, t, (live_counts == 0)[take],
                              dictionary))
        else:
            raise NotImplementedError(f"aggregate {kind}")

    out_rows = jnp.minimum(num_groups, out_cap)
    return Page(tuple(cols), out_rows, ()), num_groups


def _agg_inputs(a: AggSpec, page: Page):
    """(values, null-or-masked-out) for an aggregate input, unpermuted."""
    if a.field is not None:
        col = page.columns[a.field]
        # Decimal128 inputs have limb lanes, not a single values lane;
        # sum128/avg128 read the lanes themselves — hand them l0 so the
        # null/mask plumbing stays uniform
        vals = col.values if hasattr(col, "values") else col.l0
        nulls = col.nulls
    else:
        vals = jnp.zeros((page.capacity,), dtype=jnp.int64)
        nulls = jnp.zeros((page.capacity,), dtype=bool)
    if a.mask_field is not None:
        m = page.columns[a.mask_field]
        nulls = nulls | ~(~m.nulls & m.values.astype(bool))
    return vals, nulls


def grouped_aggregate(page: Page, group_fields: Sequence[int],
                      aggs: Sequence[AggSpec],
                      out_capacity: Optional[int] = None,
                      row_mask: Optional[jnp.ndarray] = None,
                      direct_max_bins: int = _DIRECT_MAX_BINS):
    """Group `page` by `group_fields` and evaluate `aggs`. Output columns:
    group keys (in order) then one column per agg (avg_partial emits two).
    With no group fields, emits exactly one row (SQL global aggregation).
    `row_mask` (bool per row) pre-filters rows without a compaction pass —
    the fused ScanFilterAndProject -> Aggregation pipeline.

    Returns (page, true_group_count): true_group_count is unclamped so the
    host can detect out_capacity overflow and retry at a bigger bucket."""
    cap = page.capacity
    out_cap = out_capacity or cap
    valid = page.row_valid()
    if row_mask is not None:
        valid = valid & row_mask

    if not group_fields:
        # Global aggregation: one bin, pure masked whole-array reductions —
        # never a scatter (XLA serializes colliding-index scatters on TPU).
        # min_groups=1: SQL global aggregation emits exactly one row even
        # over empty input (count()=0, sum()=NULL).
        return _direct_grouped_aggregate(page, (), aggs, out_cap, valid,
                                         [], 1, min_groups=1)

    # Decimal128 merge steps read limb-lane columns — sorted path only
    merge128 = any(a.kind in ("sum128_merge", "avg128_merge")
                   for a in aggs)
    d = None if merge128 else _direct_domains(page, group_fields,
                                              direct_max_bins)
    if d is not None:
        domains, prod = d
        return _direct_grouped_aggregate(
            page, group_fields, aggs, out_cap, valid, domains, prod)
    return _sorted_grouped_aggregate(page, group_fields, aggs, out_cap,
                                     valid)


def _sorted_grouped_aggregate(page: Page, group_fields: Sequence[int],
                              aggs: Sequence[AggSpec], out_cap: int,
                              valid: jnp.ndarray):
    """General (large-domain) grouping: sort a PERMUTATION by the group
    key lanes (composed 2-operand argsorts, ops/keys.lex_perm), gather
    the page by it, then contiguous-segment reductions via blocked
    cumsum (ops/scan.py; scatter-adds serialize on TPU). Wide variadic
    sorts carrying every column as payload are banned — their compile
    cost explodes with operand count on this stack.

    Reference role: HashAggregationOperator over MultiChannelGroupByHash —
    re-expressed as sort + segment reduce because a probe-loop hash table
    has no efficient TPU form, but a sort network does."""
    from presto_tpu.data.column import gather_page
    from presto_tpu.ops import scan as pscan
    from presto_tpu.ops.keys import group_values, lex_perm, values_equal

    cap = page.capacity

    # Sort lanes: invalid rows last, then per group field (nulls last,
    # group-canonical value). The invalid rank folds into the FIRST
    # field's null rank (invalid > null > value) to save one pass.
    inv_rank = (~valid).astype(jnp.int8)
    lanes = []
    for i, f in enumerate(group_fields):
        c = page.columns[f]
        nrank = c.nulls.astype(jnp.int8)
        lanes.append(inv_rank * 2 + nrank if i == 0 else nrank)
        lanes.append(group_values(c))
    if not group_fields:
        lanes.append(inv_rank)
    perm = lex_perm(lanes)
    gvalid = valid[perm]
    sp = gather_page(page, perm)

    # New-group flags from adjacent compare on the sorted key lanes.
    flags = jnp.zeros((cap,), dtype=bool).at[0].set(True)
    for f in group_fields:
        c = sp.columns[f]
        n = c.nulls
        v = group_values(c)
        prev_n = jnp.roll(n, 1)
        prev_v = jnp.roll(v, 1)
        # values_equal: NaN group keys compare equal (SQL grouping)
        same = (values_equal(v, prev_v) & ~n & ~prev_n) | (n & prev_n)
        flags = flags | ~same
    flags = flags.at[0].set(True)

    starts, gid = pscan.group_starts(flags, gvalid, out_cap)
    num_groups = jnp.sum(flags & gvalid).astype(jnp.int32)
    total_valid = jnp.sum(gvalid).astype(jnp.int32)
    g_arange = jnp.arange(out_cap, dtype=jnp.int32)
    out_valid = g_arange < jnp.minimum(num_groups, out_cap)
    nxt = jnp.concatenate([starts[1:], jnp.full((1,), cap, jnp.int32)])
    ends = jnp.where(g_arange + 1 < num_groups, nxt, total_valid)
    ends = jnp.where(out_valid, ends, starts)        # empty for overflow

    cols = []
    for f in group_fields:
        cols.append(sp.columns[f].gather(starts, out_valid))
    for a in aggs:
        cols.extend(_eval_agg_sorted(a, sp, gvalid, gid, starts, ends,
                                     out_valid, pscan))

    return Page(tuple(cols), jnp.minimum(num_groups, out_cap), ()), \
        num_groups


def _eval_agg_sorted(a: AggSpec, sp: Page, gvalid, gid, starts, ends,
                     out_valid, pscan):
    """Evaluate one aggregate over contiguous sorted segments."""
    t = a.output_type
    out_cap = starts.shape[0]
    if a.kind in ("sum128_merge", "avg128_merge"):
        # FINAL step over Decimal128 partial states (limb lanes summed
        # independently — the distributed DECIMAL(38) merge; reference:
        # the FINAL accumulator of DecimalSumAggregation re-expressed
        # over limb lanes). avg128_merge also sums the count column.
        from presto_tpu.data.column import Decimal128Column
        pc = sp.columns[a.field]
        assert isinstance(pc, Decimal128Column), type(pc)
        live = ~pc.nulls & gvalid
        lanes = [pscan.segment_sums(jnp.where(live, x, 0), starts, ends)
                 for x in pc.value_lanes]
        n = pscan.segment_sums(live.astype(jnp.int64), starts, ends)
        count = None
        if a.kind == "avg128_merge":
            cc = sp.columns[a.field2]
            cv = jnp.where(cc.nulls | ~gvalid, 0, cc.values)
            count = pscan.segment_sums(cv.astype(jnp.int64), starts,
                                       ends)
        is_null = (n == 0) | ~out_valid
        return [Decimal128Column(
            *[jnp.where(is_null, 0, x) for x in lanes],
            is_null, t, count=count)]
    if a.field is not None:
        col = sp.columns[a.field]
        if not hasattr(col, "values") \
                and a.kind not in ("sum128", "avg128", "count",
                                   "min", "max"):
            raise NotImplementedError(
                f"{a.kind} over DECIMAL(38) input")
        vals = col.values if hasattr(col, "values") else col.l0
        nulls = col.nulls | ~gvalid
    else:
        vals = jnp.zeros((sp.capacity,), dtype=jnp.int64)
        nulls = ~gvalid
    if a.mask_field is not None:
        m = sp.columns[a.mask_field]
        nulls = nulls | ~(~m.nulls & m.values.astype(bool))

    dictionary = (sp.columns[a.field].dictionary
                  if a.field is not None and t.is_string else None)

    def out(values, nullmask):
        sent = jnp.asarray(t.null_sentinel(), dtype=t.dtype)
        v = jnp.where(nullmask | ~out_valid, sent, values.astype(t.dtype))
        return Column(v, (nullmask | ~out_valid), t, dictionary)

    def seg_count(live_mask):
        return pscan.segment_sums(live_mask.astype(jnp.int32), starts,
                                  ends).astype(jnp.int64)

    kind = a.kind
    if kind == "count_star":
        return [out((ends - starts).astype(jnp.int64),
                    jnp.zeros_like(out_valid))]
    if kind == "count":
        return [out(seg_count(~nulls), jnp.zeros_like(out_valid))]
    if kind in ("sum128", "avg128"):
        # DECIMAL(38) accumulation: inputs as four 32-bit limb lanes
        # (int64 storage decomposes device-side; wide Decimal128 inputs
        # already carry lanes), segment-summed separately — each limb
        # sum fits int64 for any realistic row count, and the exact
        # 128-bit value recombines on the host (reference:
        # UnscaledDecimal128Arithmetic.java; limb lanes because no
        # 128-bit ops lower on TPU)
        from presto_tpu.data.column import Decimal128Column
        pc = sp.columns[a.field]
        in_lanes = (pc.value_lanes if isinstance(pc, Decimal128Column)
                    else Decimal128Column.decompose_int64(vals))
        lanes = [pscan.segment_sums(
            jnp.where(nulls, 0, x.astype(jnp.int64)), starts, ends)
            for x in in_lanes]
        n = seg_count(~nulls)
        is_null = (n == 0) | ~out_valid
        col = Decimal128Column(
            *[jnp.where(is_null, 0, x) for x in lanes],
            is_null, t, count=(n if kind == "avg128" else None))
        return [col]
    if kind in ("sum", "avg", "avg_partial"):
        acc_dtype = jnp.float64 if t.is_floating or kind != "sum" \
            else jnp.int64
        contrib = jnp.where(nulls, 0, vals).astype(acc_dtype)
        s = pscan.segment_sums(contrib, starts, ends)
        n = seg_count(~nulls)
        if acc_dtype == jnp.int64:
            from presto_tpu.expr import errors as E
            fs = pscan.segment_sums(contrib.astype(jnp.float64),
                                    starts, ends)
            E.record(E.OVF_DECIMAL if t.is_decimal else E.OVF_SUM,
                     jnp.any(jnp.abs(fs - s.astype(jnp.float64))
                             > 2.0 ** 62))
        if kind == "sum":
            return [out(s, n == 0)]
        if kind == "avg":
            return [out(s / jnp.maximum(n, 1), n == 0)]
        sum_col = Column(jnp.where(n == 0, jnp.inf, s.astype(jnp.float64)),
                         n == 0, DOUBLE)
        cnt_col = Column(n, jnp.zeros_like(n, dtype=bool), BIGINT)
        return [sum_col, cnt_col]
    if kind == "avg_final":
        cnt_col = sp.columns[a.field2]
        cvals = jnp.where(cnt_col.nulls, 0, cnt_col.values)
        s = pscan.segment_sums(jnp.where(nulls, 0.0, vals)
                               .astype(jnp.float64), starts, ends)
        n = pscan.segment_sums(cvals.astype(jnp.int64), starts, ends)
        return [out(s / jnp.maximum(n, 1), n == 0)]
    if kind in ("min", "max"):
        # Secondary sort keyed by (gid, null-last, value): the winner lands
        # at each segment start. One extra multi-operand sort, no scatter.
        import jax

        from presto_tpu.ops.keys import _orderable_values

        pc_mm = sp.columns[a.field] if a.field is not None else None
        if pc_mm is not None and not hasattr(pc_mm, "values"):
            # DECIMAL(p>18): sort by (gid, null, normalized limb lanes)
            # — lexicographic lane order IS exact 128-bit value order —
            # and gather the winner's lanes at each segment start
            from presto_tpu.data import int128 as I
            from presto_tpu.data.column import Decimal128Column
            norm = I.normalize(pc_mm.value_lanes)
            if kind == "max":
                norm = I.normalize(I.negate(norm))
            s_ops = jax.lax.sort(
                (gid, nulls.astype(jnp.int8)) + tuple(norm) + (nulls,),
                num_keys=6, is_stable=False)
            win = [jnp.take(x, starts, mode="clip") for x in s_ops[2:6]]
            if kind == "max":
                win = list(I.negate(tuple(win)))
            win_nulls = jnp.take(s_ops[6], starts, mode="clip")
            n = seg_count(~nulls)
            is_null = win_nulls | (n == 0) | ~out_valid
            win = [jnp.where(is_null, 0, w) for w in win]
            return [Decimal128Column(*win, is_null, t)]
        v = _orderable_values(Column(vals, nulls, a.output_type if
                                     a.field is None else
                                     sp.columns[a.field].type, dictionary))
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        sort_v = v if kind == "min" else (
            -v if jnp.issubdtype(v.dtype, jnp.floating)
            else -v.astype(jnp.int64))
        s_ops = jax.lax.sort(
            (gid, nulls.astype(jnp.int8), sort_v, vals, nulls),
            num_keys=3, is_stable=False)
        win_vals = jnp.take(s_ops[3], starts, mode="clip")
        win_nulls = jnp.take(s_ops[4], starts, mode="clip")
        n = seg_count(~nulls)
        return [out(win_vals, win_nulls | (n == 0))]
    if kind in ("bool_or", "bool_and"):
        b = vals.astype(bool) & ~nulls
        trues = pscan.segment_sums(b.astype(jnp.int32), starts, ends)
        n = seg_count(~nulls)
        r = (trues > 0) if kind == "bool_or" else (trues == n)
        return [out(r, n == 0)]
    if kind == "approx_distinct":
        import jax

        live = ~nulls
        reg, rank = _hll_reg_rank(vals)
        # rows re-sorted by (gid, register, rank desc); group runs stay
        # contiguous (gid is the primary key), so the original
        # starts/ends still delimit them. Dead rows sort to register M.
        reg_s = jnp.where(live, reg, _HLL_M)
        s_ops = jax.lax.sort((gid, reg_s, -rank, rank, live),
                             num_keys=3, is_stable=False)
        sgid, sreg, _nr, srank, slive = s_ops
        first = jnp.roll(sgid, 1) != sgid
        first = first | (jnp.roll(sreg, 1) != sreg)
        first = first.at[0].set(True)
        first = first & slive
        contrib = jnp.where(first, jnp.exp2(-srank.astype(jnp.float64)),
                            0.0)
        present = pscan.segment_sums(contrib, starts, ends)
        distinct_regs = pscan.segment_sums(first.astype(jnp.int32),
                                           starts, ends)
        est = _hll_estimate(present, _HLL_M - distinct_regs)
        n = seg_count(live)
        # empty group => 0 (Presto approx_distinct over no rows)
        return [out(jnp.where(n == 0, 0,
                              jnp.round(est)).astype(jnp.int64),
                    jnp.zeros_like(out_valid))]
    if kind == "approx_percentile":
        import jax

        from presto_tpu.ops.keys import _orderable_values

        frac = float(a.param if a.param is not None else 0.5)
        v = _orderable_values(Column(vals, nulls, sp.columns[a.field].type,
                                     dictionary))
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        s_ops = jax.lax.sort((gid, nulls.astype(jnp.int8), v, vals),
                             num_keys=3, is_stable=False)
        svals = s_ops[3]
        n = seg_count(~nulls)
        # lower nearest-rank: the element at floor(p * (n-1)) of the
        # group's sorted non-null run (approx contract; exact quantile)
        idx = starts + jnp.floor(
            frac * jnp.maximum(n - 1, 0).astype(jnp.float64)
        ).astype(jnp.int32)
        picked = jnp.take(svals, jnp.clip(idx, 0, sp.capacity - 1),
                          mode="clip")
        return [out(picked, n == 0)]
    raise NotImplementedError(f"aggregate {kind}")
