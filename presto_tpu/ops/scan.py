"""Blocked scan primitives.

On this TPU stack a 1-D `jnp.cumsum` over a 16M-element array takes
minutes to *compile* (XLA unrolls the log-N scan over one huge dimension)
and scatter-adds serialize per colliding index (~1.6 s for 16M->64k), so
neither is usable as a segment-reduction mechanism. These helpers reshape
to [blocks, lane] and scan hierarchically: an intra-block scan over the
small trailing axis (a handful of shifted adds the compiler handles well),
a tiny scan over per-block totals, and a broadcast combine. Compiles in
seconds, runs at memory bandwidth.

Reference role: these stand in for the sequential accumulator loops inside
the reference's operators (e.g. cumulative counts in
presto-main-base/.../operator/GroupByIdBlock / window frame offsets) —
re-expressed as data-parallel scans.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


_LANE = 2048  # trailing-axis width; power of two, fits VMEM comfortably


def _pad_to_blocks(x: jnp.ndarray):
    n = x.shape[0]
    blocks = max(1, (n + _LANE - 1) // _LANE)
    pad = blocks * _LANE - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), dtype=x.dtype)])
    return x.reshape(blocks, _LANE), n


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive 1-D cumulative sum, blocked. Same result as jnp.cumsum."""
    x2, n = _pad_to_blocks(x)
    within = jnp.cumsum(x2, axis=1)                 # [B, LANE]
    totals = within[:, -1]                          # [B]
    offsets = jnp.cumsum(totals) - totals           # exclusive block prefix
    out = within + offsets[:, None]
    return out.reshape(-1)[:n]


def fill_forward(vals: jnp.ndarray, present: jnp.ndarray,
                 init=None):
    """Per-slot last `present` value at or before the slot (blocked
    fill-forward scan). Slots before the first present value get `init`
    (default: the dtype's zero). The merge-join propagation primitive:
    after co-sorting build rows ahead of probe rows per key, every probe
    slot reads its candidate build row without any random gather."""
    import jax

    if init is None:
        init = jnp.zeros((), dtype=vals.dtype)
    x2, n = _pad_to_blocks(vals)
    p2, _ = _pad_to_blocks(present.astype(jnp.int8))
    p2 = p2.astype(bool)

    def op(a, b):
        av, ap = a
        bv, bp = b
        return jnp.where(bp, bv, av), ap | bp

    within_v, within_p = jax.lax.associative_scan(op, (x2, p2), axis=1)
    blk_v, blk_p = within_v[:, -1], within_p[:, -1]
    pre_v, pre_p = jax.lax.associative_scan(op, (blk_v, blk_p), axis=0)
    # exclusive block prefix
    pre_v = jnp.concatenate([jnp.full((1,), init, vals.dtype), pre_v[:-1]])
    pre_p = jnp.concatenate([jnp.zeros((1,), bool), pre_p[:-1]])
    out = jnp.where(within_p, within_v,
                    jnp.where(pre_p[:, None], pre_v[:, None], init))
    return out.reshape(-1)[:n]


def seg_scan(vals: jnp.ndarray, seg_start: jnp.ndarray, binop,
             ident) -> jnp.ndarray:
    """Inclusive segmented scan: out[i] = binop-fold of vals over
    [start_of_segment(i), i], where True in `seg_start` begins a new
    segment. Blocked like cumsum/fill_forward (intra-block associative
    scan + block-total scan + combine). `ident` is binop's identity
    (used for padding and pre-first-segment slots). The running min/max
    window-frame primitive."""
    import jax

    n0 = vals.shape[0]
    blocks = max(1, (n0 + _LANE - 1) // _LANE)
    pad = blocks * _LANE - n0
    if pad:
        vals = jnp.concatenate(
            [vals, jnp.full((pad,), ident, vals.dtype)])
        seg_start = jnp.concatenate(
            [seg_start, jnp.zeros((pad,), bool)])
    x2 = vals.reshape(blocks, _LANE)
    f2 = seg_start.reshape(blocks, _LANE)

    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, binop(av, bv)), af | bf

    wv, wf = jax.lax.associative_scan(op, (x2, f2), axis=1)
    pv, pf = jax.lax.associative_scan(op, (wv[:, -1], wf[:, -1]), axis=0)
    # exclusive block prefix
    pv = jnp.concatenate([jnp.full((1,), ident, vals.dtype), pv[:-1]])
    out = jnp.where(wf, wv, binop(pv[:, None], wv))
    return out.reshape(-1)[:n0]


def fill_backward(vals: jnp.ndarray, present: jnp.ndarray, init=None):
    """Per-slot next `present` value at or after the slot (reversed
    fill_forward; flips lower to strided slices, not gathers)."""
    rev = lambda a: jnp.flip(a, axis=0)          # noqa: E731
    return rev(fill_forward(rev(vals), rev(present), init))


def segment_sums(vals: jnp.ndarray, starts: jnp.ndarray,
                 ends: jnp.ndarray) -> jnp.ndarray:
    """Per-segment sums over *contiguous* segments (rows pre-sorted by
    group). starts/ends are [G] row ranges per segment (end exclusive).
    Uses one blocked cumsum + two small gathers — no scatter."""
    acc = (jnp.float64 if jnp.issubdtype(vals.dtype, jnp.floating)
           else jnp.int64)
    cs = cumsum(vals.astype(acc))
    cap = vals.shape[0]
    hi = jnp.take(cs, jnp.clip(ends - 1, 0, cap - 1), mode="clip")
    lo = jnp.where(starts > 0,
                   jnp.take(cs, jnp.clip(starts - 1, 0, cap - 1),
                            mode="clip"),
                   jnp.zeros((), dtype=acc))
    return jnp.where(ends > starts, hi - lo, 0)


def group_starts(flags: jnp.ndarray, gvalid: jnp.ndarray, out_cap: int):
    """Given sorted new-group flags + per-row validity, return
    (starts[out_cap], gid[rows]) where starts[g] is the first row of
    group g and invalid rows map to the overflow bin gid == out_cap.

    Implemented with one small multi-operand sort over row indices: rows
    that start a group sort first by group id, giving the start offsets
    densely — no scatter, no big searchsorted."""
    cap = flags.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    live_flag = flags & gvalid
    gid = cumsum(live_flag.astype(jnp.int32)) - 1
    gid = jnp.where(gvalid, gid, out_cap)
    # Sort group-start rows to the front, ordered by gid (== row order).
    key = jnp.where(live_flag, idx, cap + idx)
    import jax.lax
    _key, starts_sorted = jax.lax.sort((key, idx), num_keys=1)
    starts = starts_sorted[:out_cap]
    return starts, gid
