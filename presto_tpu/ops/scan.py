"""Blocked scan primitives.

On this TPU stack a 1-D `jnp.cumsum` over a 16M-element array takes
minutes to *compile* (XLA unrolls the log-N scan over one huge dimension)
and scatter-adds serialize per colliding index (~1.6 s for 16M->64k), so
neither is usable as a segment-reduction mechanism. These helpers reshape
to [blocks, lane] and scan hierarchically: an intra-block scan over the
small trailing axis (a handful of shifted adds the compiler handles well),
a tiny scan over per-block totals, and a broadcast combine. Compiles in
seconds, runs at memory bandwidth.

Reference role: these stand in for the sequential accumulator loops inside
the reference's operators (e.g. cumulative counts in
presto-main-base/.../operator/GroupByIdBlock / window frame offsets) —
re-expressed as data-parallel scans.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


_LANE = 2048  # trailing-axis width; power of two, fits VMEM comfortably


def _pad_to_blocks(x: jnp.ndarray):
    n = x.shape[0]
    blocks = max(1, (n + _LANE - 1) // _LANE)
    pad = blocks * _LANE - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), dtype=x.dtype)])
    return x.reshape(blocks, _LANE), n


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive 1-D cumulative sum, blocked. Same result as jnp.cumsum."""
    x2, n = _pad_to_blocks(x)
    within = jnp.cumsum(x2, axis=1)                 # [B, LANE]
    totals = within[:, -1]                          # [B]
    offsets = jnp.cumsum(totals) - totals           # exclusive block prefix
    out = within + offsets[:, None]
    return out.reshape(-1)[:n]


def _cummax_1d_doubling(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive running max of a SMALL 1-D array via Hillis-Steele
    doubling (log n shifted-max steps — plain elementwise ops, never
    lax.associative_scan, whose custom-op lowering takes tens of
    minutes to compile through the remote TPU compile service)."""
    n = x.shape[0]
    lo = jnp.full((1,), jnp.iinfo(x.dtype).min, x.dtype)
    d = 1
    while d < n:
        pad = jnp.broadcast_to(lo, (d,))
        x = jnp.maximum(x, jnp.concatenate([pad, x[:-d]]))
        d *= 2
    return x


def blocked_cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive running max, blocked: doubling scan over the [B, LANE]
    trailing axis + tiny block-prefix scan + combine."""
    x2, n = _pad_to_blocks(x)
    lo = jnp.iinfo(x.dtype).min
    if n < x2.size:                 # padding must not win the max
        flat = x2.reshape(-1)
        flat = jnp.where(jnp.arange(flat.shape[0]) < n, flat, lo)
        x2 = flat.reshape(x2.shape)
    within = x2
    d = 1
    while d < _LANE:
        shifted = jnp.concatenate(
            [jnp.full((within.shape[0], d), lo, within.dtype),
             within[:, :-d]], axis=1)
        within = jnp.maximum(within, shifted)
        d *= 2
    totals = within[:, -1]
    pre = _cummax_1d_doubling(totals)
    pre = jnp.concatenate([jnp.full((1,), lo, x.dtype), pre[:-1]])
    return jnp.maximum(within, pre[:, None]).reshape(-1)[:n]


def fill_forward(vals: jnp.ndarray, present: jnp.ndarray,
                 init=None):
    """Per-slot last `present` value at or before the slot. Slots before
    the first present value get `init` (default: the dtype's zero). The
    merge-join propagation primitive.

    Implemented as a blocked running-max of present POSITIONS + one
    gather (never a value-carrying associative_scan: its custom-op
    lowering compiles pathologically on this stack, and gathers run at
    memory bandwidth)."""
    if init is None:
        init = jnp.zeros((), dtype=vals.dtype)
    n = vals.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    pos = jnp.where(present, idx, jnp.int32(-1))
    last = blocked_cummax(pos)
    out = jnp.take(vals, jnp.clip(last, 0, n - 1), mode="clip")
    return jnp.where(last >= 0, out, jnp.asarray(init, vals.dtype))


def seg_scan(vals: jnp.ndarray, seg_start: jnp.ndarray, binop,
             ident) -> jnp.ndarray:
    """Inclusive segmented scan: out[i] = binop-fold of vals over
    [start_of_segment(i), i], where True in `seg_start` begins a new
    segment. `ident` is binop's identity (used for padding and
    pre-first-segment slots). The running min/max window-frame
    primitive.

    Hillis-Steele doubling over the blocked [B, LANE] layout — plain
    shifted elementwise steps, never lax.associative_scan (its
    custom-op lowering compiles pathologically on this stack)."""
    n0 = vals.shape[0]
    blocks = max(1, (n0 + _LANE - 1) // _LANE)
    pad = blocks * _LANE - n0
    if pad:
        vals = jnp.concatenate(
            [vals, jnp.full((pad,), ident, vals.dtype)])
        seg_start = jnp.concatenate(
            [seg_start, jnp.zeros((pad,), bool)])
    v = vals.reshape(blocks, _LANE)
    f = seg_start.reshape(blocks, _LANE)

    # segmented doubling along the lane axis: fold in the value d slots
    # left unless a segment boundary lies in between (the or-accumulated
    # flag blocks propagation across starts)
    d = 1
    while d < _LANE:
        v_sh = jnp.concatenate(
            [jnp.full((blocks, d), ident, v.dtype), v[:, :-d]], axis=1)
        f_sh = jnp.concatenate(
            [jnp.zeros((blocks, d), bool), f[:, :-d]], axis=1)
        v = jnp.where(f, v, binop(v, v_sh))
        f = f | f_sh
        d *= 2
    # tiny exclusive prefix over the per-block (total, has-boundary)
    bv, bf = v[:, -1], f[:, -1]
    db = 1
    while db < blocks:
        bv_sh = jnp.concatenate(
            [jnp.full((db,), ident, bv.dtype), bv[:-db]])
        bf_sh = jnp.concatenate([jnp.zeros((db,), bool), bf[:-db]])
        bv = jnp.where(bf, bv, binop(bv, bv_sh))
        bf = bf | bf_sh
        db *= 2
    pv = jnp.concatenate([jnp.full((1,), ident, bv.dtype), bv[:-1]])
    out = jnp.where(f, v, binop(pv[:, None], v))
    return out.reshape(-1)[:n0]


def fill_backward(vals: jnp.ndarray, present: jnp.ndarray, init=None):
    """Per-slot next `present` value at or after the slot (reversed
    fill_forward; flips lower to strided slices, not gathers)."""
    rev = lambda a: jnp.flip(a, axis=0)          # noqa: E731
    return rev(fill_forward(rev(vals), rev(present), init))


def segment_sums(vals: jnp.ndarray, starts: jnp.ndarray,
                 ends: jnp.ndarray) -> jnp.ndarray:
    """Per-segment sums over *contiguous* segments (rows pre-sorted by
    group). starts/ends are [G] row ranges per segment (end exclusive).
    Uses one blocked cumsum + two small gathers — no scatter."""
    acc = (jnp.float64 if jnp.issubdtype(vals.dtype, jnp.floating)
           else jnp.int64)
    cs = cumsum(vals.astype(acc))
    cap = vals.shape[0]
    hi = jnp.take(cs, jnp.clip(ends - 1, 0, cap - 1), mode="clip")
    lo = jnp.where(starts > 0,
                   jnp.take(cs, jnp.clip(starts - 1, 0, cap - 1),
                            mode="clip"),
                   jnp.zeros((), dtype=acc))
    return jnp.where(ends > starts, hi - lo, 0)


def group_starts(flags: jnp.ndarray, gvalid: jnp.ndarray, out_cap: int):
    """Given sorted new-group flags + per-row validity, return
    (starts[out_cap], gid[rows]) where starts[g] is the first row of
    group g and invalid rows map to the overflow bin gid == out_cap.

    Implemented with one small multi-operand sort over row indices: rows
    that start a group sort first by group id, giving the start offsets
    densely — no scatter, no big searchsorted."""
    cap = flags.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    live_flag = flags & gvalid
    gid = cumsum(live_flag.astype(jnp.int32)) - 1
    gid = jnp.where(gvalid, gid, out_cap)
    # Sort group-start rows to the front, ordered by gid (== row order).
    key = jnp.where(live_flag, idx, cap + idx)
    import jax.lax
    _key, starts_sorted = jax.lax.sort((key, idx), num_keys=1)
    starts = starts_sorted[:out_cap]
    if cap < out_cap:
        # the page has fewer rows than the requested group capacity
        # (per-device shards of a plan whose group estimate was sized
        # for the whole table): pad with `cap` so the contract
        # starts[out_cap] holds — padded bins are masked invalid by the
        # caller's out_valid and their segments are empty
        starts = jnp.concatenate(
            [starts, jnp.full((out_cap - cap,), cap, jnp.int32)])
    return starts, gid
