"""Airlift-layout HyperLogLog sketches (cross-engine approx_distinct).

Reference role: com.facebook.airlift.stats.cardinality.{HyperLogLog,
DenseHll, SparseHll} — the serialized form Presto ships between engines
for approx_distinct partial states
(presto-main-base/.../aggregation/ApproximateCountDistinctAggregation.java
merges partials with HyperLogLog.deserialize/mergeWith). This module
implements that wire layout from its public specification so partials
can cross an engine boundary; estimation stays engine-local.

Wire layout (all little-endian, airlift Slice convention):

  DENSE_V2 (tag 3):
      byte    tag = 3
      byte    indexBitLength p            (buckets m = 2^p)
      byte    baseline                    (min bucket value)
      byte[m/2] deltas                    4-bit (value - baseline) per
                                          bucket; bucket i lives in
                                          deltas[i>>1], even i = HIGH
                                          nibble, odd i = low nibble
                                          (airlift DenseHll
                                          shiftForBucket)
      short   overflowEntries             count of buckets whose delta
                                          exceeds 15
      short[overflowEntries] overflowBucket indexes
      byte[overflowEntries]  overflowValue  (delta - 15 excess)

  SPARSE_V2 (tag 2):
      byte    tag = 2
      byte    indexBitLength p
      short   numberOfEntries
      int[numberOfEntries] entries        sorted; each entry packs the
                                          top 26 bits of the 64-bit
                                          hash and, in the low 6 bits,
                                          the number of leading zeros
                                          AFTER that 26-bit prefix
                                          (airlift SparseHll: value
                                          computed at
                                          EXTENDED_PREFIX_BITS, so
                                          promotion to any p can
                                          reconstruct the register)

Hashing: Murmur3 x64 128's first word (airlift Murmur3Hash128.hash64,
seed 0) over the value's 8-byte two's-complement (BIGINT) or UTF-8
(VARCHAR) representation; bucket index = top p bits of the hash, bucket
value = number of leading zeros of the remaining bits + 1 (capped so it
fits 6 bits).
"""

import struct
from typing import Optional

import numpy as np

TAG_SPARSE_V2 = 2
TAG_DENSE_V2 = 3
MAX_DELTA = 15
VALUE_BITS = 6
_M64 = (1 << 64) - 1

_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _fmix64(x: int) -> int:
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _M64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _M64
    x ^= x >> 33
    return x


def murmur3_hash64_bytes(data: bytes, seed: int = 0) -> int:
    """Murmur3 x64 128, first 64-bit word (Murmur3Hash128.hash64)."""
    h1 = seed
    h2 = seed
    length = len(data)
    n_blocks = length // 16
    for i in range(n_blocks):
        k1, k2 = struct.unpack_from("<qq", data, i * 16)
        k1 &= _M64
        k2 &= _M64
        k1 = (k1 * _C1) & _M64
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M64
        h1 ^= k1
        h1 = _rotl(h1, 27)
        h1 = (h1 + h2) & _M64
        h1 = (h1 * 5 + 0x52DCE729) & _M64
        k2 = (k2 * _C2) & _M64
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M64
        h2 ^= k2
        h2 = _rotl(h2, 31)
        h2 = (h2 + h1) & _M64
        h2 = (h2 * 5 + 0x38495AB5) & _M64
    tail = data[n_blocks * 16:]
    k1 = 0
    k2 = 0
    for i in range(len(tail) - 1, 7, -1):
        k2 = (k2 << 8) | tail[i]
    for i in range(min(len(tail), 8) - 1, -1, -1):
        k1 = (k1 << 8) | tail[i]
    if len(tail) > 8:
        k2 = (k2 * _C2) & _M64
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M64
        h2 ^= k2
    if len(tail) > 0:
        k1 = (k1 * _C1) & _M64
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M64
        h1 ^= k1
    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _M64
    return h1


def murmur3_hash64_long(value: int) -> int:
    """hash64 of a BIGINT: its 8-byte little-endian representation."""
    return murmur3_hash64_bytes(struct.pack("<q", value))


def _index_and_value(hash64: int, p: int):
    """bucket = top p bits; value = leading zeros of the remaining
    (64 - p) bits + 1, capped to fit VALUE_BITS."""
    index = hash64 >> (64 - p)
    rest = (hash64 << p) & _M64
    # leading zeros of `rest` within 64 bits, guarded so an all-zero
    # suffix yields the max value
    if rest == 0:
        value = 64 - p + 1
    else:
        value = 65 - rest.bit_length()
    return index, min(value, (1 << VALUE_BITS) - 1)


class DenseHll:
    """Dense register file + airlift DENSE_V2 serialization."""

    def __init__(self, index_bit_length: int,
                 registers: Optional[np.ndarray] = None):
        if not (1 <= index_bit_length <= 16):
            raise ValueError(f"indexBitLength {index_bit_length}")
        self.p = index_bit_length
        m = 1 << index_bit_length
        self.registers = (np.zeros(m, dtype=np.uint8) if registers is None
                          else registers.astype(np.uint8))

    @property
    def num_buckets(self) -> int:
        return 1 << self.p

    def insert_hash(self, h: int) -> None:
        idx, val = _index_and_value(h & _M64, self.p)
        if val > self.registers[idx]:
            self.registers[idx] = val

    def add_long(self, v: int) -> None:
        self.insert_hash(murmur3_hash64_long(v))

    def add_bytes(self, b: bytes) -> None:
        self.insert_hash(murmur3_hash64_bytes(b))

    def merge(self, other: "DenseHll") -> "DenseHll":
        if other.p != self.p:
            raise ValueError(
                f"cannot merge HLLs with different indexBitLength "
                f"({self.p} vs {other.p})")
        np.maximum(self.registers, other.registers, out=self.registers)
        return self

    def cardinality(self) -> int:
        m = float(self.num_buckets)
        regs = self.registers.astype(np.float64)
        zeros = int(np.sum(regs == 0))
        if zeros:
            linear = m * np.log(m / zeros)
            if linear <= 2.5 * m:
                return int(round(linear))
        alpha = 0.7213 / (1 + 1.079 / m)
        raw = alpha * m * m / float(np.sum(np.exp2(-regs)))
        return int(round(raw))

    # ---- serialization ------------------------------------------------
    def serialize(self) -> bytes:
        baseline = int(self.registers.min())
        deltas_full = self.registers.astype(np.int32) - baseline
        overflow_idx = np.nonzero(deltas_full > MAX_DELTA)[0]
        nibbles = np.minimum(deltas_full, MAX_DELTA).astype(np.uint8)
        # even buckets take the HIGH nibble (airlift shiftForBucket:
        # shift = ((~bucket) & 1) << 2)
        packed = ((nibbles[0::2] << 4) | nibbles[1::2]).astype(np.uint8)
        out = bytearray()
        out += struct.pack("<BBB", TAG_DENSE_V2, self.p, baseline)
        out += packed.tobytes()
        out += struct.pack("<H", len(overflow_idx))
        for b in overflow_idx:
            out += struct.pack("<H", int(b))
        for b in overflow_idx:
            out += struct.pack("<B", int(deltas_full[b]) - MAX_DELTA)
        return bytes(out)

    @staticmethod
    def deserialize(data: bytes) -> "DenseHll":
        tag, p, baseline = struct.unpack_from("<BBB", data, 0)
        if tag != TAG_DENSE_V2:
            raise ValueError(f"not a DENSE_V2 sketch (tag {tag})")
        m = 1 << p
        off = 3
        packed = np.frombuffer(data, dtype=np.uint8, count=m // 2,
                               offset=off)
        off += m // 2
        regs = np.zeros(m, dtype=np.int32)
        regs[0::2] = packed >> 4
        regs[1::2] = packed & 0xF
        (n_over,) = struct.unpack_from("<H", data, off)
        off += 2
        buckets = struct.unpack_from(f"<{n_over}H", data, off)
        off += 2 * n_over
        values = struct.unpack_from(f"<{n_over}B", data, off)
        for b, v in zip(buckets, values):
            regs[b] += v
        regs += baseline
        return DenseHll(p, regs.astype(np.uint8))


class SparseHll:
    """Sparse entry list + airlift SPARSE_V2 serialization. Entries
    keep the top 26 bits of the hash plus, in the low 6 bits, the
    number of leading zeros AFTER that prefix (airlift SparseHll's
    value at EXTENDED_PREFIX_BITS) — so a sparse sketch can promote to
    dense at any p <= 26 - VALUE_BITS by reconstructing the register
    value from prefix bits below p plus the stored zero count."""

    ENTRY_HASH_BITS = 26

    def __init__(self, index_bit_length: int, entries=None):
        self.p = index_bit_length
        self.entries = set(entries or ())

    def insert_hash(self, h: int) -> None:
        h &= _M64
        prefix = h >> (64 - self.ENTRY_HASH_BITS)
        # zeros after the 26-bit prefix, with airlift's implicit guard
        # bit: an all-zero suffix counts 64 - 26 = 38 zeros (fits 6
        # bits), NOT the value at this sketch's own p
        rest = (h << self.ENTRY_HASH_BITS) & _M64
        zeros = (64 - rest.bit_length()) if rest \
            else (64 - self.ENTRY_HASH_BITS)
        self.entries.add((prefix << VALUE_BITS) | zeros)

    def add_long(self, v: int) -> None:
        self.insert_hash(murmur3_hash64_long(v))

    def add_bytes(self, b: bytes) -> None:
        self.insert_hash(murmur3_hash64_bytes(b))

    def to_dense(self) -> DenseHll:
        """Promote by reconstructing each register value at p from the
        entry (airlift SparseHll.toDense decodeBucketValue): the
        (26 - p) prefix bits below the bucket index lead the suffix;
        only when they are all zero does the stored zero count extend
        the run."""
        d = DenseHll(self.p)
        low_bits = self.ENTRY_HASH_BITS - self.p
        for e in self.entries:
            prefix = e >> VALUE_BITS
            zeros = e & ((1 << VALUE_BITS) - 1)
            idx = prefix >> low_bits
            low = prefix & ((1 << low_bits) - 1)
            if low:
                val = low_bits - low.bit_length() + 1
            else:
                val = low_bits + zeros + 1
            val = min(val, (1 << VALUE_BITS) - 1)
            if val > d.registers[idx]:
                d.registers[idx] = val
        return d

    def cardinality(self) -> int:
        # linear counting over the 26-bit prefix space (distinct
        # prefixes are a near-perfect distinct count at sparse sizes)
        m = float(1 << self.ENTRY_HASH_BITS)
        distinct = len({e >> VALUE_BITS for e in self.entries})
        if distinct == 0:
            return 0
        return int(round(m * np.log(m / (m - distinct))))

    def serialize(self) -> bytes:
        out = bytearray()
        out += struct.pack("<BBH", TAG_SPARSE_V2, self.p,
                           len(self.entries))
        for e in sorted(self.entries):
            out += struct.pack("<I", e)
        return bytes(out)

    @staticmethod
    def deserialize(data: bytes) -> "SparseHll":
        tag, p, n = struct.unpack_from("<BBH", data, 0)
        if tag != TAG_SPARSE_V2:
            raise ValueError(f"not a SPARSE_V2 sketch (tag {tag})")
        entries = struct.unpack_from(f"<{n}I", data, 4)
        return SparseHll(p, entries)


def deserialize(data: bytes):
    """Tag-dispatched deserialization (HyperLogLog.newInstance role)."""
    tag = data[0]
    if tag == TAG_DENSE_V2:
        return DenseHll.deserialize(data)
    if tag == TAG_SPARSE_V2:
        return SparseHll.deserialize(data)
    raise ValueError(f"unsupported HLL format tag {tag}")


def merge_serialized(a: bytes, b: bytes) -> bytes:
    """Merge two serialized sketches (MergeHyperLogLogAggregation
    role); result serializes dense."""
    x = deserialize(a)
    y = deserialize(b)
    if isinstance(x, SparseHll):
        x = x.to_dense()
    if isinstance(y, SparseHll):
        y = y.to_dense()
    return x.merge(y).serialize()
