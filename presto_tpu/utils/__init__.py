from presto_tpu.utils.tracing import (
    EVENTS, TRACER, EventListenerManager, QueryEvent, Span, Tracer,
)

__all__ = ["EVENTS", "TRACER", "EventListenerManager", "QueryEvent",
           "Span", "Tracer"]
from presto_tpu.utils.verifier import (  # noqa: E402
    ColumnChecksum, VerificationResult, Verifier,
)

__all__ += ["ColumnChecksum", "VerificationResult", "Verifier"]
