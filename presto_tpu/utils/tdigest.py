"""Mergeable t-digest quantile sketches, reference wire layout.

Reference role: presto-main-base/.../tdigest/TDigest.java — the
mergeable quantile sketch behind approx_percentile's cross-engine
partial states (this engine's in-fragment approx_percentile stays the
exact sorted-run quantile, which dominates on-device; this module is
the interchange form so partials can cross an engine boundary).

Algorithm: Dunning's merging t-digest (public design): incoming values
buffer, and compression merge-sorts buffered values with existing
centroids, closing a centroid whenever the k-scale budget
k(q) = (delta / (2 pi)) * asin(2q - 1) advances by one unit — small
centroids at the distribution tails, big ones in the middle, which is
what bounds relative quantile error at the extremes.

Wire layout (little-endian, matching TDigest.java serialize()):
    byte    version (1)
    byte    value type (0 = double)
    double  min, max, sum, compression, totalWeight
    int     activeCentroids
    double[activeCentroids] weights
    double[activeCentroids] means
Version-0 frames (no `sum` field) deserialize too.
"""

import math
import struct
from typing import List, Optional, Tuple

_BUFFER = 5


class TDigest:
    def __init__(self, compression: float = 100.0):
        if compression < 10:
            compression = 10.0
        self.compression = float(compression)
        self.min = math.inf
        self.max = -math.inf
        self.sum = 0.0
        self.total_weight = 0.0
        self._centroids: List[Tuple[float, float]] = []  # (mean, weight)
        self._buffer: List[Tuple[float, float]] = []

    # ------------------------------------------------------------ build
    def add(self, value: float, weight: float = 1.0) -> None:
        if math.isnan(value):
            raise ValueError("cannot add NaN to t-digest")
        self._buffer.append((float(value), float(weight)))
        self.sum += value * weight
        self.total_weight += weight
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if len(self._buffer) >= _BUFFER * self.compression:
            self._compress()

    def merge(self, other: "TDigest") -> "TDigest":
        other._compress()
        for mean, w in other._centroids:
            self._buffer.append((mean, w))
        self.sum += other.sum
        self.total_weight += other.total_weight
        if other.total_weight:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        self._compress()
        return self

    def _k(self, q: float) -> float:
        q = min(max(q, 0.0), 1.0)
        return self.compression / (2 * math.pi) * math.asin(2 * q - 1)

    def _compress(self) -> None:
        if not self._buffer:
            return
        pts = sorted(self._centroids + self._buffer)
        self._buffer = []
        total = sum(w for _m, w in pts)
        out: List[Tuple[float, float]] = []
        cur_m, cur_w = pts[0]
        seen = 0.0
        k_lo = self._k(0.0)
        for mean, w in pts[1:]:
            q_next = (seen + cur_w + w) / total
            if self._k(q_next) - k_lo <= 1.0:
                # merge into the open centroid (weighted mean)
                cur_m = (cur_m * cur_w + mean * w) / (cur_w + w)
                cur_w += w
            else:
                out.append((cur_m, cur_w))
                seen += cur_w
                k_lo = self._k(seen / total)
                cur_m, cur_w = mean, w
        out.append((cur_m, cur_w))
        self._centroids = out

    # ------------------------------------------------------------ query
    def quantile(self, q: float) -> Optional[float]:
        """value_at_quantile semantics: interpolated between centroid
        means, clamped by the exact observed min/max."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"quantile must be in [0, 1]: {q}")
        self._compress()
        if not self._centroids:
            return None
        if q <= 0:
            return self.min
        if q >= 1:
            return self.max
        target = q * self.total_weight
        seen = 0.0
        prev_mean, prev_mid = self.min, 0.0
        for mean, w in self._centroids:
            # a heavy centroid owns its interior: a target inside its
            # mass (beyond the half-unit shared with each neighbor)
            # returns the mean exactly (Dunning's singleton rule, which
            # keeps e.g. a 97-weight centroid's median at its mean)
            if w > 1 and seen + 0.5 <= target <= seen + w - 0.5:
                return mean
            mid = seen + w / 2.0
            if target < mid:
                if mid == prev_mid:
                    return mean
                f = (target - prev_mid) / (mid - prev_mid)
                return prev_mean + f * (mean - prev_mean)
            prev_mean, prev_mid = mean, mid
            seen += w
        f_last = self._centroids[-1]
        span = self.total_weight - prev_mid
        if span <= 0:
            return f_last[0]
        f = (target - prev_mid) / span
        return prev_mean + f * (self.max - prev_mean)

    def centroid_count(self) -> int:
        self._compress()
        return len(self._centroids)

    # -------------------------------------------------------------- wire
    def serialize(self) -> bytes:
        self._compress()
        out = bytearray()
        out += struct.pack("<bb", 1, 0)
        out += struct.pack("<ddddd", self.min, self.max, self.sum,
                           self.compression, self.total_weight)
        out += struct.pack("<i", len(self._centroids))
        for _m, w in self._centroids:
            out += struct.pack("<d", w)
        for m, _w in self._centroids:
            out += struct.pack("<d", m)
        return bytes(out)

    @staticmethod
    def deserialize(data: bytes) -> "TDigest":
        version, vtype = struct.unpack_from("<bb", data, 0)
        if version not in (0, 1):
            raise ValueError(f"bad t-digest version {version}")
        if vtype != 0:
            raise ValueError(f"unsupported t-digest value type {vtype}")
        off = 2
        mn, mx = struct.unpack_from("<dd", data, off)
        off += 16
        s = 0.0
        if version == 1:
            (s,) = struct.unpack_from("<d", data, off)
            off += 8
        comp, total = struct.unpack_from("<dd", data, off)
        off += 16
        (n,) = struct.unpack_from("<i", data, off)
        off += 4
        weights = struct.unpack_from(f"<{n}d", data, off)
        off += 8 * n
        means = struct.unpack_from(f"<{n}d", data, off)
        d = TDigest(max(comp, 10.0))
        d.min, d.max, d.sum, d.total_weight = mn, mx, s, total
        d._centroids = [(m, w) for m, w in zip(means, weights)]
        for m, w in d._centroids:
            if math.isnan(m) or w <= 0:
                raise ValueError("corrupt t-digest frame")
        return d


def merge_serialized(frames) -> bytes:
    """merge_tdigest aggregation role: fold serialized partials."""
    frames = list(frames)
    if not frames:
        raise ValueError("nothing to merge")
    acc = TDigest.deserialize(frames[0])
    for f in frames[1:]:
        acc.merge(TDigest.deserialize(f))
    return acc.serialize()
