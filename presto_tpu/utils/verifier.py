"""Verifier — A/B query verification between two engines.

Reference: presto-verifier (framework/AbstractVerification.java:74 +
checksum/): replay queries against a *control* and a *test* engine and
compare per-column checksums rather than raw row dumps, with
floating-point tolerance and row-count checks; emit a structured
VerificationResult per query.

Here the two engines are any objects with `execute_sql` +`plan_sql`
(LocalEngine / DistEngine / TpuCluster), which is exactly how the
reference verifies the C++ worker against the Java engine — and how this
framework pins its distributed paths against the single-device engine."""

from __future__ import annotations

import dataclasses
import math
import time
import zlib
from typing import List, Optional, Sequence


@dataclasses.dataclass
class ColumnChecksum:
    """Per-column order-insensitive checksum (reference:
    checksum/ChecksumValidator's per-type column checksums).

    `checksum` is the SUM (mod 2^64) of per-value crcs — additive, so
    even multiplicities cannot cancel (XOR would report
    crc(x)^crc(x) == crc(y)^crc(y)). Floats have no exact checksum
    (cross-engine rounding); they compare by first AND second moments
    (sum + sum of squares) so equal-sum different multisets like
    [2, 0] vs [1, 1] still mismatch. Numeric columns carry BOTH forms so
    an int column verifies tolerantly against a float column (engines
    may widen types differently)."""
    count: int
    null_count: int
    checksum: Optional[int]
    float_sum: Optional[float]
    float_sum_sq: Optional[float]

    def matches(self, other: "ColumnChecksum",
                rel_tol: float = 1e-6) -> bool:
        if (self.count, self.null_count) != (other.count,
                                             other.null_count):
            return False
        if self.checksum is not None and other.checksum is not None:
            return self.checksum == other.checksum
        if self.float_sum is None or other.float_sum is None:
            return False           # numeric vs non-numeric: structural
        return (math.isclose(self.float_sum, other.float_sum,
                             rel_tol=rel_tol, abs_tol=rel_tol)
                and math.isclose(self.float_sum_sq, other.float_sum_sq,
                                 rel_tol=rel_tol, abs_tol=rel_tol))


def column_checksums(rows: Sequence[tuple]) -> List[ColumnChecksum]:
    if not rows:
        return []
    ncol = len(rows[0])
    out = []
    for c in range(ncol):
        vals = [r[c] for r in rows]
        nulls = sum(1 for v in vals if v is None)
        live = [v for v in vals if v is not None]
        is_float = any(isinstance(v, float) for v in live)
        numeric = live and all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in live)
        fs = fss = None
        if numeric:
            fs = float(sum(live))
            fss = float(sum(v * v for v in live))
        if is_float:
            out.append(ColumnChecksum(len(vals), nulls, None, fs, fss))
        else:
            x = 0
            for v in live:
                x = (x + zlib.crc32(repr(v).encode())) % (1 << 64)
            out.append(ColumnChecksum(len(vals), nulls, x, fs, fss))
    return out


@dataclasses.dataclass
class VerificationResult:
    sql: str
    status: str                   # MATCH | MISMATCH | CONTROL_FAILED |
    #                               TEST_FAILED
    control_rows: Optional[int] = None
    test_rows: Optional[int] = None
    control_s: Optional[float] = None
    test_s: Optional[float] = None
    detail: str = ""


class Verifier:
    def __init__(self, control, test, rel_tol: float = 1e-6):
        self.control = control
        self.test = test
        self.rel_tol = rel_tol

    def verify(self, sql: str) -> VerificationResult:
        try:
            t0 = time.time()
            control_rows = self.control.execute_sql(sql)
            control_s = time.time() - t0
        except Exception as e:    # noqa: BLE001 — reported, not raised
            return VerificationResult(sql, "CONTROL_FAILED",
                                      detail=str(e)[:500])
        try:
            t0 = time.time()
            test_rows = self.test.execute_sql(sql)
            test_s = time.time() - t0
        except Exception as e:    # noqa: BLE001 — reported, not raised
            return VerificationResult(
                sql, "TEST_FAILED", control_rows=len(control_rows),
                control_s=control_s, detail=str(e)[:500])

        r = VerificationResult(sql, "MATCH", len(control_rows),
                               len(test_rows), control_s, test_s)
        if len(control_rows) != len(test_rows):
            r.status = "MISMATCH"
            r.detail = f"row count {len(control_rows)} != {len(test_rows)}"
            return r
        # checksums are commutative sums — no sort needed
        a = column_checksums(control_rows)
        b = column_checksums(test_rows)
        if len(a) != len(b):
            r.status = "MISMATCH"
            r.detail = f"column count {len(a)} != {len(b)}"
            return r
        for i, (x, y) in enumerate(zip(a, b)):
            if not x.matches(y, self.rel_tol):
                r.status = "MISMATCH"
                r.detail = f"column {i} checksum mismatch ({x} vs {y})"
                return r
        return r

    def verify_suite(self, queries: Sequence[str]
                     ) -> List[VerificationResult]:
        return [self.verify(q) for q in queries]
