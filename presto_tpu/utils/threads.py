"""The one sanctioned thread-spawn helper.

Every background thread in the engine is spawned here (the
`thread-discipline` lint rule enforces it), so all of them are:

  - named ``presto-tpu-<role>-<purpose>-<seq>`` — a stuck-thread dump
    (`py-spy`, faulthandler, `threading.enumerate()`) attributes every
    thread to the subsystem that started it;
  - daemon-flagged uniformly (default True: engine threads must never
    keep a dying process alive — clean shutdown paths stop them
    explicitly via events/joins, not via interpreter refusal to exit).

`role` is the node role or subsystem (coordinator / worker / exchange /
exec); `purpose` says what this specific thread does (heartbeat,
task-run-3.0.0, fetch-2)."""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Optional

_seq = itertools.count()


def thread_name(role: str, purpose: str) -> str:
    return f"presto-tpu-{role}-{purpose}-{next(_seq)}"


def spawn(role: str, purpose: str, target: Callable, *,
          args: tuple = (), kwargs: Optional[dict] = None,
          daemon: bool = True, start: bool = True) -> threading.Thread:
    """Create (and by default start) a named daemon thread."""
    t = threading.Thread(target=target, args=args,
                         kwargs=kwargs or {},
                         name=thread_name(role, purpose),
                         daemon=daemon)
    if start:
        t.start()
    return t
