"""Tracing + query-event pipeline.

Reference roles:
  - spi/tracing/Tracer.java + TracerProvider (SURVEY.md §5.1): named
    spans with wall-time points, queryable per query. SimpleTracer's
    add-point/get-points surface, W3C-style nesting flattened to
    (name, start, end, attributes) records.
  - spi/eventlistener (QueryCreatedEvent / QueryCompletedEvent /
    SplitCompletedEvent -> eventlistener/EventListenerManager.java +
    event/QueryMonitor.java, SURVEY.md §5.5): registered listeners get
    lifecycle events with timing/stats payloads.

Engines call `tracer.span(...)` around phases (plan/lower/execute) and
`emit_query_event(...)` at lifecycle edges; listeners are plain
callables (the plugin surface collapsed to its functional core)."""

from __future__ import annotations

import dataclasses
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start


class Tracer:
    """Per-process tracer: spans grouped by trace id (query id). Bounded:
    only the most recent `max_traces` query traces are retained (the
    reference's QueryTracker similarly caps finished-query history)."""

    def __init__(self, max_traces: int = 256):
        self._lock = threading.Lock()
        self.max_traces = max_traces
        self.spans: Dict[str, List[Span]] = {}

    @contextmanager
    def span(self, trace_id: str, name: str, **attributes):
        s = Span(name, time.time(), attributes=dict(attributes))
        with self._lock:
            self.spans.setdefault(trace_id, []).append(s)
            while len(self.spans) > self.max_traces:
                self.spans.pop(next(iter(self.spans)))   # oldest insert
        try:
            yield s
        finally:
            s.end = time.time()

    def get(self, trace_id: str) -> List[Span]:
        with self._lock:
            return list(self.spans.get(trace_id, []))

    def render(self, trace_id: str) -> str:
        out = []
        for s in self.get(trace_id):
            d = f"{s.duration_s * 1000:.1f}ms" if s.end else "…"
            attrs = " ".join(f"{k}={v}" for k, v in s.attributes.items())
            out.append(f"{s.name:<24} {d:>10} {attrs}")
        return "\n".join(out)


@dataclasses.dataclass(frozen=True)
class QueryEvent:
    """QueryCreated/QueryCompleted payload subset (reference:
    spi/eventlistener/QueryCompletedEvent.java)."""
    kind: str                 # "created" | "completed" | "failed"
    query_id: str
    sql: str
    wall_s: Optional[float] = None
    rows: Optional[int] = None
    error: Optional[str] = None


class EventListenerManager:
    def __init__(self):
        self._listeners: List[Callable[[QueryEvent], None]] = []
        self._lock = threading.Lock()

    def register(self, listener: Callable[[QueryEvent], None]):
        with self._lock:
            self._listeners.append(listener)

    def unregister(self, listener: Callable[[QueryEvent], None]):
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def emit(self, event: QueryEvent):
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(event)
            except Exception:   # noqa: BLE001 — listeners must not kill queries
                pass


# process-wide defaults (the Guice-singleton analog)
TRACER = Tracer()
EVENTS = EventListenerManager()


@contextmanager
def query_lifecycle(qid: str, sql: str):
    """Shared created/failed/completed emission around one query's
    execution (used by LocalEngine and TpuCluster). Yields a one-slot
    list the body fills with the result rows so `completed` can report
    the row count."""
    t0 = time.time()
    EVENTS.emit(QueryEvent("created", qid, sql))
    box: List[Any] = [None]
    try:
        yield box
    except Exception as e:
        EVENTS.emit(QueryEvent("failed", qid, sql,
                               wall_s=time.time() - t0, error=str(e)))
        raise
    rows = box[0]
    EVENTS.emit(QueryEvent(
        "completed", qid, sql, wall_s=time.time() - t0,
        rows=len(rows) if rows is not None else None))
