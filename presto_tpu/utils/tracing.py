"""Tracing + query-event pipeline.

Reference roles:
  - spi/tracing/Tracer.java + TracerProvider (SURVEY.md §5.1): named
    spans with wall-time points, queryable per query. SimpleTracer's
    add-point/get-points surface, W3C-style nesting flattened to
    (name, start, end, attributes) records.
  - spi/eventlistener (QueryCreatedEvent / QueryCompletedEvent /
    SplitCompletedEvent -> eventlistener/EventListenerManager.java +
    event/QueryMonitor.java, SURVEY.md §5.5): registered listeners get
    lifecycle events with timing/stats payloads.
  - TelemetryTracingImpl's context propagation: the coordinator stamps
    every worker RPC with an `X-Presto-Trace: <trace_id>;<span_id>`
    header; workers open their spans under the propagated trace id and
    the coordinator stitches worker span dumps (GET /v1/trace/{id})
    back into one cross-node timeline.

Engines call `tracer.span(...)` around phases (plan/lower/execute) and
`emit_query_event(...)` at lifecycle edges; listeners are plain
callables (the plugin surface collapsed to its functional core)."""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger("presto_tpu.tracing")

#: wire header carrying "<trace_id>;<parent_span_id>" on every
#: coordinator -> worker RPC (PrestoHeaders-style custom header)
TRACE_HEADER = "X-Presto-Trace"


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: process-unique id — remote-span stitching dedupes on it
    span_id: str = ""
    #: parent span id (propagated cross-node via X-Presto-Trace)
    parent_id: str = ""

    def __post_init__(self):
        if not self.span_id:
            self.span_id = uuid.uuid4().hex[:16]

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_json(self) -> dict:
        return {"name": self.name, "start": self.start, "end": self.end,
                "spanId": self.span_id, "parentId": self.parent_id,
                "attributes": dict(self.attributes)}

    @staticmethod
    def from_json(doc: dict) -> "Span":
        return Span(name=doc.get("name", "?"),
                    start=float(doc.get("start", 0.0)),
                    end=(None if doc.get("end") is None
                         else float(doc["end"])),
                    attributes=dict(doc.get("attributes") or {}),
                    span_id=str(doc.get("spanId") or ""),
                    parent_id=str(doc.get("parentId") or ""))


# --------------------------------------------------------------------------
# Trace-context propagation. The ACTIVE context is thread-local: the
# scheduler thread sets it for one query, and `transport.HttpClient`
# stamps every outgoing RPC on that thread with the header. (Watcher /
# puller helper threads deliberately do not inherit it — control-plane
# polls are not part of the query timeline.)
@dataclasses.dataclass(frozen=True)
class TraceContext:
    trace_id: str
    parent_span_id: str = ""

    def header_value(self) -> str:
        return f"{self.trace_id};{self.parent_span_id}"


_ACTIVE = threading.local()

# tid -> trace id mirror of the thread-local context. `threading.local`
# cannot be read from another thread, but the sampling profiler
# (obs/profiler.py) attributes stacks to the query each thread is
# working on — so trace_scope maintains this parallel map too. Guarded
# by its own lock; entries live exactly as long as the scope.
_THREAD_TRACES: Dict[int, str] = {}
_THREAD_TRACES_LOCK = threading.Lock()


def current_trace() -> Optional[TraceContext]:
    return getattr(_ACTIVE, "ctx", None)


def thread_traces() -> Dict[int, str]:
    """Snapshot of thread-id -> active trace id (profiler attribution)."""
    with _THREAD_TRACES_LOCK:
        return dict(_THREAD_TRACES)


@contextmanager
def trace_scope(trace_id: str, parent_span_id: str = ""):
    """Install a TraceContext for the current thread; outgoing RPCs via
    transport.HttpClient carry it as X-Presto-Trace until exit."""
    prev = getattr(_ACTIVE, "ctx", None)
    _ACTIVE.ctx = TraceContext(trace_id, parent_span_id)
    tid = threading.get_ident()
    with _THREAD_TRACES_LOCK:
        prev_tid = _THREAD_TRACES.get(tid)
        _THREAD_TRACES[tid] = trace_id
    try:
        yield _ACTIVE.ctx
    finally:
        _ACTIVE.ctx = prev
        with _THREAD_TRACES_LOCK:
            if prev_tid is None:
                _THREAD_TRACES.pop(tid, None)
            else:
                _THREAD_TRACES[tid] = prev_tid


def parse_trace_header(value: Optional[str]) -> Optional[TraceContext]:
    """'<trace_id>;<parent_span_id>' -> TraceContext (None on absent or
    malformed input — tracing is never a reason to fail an RPC)."""
    if not value:
        return None
    parts = value.split(";", 1)
    trace_id = parts[0].strip()
    if not trace_id:
        return None
    parent = parts[1].strip() if len(parts) > 1 else ""
    return TraceContext(trace_id, parent)


class Tracer:
    """Per-process tracer: spans grouped by trace id (query id). Bounded
    two ways: only the most recent `max_traces` query traces are
    retained (the reference's QueryTracker similarly caps
    finished-query history), and within one trace at most
    `max_spans_per_trace` spans are recorded — beyond that spans still
    time their bodies but are counted as dropped instead of growing the
    list without bound (a long-running query with per-chunk spans must
    not eat the heap)."""

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 2048):
        self._lock = threading.Lock()
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self.spans: Dict[str, List[Span]] = {}
        #: trace id -> spans dropped by the per-trace cap
        self.dropped: Dict[str, int] = {}

    def _store(self, trace_id: str, s: Span) -> bool:
        """Append under the caps; False when the span was dropped."""
        with self._lock:
            lst = self.spans.setdefault(trace_id, [])
            if len(lst) >= self.max_spans_per_trace:
                self.dropped[trace_id] = \
                    self.dropped.get(trace_id, 0) + 1
                kept = False
            else:
                lst.append(s)
                kept = True
            while len(self.spans) > self.max_traces:
                evicted = next(iter(self.spans))   # oldest insert
                self.spans.pop(evicted)
                self.dropped.pop(evicted, None)
        if not kept:
            from presto_tpu.obs.metrics import counter
            counter("presto_tpu_tracer_dropped_spans_total",
                    "Spans dropped by the per-trace span cap").inc()
        return kept

    @contextmanager
    def span(self, trace_id: str, name: str, **attributes):
        ctx = current_trace()
        parent = ctx.parent_span_id \
            if ctx is not None and ctx.trace_id == trace_id else ""
        s = Span(name, time.time(), attributes=dict(attributes),
                 parent_id=parent)
        self._store(trace_id, s)
        try:
            yield s
        finally:
            s.end = time.time()

    def record(self, trace_id: str, name: str, start: float,
               end: Optional[float] = None, parent_id: str = "",
               **attributes) -> Span:
        """Record an already-timed span (worker-side per-operator spans
        whose wall times come from the executor's profile)."""
        s = Span(name, start, end=end, attributes=dict(attributes),
                 parent_id=parent_id)
        self._store(trace_id, s)
        return s

    def get(self, trace_id: str) -> List[Span]:
        with self._lock:
            return list(self.spans.get(trace_id, []))

    def dropped_spans(self, trace_id: str) -> int:
        with self._lock:
            return self.dropped.get(trace_id, 0)

    # ---- cross-node stitching -------------------------------------------
    def to_json(self, trace_id: str) -> dict:
        """Wire dump for GET /v1/trace/{trace_id}."""
        return {"traceId": trace_id,
                "spans": [s.to_json() for s in self.get(trace_id)],
                "droppedSpans": self.dropped_spans(trace_id)}

    def merge_remote(self, trace_id: str, doc: dict) -> int:
        """Stitch a worker's span dump into this tracer's trace.
        Dedupes by span_id, so re-scrapes — and the in-process cluster,
        where workers share this very tracer — never duplicate spans.
        Returns the number of spans added."""
        have = {s.span_id for s in self.get(trace_id)}
        added = 0
        for sdoc in doc.get("spans", []):
            s = Span.from_json(sdoc)
            if s.span_id in have:
                continue
            if not self._store(trace_id, s):
                break
            have.add(s.span_id)
            added += 1
        return added

    def render(self, trace_id: str) -> str:
        """One cross-node timeline: spans sorted by start, offsets
        relative to the earliest span, worker column from the `worker`
        attribute (coordinator spans carry none)."""
        spans = sorted(self.get(trace_id), key=lambda s: s.start)
        if not spans:
            return ""
        t0 = spans[0].start
        out = []
        for s in spans:
            d = f"{s.duration_s * 1000:.1f}ms" if s.end else "…"
            attrs = dict(s.attributes)
            worker = str(attrs.pop("worker", "coordinator"))
            rest = " ".join(f"{k}={v}" for k, v in attrs.items())
            out.append(f"+{(s.start - t0) * 1000:8.1f}ms "
                       f"{worker:<16} {s.name:<24} {d:>10} {rest}")
        ndrop = self.dropped_spans(trace_id)
        if ndrop:
            out.append(f"… {ndrop} span(s) dropped by the per-trace cap")
        return "\n".join(out)


@dataclasses.dataclass(frozen=True)
class QueryEvent:
    """QueryCreated/QueryCompleted payload subset (reference:
    spi/eventlistener/QueryCompletedEvent.java)."""
    kind: str   # "created" | "completed" | "failed" | "wide" | "alert"
    query_id: str
    sql: str
    wall_s: Optional[float] = None
    rows: Optional[int] = None
    error: Optional[str] = None
    #: structured payload for "wide" events (obs/wide_events.py): the
    #: full per-query stat surface as one JSON-compatible dict
    detail: Optional[dict] = None


class EventListenerManager:
    def __init__(self):
        self._listeners: List[Callable[[QueryEvent], None]] = []
        self._lock = threading.Lock()
        self._logged_failures: set = set()

    def register(self, listener: Callable[[QueryEvent], None]):
        with self._lock:
            self._listeners.append(listener)

    def unregister(self, listener: Callable[[QueryEvent], None]):
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def emit(self, event: QueryEvent):
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(event)
            except Exception:   # noqa: BLE001 — listeners must not kill queries
                # ...but they must not fail INVISIBLY either: count every
                # swallow in the registry and log each failing listener
                # once (not once per event — a broken listener on a busy
                # cluster would flood the log)
                from presto_tpu.obs.metrics import counter
                counter("presto_tpu_event_listener_errors_total",
                        "Exceptions swallowed from event listeners"
                        ).inc()
                key = id(cb)
                if key not in self._logged_failures:
                    self._logged_failures.add(key)
                    log.exception(
                        "event listener %r raised on %s event "
                        "(logged once; further failures only counted)",
                        getattr(cb, "__name__", cb), event.kind)


# process-wide defaults (the Guice-singleton analog)
TRACER = Tracer()
EVENTS = EventListenerManager()


@contextmanager
def query_lifecycle(qid: str, sql: str):
    """Shared created/failed/completed emission around one query's
    execution (used by LocalEngine and TpuCluster). Yields a one-slot
    list the body fills with the result rows so `completed` can report
    the row count."""
    t0 = time.time()
    EVENTS.emit(QueryEvent("created", qid, sql))
    box: List[Any] = [None]
    try:
        yield box
    except Exception as e:
        EVENTS.emit(QueryEvent("failed", qid, sql,
                               wall_s=time.time() - t0, error=str(e)))
        raise
    rows = box[0]
    EVENTS.emit(QueryEvent(
        "completed", qid, sql, wall_s=time.time() - t0,
        rows=len(rows) if rows is not None else None))
