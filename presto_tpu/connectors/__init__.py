from presto_tpu.connectors.tpch import TPCH_SCHEMA, TpchConnector
from presto_tpu.connectors.tpcds import TPCDS_SCHEMA, TpcdsConnector
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.parquet import ParquetConnector

__all__ = ["TPCH_SCHEMA", "TpchConnector", "TPCDS_SCHEMA",
           "TpcdsConnector", "MemoryConnector", "ParquetConnector"]
