from presto_tpu.connectors.tpch import TPCH_SCHEMA, TpchConnector

__all__ = ["TPCH_SCHEMA", "TpchConnector"]
