"""TPC-H connector: deterministic in-memory data generation.

Reference role: presto-tpch (presto-tpch/src/main/java/com/facebook/presto/
tpch/TpchConnectorFactory.java, TpchRecordSetProvider) — data generated on
the fly from split info, no external files; the standard deterministic
fixture for every engine test (SURVEY.md §4).

This generator is *spec-shaped*, not bit-identical to dbgen: row counts,
key relationships (lineitem->orders, partsupp's 4-suppliers-per-part
formula, customers without orders), value distributions and date ranges
follow the TPC-H spec so query selectivities and join fan-outs are
realistic; exact values differ from airlift's dbgen port. Correctness
testing compares against a pandas oracle over the *same* data
(tests/oracle.py), mirroring the reference's H2QueryRunner strategy
(presto-tests/.../H2QueryRunner.java).

Tables partition by primary-key row ranges (part k of n), matching the
reference's split model where tpch splits are self-describing
(TpchSplitManager). Splits are row-range slices of the cached full table
so string codes share one table-wide StringDict — the invariant every
cross-device exchange and dictionary-aligned operator relies on.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.data.column import Column, Page, StringDict, bucket_capacity
from presto_tpu.expr.compile import days_from_civil
from presto_tpu.types import BIGINT, DATE, DOUBLE, INTEGER, VARCHAR, Type

# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

TPCH_SCHEMA: Dict[str, List[Tuple[str, Type]]] = {
    "region": [("r_regionkey", BIGINT), ("r_name", VARCHAR),
               ("r_comment", VARCHAR)],
    "nation": [("n_nationkey", BIGINT), ("n_name", VARCHAR),
               ("n_regionkey", BIGINT), ("n_comment", VARCHAR)],
    "supplier": [("s_suppkey", BIGINT), ("s_name", VARCHAR),
                 ("s_address", VARCHAR), ("s_nationkey", BIGINT),
                 ("s_phone", VARCHAR), ("s_acctbal", DOUBLE),
                 ("s_comment", VARCHAR)],
    "customer": [("c_custkey", BIGINT), ("c_name", VARCHAR),
                 ("c_address", VARCHAR), ("c_nationkey", BIGINT),
                 ("c_phone", VARCHAR), ("c_acctbal", DOUBLE),
                 ("c_mktsegment", VARCHAR), ("c_comment", VARCHAR)],
    "part": [("p_partkey", BIGINT), ("p_name", VARCHAR), ("p_mfgr", VARCHAR),
             ("p_brand", VARCHAR), ("p_type", VARCHAR), ("p_size", INTEGER),
             ("p_container", VARCHAR), ("p_retailprice", DOUBLE),
             ("p_comment", VARCHAR)],
    "partsupp": [("ps_partkey", BIGINT), ("ps_suppkey", BIGINT),
                 ("ps_availqty", INTEGER), ("ps_supplycost", DOUBLE),
                 ("ps_comment", VARCHAR)],
    "orders": [("o_orderkey", BIGINT), ("o_custkey", BIGINT),
               ("o_orderstatus", VARCHAR), ("o_totalprice", DOUBLE),
               ("o_orderdate", DATE), ("o_orderpriority", VARCHAR),
               ("o_clerk", VARCHAR), ("o_shippriority", INTEGER),
               ("o_comment", VARCHAR)],
    "lineitem": [("l_orderkey", BIGINT), ("l_partkey", BIGINT),
                 ("l_suppkey", BIGINT), ("l_linenumber", INTEGER),
                 ("l_quantity", DOUBLE), ("l_extendedprice", DOUBLE),
                 ("l_discount", DOUBLE), ("l_tax", DOUBLE),
                 ("l_returnflag", VARCHAR), ("l_linestatus", VARCHAR),
                 ("l_shipdate", DATE), ("l_commitdate", DATE),
                 ("l_receiptdate", DATE), ("l_shipinstruct", VARCHAR),
                 ("l_shipmode", VARCHAR), ("l_comment", VARCHAR)],
}

_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("SAUDI ARABIA", 4),
    ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE",
              "TAKE BACK RETURN"]
_CONTAINERS = [f"{a} {b}" for a in
               ["SM", "LG", "MED", "JUMBO", "WRAP"] for b in
               ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_PTYPES = [f"{a} {b} {c}" for a in _TYPE_S1 for b in _TYPE_S2
           for c in _TYPE_S3]
_PNAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
    "green", "grey", "honeydew", "hot", "hotpink", "indian", "ivory",
    "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
    "magenta", "maroon", "medium", "metallic", "midnight", "mint", "misty",
    "moccasin", "navajo", "navy", "olive", "orange", "orchid", "pale",
    "papaya", "peach", "peru", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan",
    "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]
_COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "final", "pending", "regular", "express", "special", "bold", "even",
    "silent", "unusual", "requests", "deposits", "packages", "accounts",
    "instructions", "theodolites", "platelets", "foxes", "ideas", "courts",
    "sleep", "wake", "nag", "haggle", "cajole", "detect", "integrate",
    "among", "across", "above", "against", "along",
]

_MIN_DATE = days_from_civil(1992, 1, 1)
_MAX_ORDER_DATE = days_from_civil(1998, 8, 2)
_CURRENT = days_from_civil(1995, 6, 17)  # dbgen CURRENTDATE analogue

_SF_BASE = {"supplier": 10_000, "customer": 150_000, "part": 200_000,
            "orders": 1_500_000}
_SUPP_PER_PART = 4
_SCHEMA_SCALES = {"tiny": 0.001, "sf0.01": 0.01, "sf0.1": 0.1, "sf1": 1.0,
                  "sf10": 10.0, "sf100": 100.0}


def _counts(sf: float) -> Dict[str, int]:
    return {
        "region": 5,
        "nation": 25,
        "supplier": max(10, int(_SF_BASE["supplier"] * sf)),
        "customer": max(15, int(_SF_BASE["customer"] * sf)),
        "part": max(20, int(_SF_BASE["part"] * sf)),
        "orders": max(150, int(_SF_BASE["orders"] * sf)),
    }


def _comment(rng: np.random.Generator, n: int, words: int = 4) -> np.ndarray:
    w = np.asarray(_COMMENT_WORDS, dtype=object)
    idx = rng.integers(0, len(w), size=(n, words))
    out = w[idx[:, 0]]
    for k in range(1, words):
        out = out + " " + w[idx[:, k]]
    return out


def _phone(rng: np.random.Generator, nation: np.ndarray) -> np.ndarray:
    a = nation + 10
    b = rng.integers(100, 1000, size=len(nation))
    c = rng.integers(100, 1000, size=len(nation))
    d = rng.integers(1000, 10000, size=len(nation))
    return np.char.add(np.char.add(np.char.add(np.char.add(
        a.astype(str), "-"), b.astype(str)), "-"),
        np.char.add(np.char.add(c.astype(str), "-"), d.astype(str))
    ).astype(object)


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    return (90000 + ((partkey // 10) % 20001) + 100 * (partkey % 1000)) / 100.0


def _part_suppliers(partkey: np.ndarray, j: np.ndarray, num_supp: int
                    ) -> np.ndarray:
    """dbgen-style formula: the j-th supplier of part p (j in [0,4))."""
    return ((partkey - 1 + j * (num_supp // _SUPP_PER_PART + 1)) % num_supp
            ) + 1


@dataclasses.dataclass
class HostTable:
    """Host-side generated table: numeric numpy arrays (string columns
    stored as int32 codes) + shared StringDicts. `page()` uploads a
    column-pruned, bucket-padded device Page. `nulls` is optional (the
    TPC fixtures are null-free; written tables — connectors/memory.py —
    carry real null masks)."""
    name: str
    num_rows: int
    arrays: Dict[str, np.ndarray]
    types: Dict[str, Type]
    dicts: Dict[str, StringDict]
    nulls: Optional[Dict[str, np.ndarray]] = None

    def column_names(self) -> List[str]:
        return list(self.types)      # schema insertion order

    def null_mask(self, c: str) -> Optional[np.ndarray]:
        if self.nulls is None:
            return None
        m = self.nulls.get(c)
        return m[:self.num_rows] if m is not None else None

    def row_slice(self, lo: int, hi: int) -> "HostTable":
        """A [lo, hi) row window as a VIEW table: numpy slices share the
        parent's buffers and StringDicts — no copy, and no entry in the
        parent's device-page cache (run tables are throwaway by design;
        streaming scans upload each run once). Column access goes
        through `arrays[c]` so lazy tables (parquet) load on demand."""
        arrays = {c: self.arrays[c][lo:hi] for c in self.column_names()}
        nulls = None
        if self.nulls is not None:
            nulls = {c: m[lo:hi] for c, m in
                     ((c, self.null_mask(c)) for c in self.column_names())
                     if m is not None}
        return HostTable(self.name, hi - lo, arrays, self.types,
                         self.dicts, nulls)

    def page(self, columns: Optional[Sequence[str]] = None,
             capacity: Optional[int] = None) -> Page:
        cols = list(columns) if columns is not None else self.column_names()
        cap = capacity or bucket_capacity(self.num_rows)
        # per-(column, capacity) DEVICE cache: re-executions and sibling
        # islands reuse resident columns instead of re-uploading hundreds
        # of MB through the host->device tunnel each run (measured: the
        # lineitem upload alone cost ~19 s/run at SF1). Different column
        # subsets share entries because caching is per column. NOTE: the
        # cache lives on the HostTable instance, so it covers whole-table
        # scans (lru-cached _gen_table / MemoryConnector.tables entries —
        # the single-chip engine + bench path); split slices
        # (table(part=...)) build throwaway HostTables and still upload
        # per call.
        cache = self.__dict__.setdefault("_dev_page_cache", {})
        out = []
        for c in cols:
            key = (c, cap)
            col = cache.get(key)
            if col is None:
                t = self.types[c]
                if t.name in ("array", "map", "row"):
                    from presto_tpu.data.column import NestedColumn
                    col = NestedColumn.from_pylist(
                        list(self.arrays[c][:self.num_rows]), t, cap)
                elif getattr(t, "uses_int128", False):
                    # DECIMAL(p>18) at rest: python-int unscaled values
                    # -> four 32-bit limb lanes (exact 38-digit range)
                    from presto_tpu.data.column import Decimal128Column
                    col = Decimal128Column.from_unscaled_ints(
                        list(self.arrays[c][:self.num_rows]), t,
                        nulls=self.null_mask(c), capacity=cap)
                else:
                    col = Column.from_numpy(
                        self.arrays[c][:self.num_rows], t,
                        nulls=self.null_mask(c),
                        dictionary=self.dicts.get(c), capacity=cap)
                cache[key] = col
            out.append(col)
        return Page.from_columns(out, self.num_rows, cols)


def _dictify(values: np.ndarray) -> Tuple[np.ndarray, StringDict]:
    d, codes = StringDict.build(values)
    return codes, d


def _slice_rows(total: int, part: int, num_parts: int) -> Tuple[int, int]:
    per = (total + num_parts - 1) // num_parts
    lo = min(part * per, total)
    hi = min(lo + per, total)
    return lo, hi


def _seed(name: str, sf: float, part: int) -> int:
    """Stable across processes (python hash() is per-process randomized —
    workers on different hosts must regenerate identical splits)."""
    import zlib
    return zlib.crc32(f"{name}|{sf}|{part}".encode())


@functools.lru_cache(maxsize=64)
def _gen_table(name: str, sf: float) -> HostTable:
    # Whole-table generation only: splits are row-range slices served by
    # TpchConnector.table() so codes share one table-wide StringDict.
    part, num_parts = 0, 1
    c = _counts(sf)
    rng = np.random.default_rng(
        _seed(name if name != "lineitem" else "orders", sf, part))
    types = dict(TPCH_SCHEMA[name])
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}

    def put_str(col: str, vals: np.ndarray):
        arrays[col], dicts[col] = _dictify(vals)

    if name == "region":
        lo, hi = _slice_rows(5, part, num_parts)
        arrays["r_regionkey"] = np.arange(lo, hi, dtype=np.int64)
        put_str("r_name", np.asarray(_REGIONS, dtype=object)[lo:hi])
        put_str("r_comment", _comment(rng, hi - lo))
        n = hi - lo
    elif name == "nation":
        lo, hi = _slice_rows(25, part, num_parts)
        arrays["n_nationkey"] = np.arange(lo, hi, dtype=np.int64)
        put_str("n_name", np.asarray([x[0] for x in _NATIONS],
                                     dtype=object)[lo:hi])
        arrays["n_regionkey"] = np.asarray(
            [x[1] for x in _NATIONS], dtype=np.int64)[lo:hi]
        put_str("n_comment", _comment(rng, hi - lo))
        n = hi - lo
    elif name == "supplier":
        lo, hi = _slice_rows(c["supplier"], part, num_parts)
        n = hi - lo
        key = np.arange(lo + 1, hi + 1, dtype=np.int64)
        arrays["s_suppkey"] = key
        put_str("s_name", np.char.add("Supplier#",
                np.char.zfill(key.astype(str), 9)).astype(object))
        put_str("s_address", _comment(rng, n, 2))
        nat = rng.integers(0, 25, size=n)
        arrays["s_nationkey"] = nat.astype(np.int64)
        put_str("s_phone", _phone(rng, nat))
        arrays["s_acctbal"] = np.round(
            rng.uniform(-999.99, 9999.99, size=n), 2)
        # ~5 of every 1000 suppliers complain, ~5 recommend (Q16/Q21)
        comm = _comment(rng, n)
        tag = rng.integers(0, 1000, size=n)
        comm = np.where(tag < 5, "Customer Complaints " + comm, comm)
        comm = np.where(tag >= 995, "Customer Recommends " + comm, comm)
        put_str("s_comment", comm.astype(object))
    elif name == "customer":
        lo, hi = _slice_rows(c["customer"], part, num_parts)
        n = hi - lo
        key = np.arange(lo + 1, hi + 1, dtype=np.int64)
        arrays["c_custkey"] = key
        put_str("c_name", np.char.add("Customer#",
                np.char.zfill(key.astype(str), 9)).astype(object))
        put_str("c_address", _comment(rng, n, 2))
        nat = rng.integers(0, 25, size=n)
        arrays["c_nationkey"] = nat.astype(np.int64)
        put_str("c_phone", _phone(rng, nat))
        arrays["c_acctbal"] = np.round(
            rng.uniform(-999.99, 9999.99, size=n), 2)
        put_str("c_mktsegment",
                np.asarray(_SEGMENTS, dtype=object)[
                    rng.integers(0, 5, size=n)])
        put_str("c_comment", _comment(rng, n, 6))
    elif name == "part":
        lo, hi = _slice_rows(c["part"], part, num_parts)
        n = hi - lo
        key = np.arange(lo + 1, hi + 1, dtype=np.int64)
        arrays["p_partkey"] = key
        w = np.asarray(_PNAME_WORDS, dtype=object)
        idx = rng.integers(0, len(w), size=(n, 5))
        nm = w[idx[:, 0]]
        for k in range(1, 5):
            nm = nm + " " + w[idx[:, k]]
        put_str("p_name", nm)
        mfgr = rng.integers(1, 6, size=n)
        put_str("p_mfgr", np.char.add("Manufacturer#",
                                      mfgr.astype(str)).astype(object))
        brand = mfgr * 10 + rng.integers(1, 6, size=n)
        put_str("p_brand", np.char.add("Brand#",
                                       brand.astype(str)).astype(object))
        put_str("p_type", np.asarray(_PTYPES, dtype=object)[
            rng.integers(0, len(_PTYPES), size=n)])
        arrays["p_size"] = rng.integers(1, 51, size=n).astype(np.int32)
        put_str("p_container", np.asarray(_CONTAINERS, dtype=object)[
            rng.integers(0, len(_CONTAINERS), size=n)])
        arrays["p_retailprice"] = _retailprice(key)
        put_str("p_comment", _comment(rng, n, 2))
    elif name == "partsupp":
        lo, hi = _slice_rows(c["part"], part, num_parts)
        n = (hi - lo) * _SUPP_PER_PART
        pk = np.repeat(np.arange(lo + 1, hi + 1, dtype=np.int64),
                       _SUPP_PER_PART)
        j = np.tile(np.arange(_SUPP_PER_PART, dtype=np.int64), hi - lo)
        arrays["ps_partkey"] = pk
        arrays["ps_suppkey"] = _part_suppliers(pk, j, c["supplier"])
        arrays["ps_availqty"] = rng.integers(
            1, 10000, size=n).astype(np.int32)
        arrays["ps_supplycost"] = np.round(
            rng.uniform(1.0, 1000.0, size=n), 2)
        put_str("ps_comment", _comment(rng, n, 6))
    elif name in ("orders", "lineitem"):
        return _gen_orders_lineitem(name, sf)
    else:
        raise KeyError(name)

    return HostTable(name, n, arrays, types, dicts)


@functools.lru_cache(maxsize=32)
def _gen_orders_lineitem(which: str, sf: float) -> HostTable:
    """Orders and their lineitems generate together (totalprice is the sum
    of its lines). Whole-table only — splits are slices, see table()."""
    part, num_parts = 0, 1
    c = _counts(sf)
    rng = np.random.default_rng(_seed("orders", sf, part))
    lo, hi = _slice_rows(c["orders"], part, num_parts)
    n = hi - lo
    okey = np.arange(lo + 1, hi + 1, dtype=np.int64)
    # Customers with c%3==0 never order (dbgen leaves 1/3 of customers
    # orderless — exercised by Q13/Q22).
    ck = rng.integers(1, c["customer"] + 1, size=n).astype(np.int64)
    ck = np.where(ck % 3 == 0, (ck % (c["customer"] - 1)) + 1, ck)
    ck = np.where(ck % 3 == 0, ck + 1, ck)
    odate = rng.integers(_MIN_DATE, _MAX_ORDER_DATE - 121, size=n
                         ).astype(np.int32)

    nlines = rng.integers(1, 8, size=n)
    total_lines = int(nlines.sum())
    l_okey = np.repeat(okey, nlines)
    l_odate = np.repeat(odate, nlines)
    starts = np.concatenate([[0], np.cumsum(nlines)[:-1]])
    l_lineno = (np.arange(total_lines) -
                np.repeat(starts, nlines) + 1).astype(np.int32)

    pk = rng.integers(1, c["part"] + 1, size=total_lines).astype(np.int64)
    j = rng.integers(0, _SUPP_PER_PART, size=total_lines).astype(np.int64)
    sk = _part_suppliers(pk, j, c["supplier"])
    qty = rng.integers(1, 51, size=total_lines).astype(np.float64)
    eprice = qty * _retailprice(pk)
    disc = rng.integers(0, 11, size=total_lines) / 100.0
    tax = rng.integers(0, 9, size=total_lines) / 100.0
    sdate = (l_odate + rng.integers(1, 122, size=total_lines)).astype(np.int32)
    cdate = (l_odate + rng.integers(30, 91, size=total_lines)).astype(np.int32)
    rdate = (sdate + rng.integers(1, 31, size=total_lines)).astype(np.int32)
    returned = rdate <= _CURRENT
    rflag = np.where(returned,
                     np.where(rng.random(total_lines) < 0.5, "R", "A"),
                     "N").astype(object)
    lstatus = np.where(sdate > _CURRENT, "O", "F").astype(object)

    if which == "lineitem":
        arrays: Dict[str, np.ndarray] = {
            "l_orderkey": l_okey, "l_partkey": pk, "l_suppkey": sk,
            "l_linenumber": l_lineno, "l_quantity": qty,
            "l_extendedprice": eprice, "l_discount": disc, "l_tax": tax,
            "l_shipdate": sdate, "l_commitdate": cdate,
            "l_receiptdate": rdate,
        }
        dicts: Dict[str, StringDict] = {}

        def put_str(col, vals):
            arrays[col], dicts[col] = _dictify(vals)
        put_str("l_returnflag", rflag)
        put_str("l_linestatus", lstatus)
        put_str("l_shipinstruct", np.asarray(_INSTRUCTS, dtype=object)[
            rng.integers(0, 4, size=total_lines)])
        put_str("l_shipmode", np.asarray(_SHIPMODES, dtype=object)[
            rng.integers(0, 7, size=total_lines)])
        put_str("l_comment", _comment(rng, total_lines, 3))
        return HostTable("lineitem", total_lines, arrays,
                         dict(TPCH_SCHEMA["lineitem"]), dicts)

    # orders
    line_total = eprice * (1.0 + tax) * (1.0 - disc)
    totalprice = np.add.reduceat(line_total, starts)
    any_open = np.add.reduceat((lstatus == "O").astype(np.int64), starts)
    nline_arr = nlines
    status = np.where(any_open == 0, "F",
                      np.where(any_open == nline_arr, "O", "P")
                      ).astype(object)
    arrays = {"o_orderkey": okey, "o_custkey": ck,
              "o_totalprice": np.round(totalprice, 2), "o_orderdate": odate,
              "o_shippriority": np.zeros(n, dtype=np.int32)}
    dicts = {}

    def put_str(col, vals):
        arrays[col], dicts[col] = _dictify(vals)
    put_str("o_orderstatus", status)
    put_str("o_orderpriority", np.asarray(_PRIORITIES, dtype=object)[
        rng.integers(0, 5, size=n)])
    put_str("o_clerk", np.char.add("Clerk#", np.char.zfill(
        rng.integers(1, max(2, int(1000 * sf)) + 1, size=n).astype(str), 9)
    ).astype(object))
    put_str("o_comment", _comment(rng, n, 5))
    return HostTable("orders", n, arrays, dict(TPCH_SCHEMA["orders"]), dicts)


from presto_tpu.connectors.base import SplitSource


class TpchConnector(SplitSource):
    NAME = "tpch"
    """Connector facade: schema + partitioned table generation.

    Reference surface: ConnectorMetadata + ConnectorSplitManager +
    ConnectorPageSource (presto-spi/.../ConnectorPageSource.java), collapsed
    into the two calls an in-memory generated source actually needs."""

    def __init__(self, scale_factor: float = 0.01):
        self.scale_factor = scale_factor

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return TPCH_SCHEMA[table]

    def row_count(self, table: str) -> int:
        """Planner statistics (reference role: connector-provided
        TableStatistics feeding the CBO, cost/ package)."""
        c = _counts(self.scale_factor)
        if table in c:
            return c[table]
        if table == "partsupp":
            return c["part"] * _SUPP_PER_PART
        if table == "lineitem":
            return c["orders"] * 4
        raise KeyError(table)

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        """Full table (cached), or split `part` of `num_parts` as a
        row-range slice of it. Slices share the full table's StringDicts,
        so codes are globally consistent — the property every cross-device
        exchange and dictionary-aligned operator relies on (reference
        analogue: TpchSplitManager handing row ranges of one logical
        table, presto-tpch/.../TpchSplitManager.java)."""
        if name not in TPCH_SCHEMA:
            raise KeyError(f"unknown tpch table {name}")
        full = _gen_table(name, self.scale_factor)  # lru_cached
        if num_parts == 1:
            return full
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: a[lo:hi] for c, a in full.arrays.items()}
        return HostTable(name, hi - lo, arrays, full.types, full.dicts)
