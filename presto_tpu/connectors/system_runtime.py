"""`system` catalog — live cluster state exposed as real tables.

Reference role: presto-main's SystemConnector / system.runtime schema
(SystemTablesMetadata + RuntimeQueriesSystemTable / TaskSystemTable /
NodesSystemTable, SURVEY.md §5): the cluster observes itself through its
own query engine, so `SELECT state, count(*) FROM system.runtime.tasks
GROUP BY state` plans, schedules and filters with the engine's own
operators instead of a bespoke admin endpoint.

Shape: a facade connector (the MemoryConnector fallback idiom) wraps the
cluster's real connector; names under `system.` route to providers that
snapshot coordinator state, everything else delegates untouched. The
cluster reference is late-bound (`attach_cluster`) because the facade
must exist before TpuCluster finishes constructing.

Split model: system tables ride the normal split/scan path, but their
snapshots are point-in-time — handing every task its own row-range of a
*different* snapshot would duplicate or drop rows. So `table_splits`
returns the standard one-split-per-task payloads while `table()` serves
the full snapshot for part 0 and an empty slice for every other part:
one consistent snapshot per query, engine operators downstream.

Tables (schemas frozen in README "Introspection"):
  system.runtime.queries — statement front-door queries + the wide-event
      ledger (source column distinguishes them)
  system.runtime.tasks   — fan-out over worker GET /v1/tasks
  system.runtime.nodes   — membership view incl. DRAINING/DEAD workers
  system.runtime.profile — sampling profiler buckets (obs/profiler.py)
  system.runtime.materialized_views — MV registry: fingerprint,
      refreshed versions, staleness, pinned state bytes (presto_tpu/mv/)
  system.runtime.metrics_history — the telemetry TSDB (obs/tsdb.py):
      every retained (name, labels, timestamp, value) point, joinable
      against system.runtime.queries by time
  system.runtime.alerts  — alert-transition history (obs/alerts.py)
  system.metrics         — every registry series as rows
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.base import SplitSource
from presto_tpu.connectors.tpch import HostTable
from presto_tpu.data.column import StringDict
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, Type

log = logging.getLogger("presto_tpu.system")

QUERIES = "system.runtime.queries"
TASKS = "system.runtime.tasks"
NODES = "system.runtime.nodes"
PROFILE = "system.runtime.profile"
MATERIALIZED_VIEWS = "system.runtime.materialized_views"
METRICS_HISTORY = "system.runtime.metrics_history"
ALERTS = "system.runtime.alerts"
METRICS = "system.metrics"

SYSTEM_SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    QUERIES: [
        ("query_id", VARCHAR), ("source", VARCHAR), ("state", VARCHAR),
        ("user_name", VARCHAR), ("query", VARCHAR),
        ("resource_group", VARCHAR), ("queue_wait_s", DOUBLE),
        ("wall_s", DOUBLE), ("result_rows", BIGINT),
        ("hbo_hits", BIGINT), ("hbo_misses", BIGINT),
        ("cached_tasks", BIGINT), ("spooled_bytes", BIGINT),
        ("trace_id", VARCHAR), ("error", VARCHAR)],
    TASKS: [
        ("node_id", VARCHAR), ("task_id", VARCHAR), ("query_id", VARCHAR),
        ("state", VARCHAR), ("splits", BIGINT), ("bytes_out", BIGINT),
        ("output_rows", BIGINT), ("cache_hit", BIGINT),
        ("df_pruned", BIGINT), ("wall_s", DOUBLE), ("trace_id", VARCHAR)],
    NODES: [
        ("uri", VARCHAR), ("node_id", VARCHAR), ("state", VARCHAR),
        ("uptime_s", DOUBLE), ("task_count", BIGINT),
        ("tasks_created", BIGINT), ("drain_seconds", DOUBLE),
        ("drain_rejected", BIGINT), ("announce_age_s", DOUBLE),
        ("role", VARCHAR), ("queries_owned", BIGINT),
        ("journal_lag_s", DOUBLE)],
    PROFILE: [
        ("role", VARCHAR), ("purpose", VARCHAR), ("query_id", VARCHAR),
        ("stack", VARCHAR), ("samples", BIGINT)],
    MATERIALIZED_VIEWS: [
        ("name", VARCHAR), ("fingerprint", VARCHAR),
        ("tables", VARCHAR), ("incremental_capable", BIGINT),
        ("last_refresh_kind", VARCHAR),
        ("last_refresh_duration_s", DOUBLE),
        ("last_delta_rows", BIGINT), ("staleness_seconds", DOUBLE),
        ("pinned_bytes", BIGINT), ("refreshes", BIGINT)],
    METRICS_HISTORY: [
        ("name", VARCHAR), ("labels", VARCHAR),
        ("timestamp", DOUBLE), ("value", DOUBLE)],
    ALERTS: [
        ("rule", VARCHAR), ("state", VARCHAR), ("severity", VARCHAR),
        ("metric", VARCHAR), ("value", DOUBLE),
        ("threshold", DOUBLE), ("timestamp", DOUBLE)],
    METRICS: [
        ("name", VARCHAR), ("kind", VARCHAR), ("labels", VARCHAR),
        ("value", DOUBLE)],
}


def _host_table(name: str, schema: List[Tuple[str, Type]],
                rows: List[tuple]) -> HostTable:
    """Python rows -> the HostTable shape every scan path expects:
    string columns as int32 codes + a table-wide StringDict, numerics
    as typed arrays, None as a null-mask bit."""
    n = len(rows)
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}
    types: Dict[str, Type] = {}
    nulls: Dict[str, np.ndarray] = {}
    for i, (c, t) in enumerate(schema):
        vals = [r[i] for r in rows]
        types[c] = t
        nulls[c] = np.asarray([v is None for v in vals], bool)
        if t.is_string:
            d, codes = StringDict.build(
                np.asarray(["" if v is None else str(v) for v in vals],
                           dtype=object))
            arrays[c], dicts[c] = codes, d
        else:
            arrays[c] = np.asarray([0 if v is None else v for v in vals],
                                   dtype=t.dtype)
    return HostTable(name, n, arrays, types, dicts, nulls)


class SystemTablesConnector(SplitSource):
    """Facade: `system.*` names answer from cluster state, everything
    else reads/writes through the wrapped delegate connector."""

    NAME = "system"

    def __init__(self, delegate):
        self.delegate = delegate
        self._cluster = None

    def attach_cluster(self, cluster) -> None:
        """Late binding: TpuCluster installs the facade before its own
        membership/journal state exists, then attaches itself."""
        self._cluster = cluster

    # ----------------------------------------------------------- identity
    @staticmethod
    def is_system_table(table: Optional[str]) -> bool:
        return bool(table) and table in SYSTEM_SCHEMAS

    def connector_id(self, table: Optional[str] = None) -> str:
        if self.is_system_table(table):
            return self.NAME
        return self.delegate.connector_id(table)

    def table_splits(self, table: str, n_splits: int) -> List[dict]:
        if self.is_system_table(table):
            return [{"@type": self.NAME, "part": i, "numParts": n_splits}
                    for i in range(n_splits)]
        return self.delegate.table_splits(table, n_splits)

    def table_version(self, table: str) -> int:
        if self.is_system_table(table):
            # live state: a fresh version per call keys every fragment-
            # cache entry uniquely, so snapshots are never served stale
            return time.time_ns()
        return self.delegate.table_version(table)

    def bump_table_version(self, table: str) -> int:
        return self.delegate.bump_table_version(table)

    # -------------------------------------------------------------- reads
    def schema(self, table: str) -> List[Tuple[str, Type]]:
        if self.is_system_table(table):
            return list(SYSTEM_SCHEMAS[table])
        return self.delegate.schema(table)

    def row_count(self, table: str) -> int:
        if self.is_system_table(table):
            # planner estimate only — never pay a cluster fan-out at
            # plan time; system tables are small by construction
            return 128
        return self.delegate.row_count(table)

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        if not self.is_system_table(name):
            return self.delegate.table(name, part, num_parts)
        schema = SYSTEM_SCHEMAS[name]
        # one consistent snapshot per query: part 0 serves everything,
        # sibling tasks scan an empty slice (see module docstring)
        if part != 0:
            return _host_table(name, schema, [])
        try:
            rows = self._rows(name)
        except Exception:   # noqa: BLE001 — introspection never fails a query
            log.exception("system table %s snapshot failed", name)
            rows = []
        return _host_table(name, schema, rows)

    # everything else (exists/create/drop/append_rows/move_table_rows,
    # connector-specific surfaces) passes through so the facade is
    # write-transparent — hasattr(conn, "create") keeps answering for
    # exactly the connectors that are actually writable
    def __getattr__(self, attr):
        return getattr(self.delegate, attr)

    # ---------------------------------------------------------- providers
    def _rows(self, name: str) -> List[tuple]:
        if name == QUERIES:
            return self._query_rows()
        if name == TASKS:
            return self._task_rows()
        if name == NODES:
            return self._node_rows()
        if name == PROFILE:
            return self._profile_rows()
        if name == MATERIALIZED_VIEWS:
            return self._mv_rows()
        if name == METRICS_HISTORY:
            return self._metrics_history_rows()
        if name == ALERTS:
            return self._alert_rows()
        return self._metric_rows()

    def _query_rows(self) -> List[tuple]:
        rows: List[tuple] = []
        cl = self._cluster
        # finished cluster queries: the wide-event ledger already joins
        # the full stat surface per query — reuse it verbatim
        from presto_tpu.obs.wide_events import LEDGER
        for ev in LEDGER.snapshot():
            adm = ev.get("admission") or {}
            hbo = ev.get("hbo") or {}
            cache = ev.get("cache") or {}
            spool = ev.get("spool") or {}
            rows.append((
                ev.get("query_id"), "cluster", ev.get("state"),
                ev.get("user_name"), ev.get("query"),
                adm.get("group"), adm.get("queue_wait_s"),
                ev.get("wall_s"), ev.get("result_rows"),
                hbo.get("hits"), hbo.get("misses"),
                cache.get("cached_tasks"), spool.get("bytes_written"),
                ev.get("trace_id"), ev.get("error")))
        # statement front door: live dispatcher states (the journal's
        # in-flight view), matched by tests against GET /v1/status
        frontend = getattr(cl, "statement_frontend", None) \
            if cl is not None else None
        if frontend is not None:
            for q in list(frontend.queries.values()):
                rows.append((
                    q.qid, "statement", q.state, q.user, q.sql,
                    None, None, None, None, None, None, None, None,
                    None, q.error))
        return rows

    def _task_rows(self) -> List[tuple]:
        cl = self._cluster
        if cl is None:
            return []
        rows: List[tuple] = []
        uris = list(cl.worker_uris)
        uris += [u for u in sorted(set(cl.drained)) if u not in uris]
        for uri in uris:
            try:
                docs = cl.http.get_json(f"{uri}/v1/tasks",
                                        request_class="control",
                                        timeout=5.0)
            except Exception:   # noqa: BLE001 — a dying worker just drops out
                continue
            for d in docs:
                tid = str(d.get("taskId", ""))
                rows.append((
                    d.get("nodeId"), tid, tid.split(".", 1)[0] or None,
                    d.get("state"), d.get("splits"), d.get("bytesOut"),
                    d.get("outputRows"), int(bool(d.get("cacheHit"))),
                    d.get("dfPruned"), d.get("wallS"), d.get("traceId")))
        return rows

    def _node_rows(self) -> List[tuple]:
        cl = self._cluster
        if cl is None:
            return []
        dead, drained = set(cl.dead), set(cl.drained)
        announce: Dict[str, float] = {}
        disc = getattr(cl, "discovery", None)
        if disc is not None:
            for _nid, (uri, ts) in disc.snapshot().items():
                announce[uri] = ts
        now = time.time()
        rows: List[tuple] = []
        for uri in cl._probe_candidates():
            state = ("DEAD" if uri in dead
                     else "DRAINING" if uri in drained else "ACTIVE")
            node_id = uptime = tasks = created = None
            drain_s = rejected = None
            if state != "DEAD":
                try:
                    st = cl.http.get_json(f"{uri}/v1/status",
                                          request_class="control",
                                          timeout=5.0)
                    node_id = st.get("nodeId")
                    uptime = st.get("uptimeSeconds")
                    tasks = st.get("taskCount")
                    created = st.get("tasksCreated")
                    dr = st.get("drain") or {}
                    drain_s = dr.get("drainSeconds")
                    rejected = dr.get("rejected")
                    if str(st.get("nodeState", "")).upper() \
                            == "SHUTTING_DOWN":
                        state = "DRAINING"
                except Exception:   # noqa: BLE001 — probe verdict: unreachable
                    state = "DEAD"
            age = (now - announce[uri]) if uri in announce else None
            rows.append((uri, node_id, state, uptime, tasks, created,
                         drain_s, rejected, age, "worker", None, None))
        # coordinator rows (multi-coordinator HA): every statement
        # frontend over this engine registers in statement_frontends;
        # a fleet revive replaces the instance, so dedupe by base with
        # the LATEST registration winning
        fronts: Dict[str, object] = {}
        for f in getattr(cl, "statement_frontends", None) or []:
            fronts[f.base] = f
        for base, f in sorted(fronts.items()):
            state = "ACTIVE"
            uptime = lag = None
            owned = len(getattr(f, "queries", {}) or {})
            try:
                st = cl.http.get_json(f"{base}/v1/status",
                                      request_class="control",
                                      timeout=5.0)
                uptime = st.get("uptimeSeconds")
                owned = st.get("queryCount", owned)
                j = st.get("journal") or {}
                lag = j.get("lastAppendAgeS")
                if (st.get("ha") or {}).get("draining"):
                    state = "DRAINING"
            except Exception:   # noqa: BLE001 — probe verdict: unreachable
                state = "DEAD"
            rows.append((base, f.coordinator_id, state, uptime, None,
                         None, None, None, None, "coordinator", owned,
                         lag))
        return rows

    def _profile_rows(self) -> List[tuple]:
        from presto_tpu.obs.profiler import PROFILER
        return PROFILER.rows()

    def _mv_rows(self) -> List[tuple]:
        # non-creating read: a cluster with no MV statements yet has no
        # manager, and introspection must not conjure one
        mgr = getattr(self._cluster, "_mv_manager", None) \
            if self._cluster is not None else None
        if mgr is None:
            return []
        rows: List[tuple] = []
        for s in mgr.stats():
            rows.append((
                s["name"], s["fingerprint"],
                json.dumps(s["tables"], sort_keys=True),
                int(bool(s["incremental_capable"])),
                s["last_refresh_kind"], s["last_refresh_duration_s"],
                s["last_delta_rows"], s["staleness_seconds"],
                s["pinned_bytes"], s["refreshes"]))
        return rows

    def _metrics_history_rows(self) -> List[tuple]:
        tel = getattr(self._cluster, "telemetry", None) \
            if self._cluster is not None else None
        if tel is None:
            return []
        return list(tel.store.rows())

    def _alert_rows(self) -> List[tuple]:
        eng = getattr(self._cluster, "alerts", None) \
            if self._cluster is not None else None
        if eng is None:
            return []
        return list(eng.rows())

    def _metric_rows(self) -> List[tuple]:
        from presto_tpu.obs.metrics import REGISTRY
        rows: List[tuple] = []
        for mname in REGISTRY.names():
            m = REGISTRY.get(mname)
            kind = m.kind
            for sname, lnames, lvalues, value in m.samples():
                labels = json.dumps(dict(zip(lnames, lvalues)),
                                    sort_keys=True) if lnames else "{}"
                rows.append((sname, kind, labels, float(value)))
        return rows
