"""TPC-DS connector: deterministic in-memory data generation.

Reference role: presto-tpcds (presto-tpcds/src/main/java/com/facebook/
presto/tpcds/ — the second standard fixture connector; BASELINE.json names
the TPC-DS 99-query suite as a target harness, SURVEY.md §6).

Like the TPC-H generator (connectors/tpch.py), this is *spec-shaped*, not
bit-identical to dsdgen: table row-count ratios, surrogate-key ranges
(date_sk = julian day), dimension cross-products (customer/household
demographics), fact->dimension FK relationships, NULLable FK columns and
value distributions follow the TPC-DS spec so query selectivities are
realistic; exact values differ. Correctness tests compare against a
sqlite oracle over the SAME generated data.

Fixed-cardinality dimensions (date_dim 1900..2100, time_dim 86400,
demographics cross-products) are scale-independent, as in the spec; fact
tables scale with `scale_factor` (≈GB)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.expr.compile import days_from_civil
from presto_tpu.types import BIGINT, DATE, DOUBLE, INTEGER, VARCHAR, Type

# ---------------------------------------------------------------------------
# schema (column subset used by the implemented query set; same layout
# conventions as the reference's tpcds tables)
# ---------------------------------------------------------------------------

TPCDS_SCHEMA: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date_id", VARCHAR), ("d_date", DATE),
        ("d_month_seq", INTEGER), ("d_week_seq", INTEGER),
        ("d_quarter_seq", INTEGER), ("d_year", INTEGER), ("d_dow", INTEGER),
        ("d_moy", INTEGER), ("d_dom", INTEGER), ("d_qoy", INTEGER),
        ("d_day_name", VARCHAR),
        ("d_quarter_name", VARCHAR),
    ],
    "time_dim": [
        ("t_time_sk", BIGINT), ("t_time", INTEGER), ("t_hour", INTEGER),
        ("t_minute", INTEGER), ("t_second", INTEGER),
        ("t_meal_time", VARCHAR),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VARCHAR),
        ("i_item_desc", VARCHAR), ("i_current_price", DOUBLE),
        ("i_wholesale_cost", DOUBLE),
        ("i_brand_id", INTEGER), ("i_brand", VARCHAR),
        ("i_class_id", INTEGER), ("i_class", VARCHAR),
        ("i_category_id", INTEGER), ("i_category", VARCHAR),
        ("i_manufact_id", INTEGER), ("i_manufact", VARCHAR),
        ("i_manager_id", INTEGER), ("i_color", VARCHAR),
        ("i_units", VARCHAR), ("i_size", VARCHAR),
        ("i_product_name", VARCHAR),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VARCHAR),
        ("s_store_name", VARCHAR), ("s_number_employees", INTEGER),
        ("s_hours", VARCHAR), ("s_manager", VARCHAR),
        ("s_market_id", INTEGER), ("s_company_id", INTEGER),
        ("s_company_name", VARCHAR),
        ("s_city", VARCHAR), ("s_county", VARCHAR), ("s_state", VARCHAR),
        ("s_zip", VARCHAR), ("s_gmt_offset", DOUBLE),
    ],
    "warehouse": [
        ("w_warehouse_sk", BIGINT), ("w_warehouse_name", VARCHAR),
        ("w_warehouse_sq_ft", INTEGER), ("w_state", VARCHAR),
        ("w_country", VARCHAR),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VARCHAR),
        ("p_channel_dmail", VARCHAR), ("p_channel_email", VARCHAR),
        ("p_channel_tv", VARCHAR), ("p_channel_event", VARCHAR),
    ],
    "customer": [
        ("c_customer_sk", BIGINT), ("c_customer_id", VARCHAR),
        ("c_current_cdemo_sk", BIGINT), ("c_current_hdemo_sk", BIGINT),
        ("c_current_addr_sk", BIGINT), ("c_salutation", VARCHAR),
        ("c_first_name", VARCHAR),
        ("c_last_name", VARCHAR), ("c_preferred_cust_flag", VARCHAR),
        ("c_birth_day", INTEGER), ("c_birth_month", INTEGER),
        ("c_birth_year", INTEGER),
        ("c_birth_country", VARCHAR), ("c_login", VARCHAR),
        ("c_email_address", VARCHAR),
        ("c_last_review_date_sk", BIGINT),
    ],
    "customer_address": [
        ("ca_address_sk", BIGINT), ("ca_address_id", VARCHAR),
        ("ca_city", VARCHAR), ("ca_county", VARCHAR), ("ca_state", VARCHAR),
        ("ca_zip", VARCHAR), ("ca_country", VARCHAR),
        ("ca_gmt_offset", DOUBLE), ("ca_location_type", VARCHAR),
    ],
    "customer_demographics": [
        ("cd_demo_sk", BIGINT), ("cd_gender", VARCHAR),
        ("cd_marital_status", VARCHAR), ("cd_education_status", VARCHAR),
        ("cd_purchase_estimate", INTEGER), ("cd_credit_rating", VARCHAR),
        ("cd_dep_count", INTEGER), ("cd_dep_employed_count", INTEGER),
        ("cd_dep_college_count", INTEGER),
    ],
    "household_demographics": [
        ("hd_demo_sk", BIGINT), ("hd_income_band_sk", BIGINT),
        ("hd_buy_potential", VARCHAR), ("hd_dep_count", INTEGER),
        ("hd_vehicle_count", INTEGER),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_sold_time_sk", BIGINT),
        ("ss_item_sk", BIGINT), ("ss_customer_sk", BIGINT),
        ("ss_cdemo_sk", BIGINT), ("ss_hdemo_sk", BIGINT),
        ("ss_addr_sk", BIGINT), ("ss_store_sk", BIGINT),
        ("ss_promo_sk", BIGINT), ("ss_ticket_number", BIGINT),
        ("ss_quantity", INTEGER), ("ss_wholesale_cost", DOUBLE),
        ("ss_list_price", DOUBLE), ("ss_sales_price", DOUBLE),
        ("ss_ext_discount_amt", DOUBLE), ("ss_ext_sales_price", DOUBLE),
        ("ss_ext_wholesale_cost", DOUBLE), ("ss_ext_list_price", DOUBLE),
        ("ss_ext_tax", DOUBLE),
        ("ss_coupon_amt", DOUBLE), ("ss_net_paid", DOUBLE),
        ("ss_net_profit", DOUBLE),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", BIGINT), ("cs_sold_time_sk", BIGINT),
        ("cs_ship_date_sk", BIGINT), ("cs_bill_customer_sk", BIGINT),
        ("cs_bill_cdemo_sk", BIGINT), ("cs_bill_addr_sk", BIGINT),
        ("cs_ship_addr_sk", BIGINT), ("cs_ship_customer_sk", BIGINT),
        ("cs_warehouse_sk", BIGINT), ("cs_ship_mode_sk", BIGINT),
        ("cs_call_center_sk", BIGINT),
        ("cs_item_sk", BIGINT), ("cs_promo_sk", BIGINT),
        ("cs_order_number", BIGINT), ("cs_quantity", INTEGER),
        ("cs_wholesale_cost", DOUBLE), ("cs_list_price", DOUBLE),
        ("cs_sales_price", DOUBLE), ("cs_ext_discount_amt", DOUBLE),
        ("cs_ext_sales_price", DOUBLE),
        ("cs_ext_wholesale_cost", DOUBLE),
        ("cs_ext_list_price", DOUBLE), ("cs_ext_ship_cost", DOUBLE),
        ("cs_coupon_amt", DOUBLE), ("cs_net_paid", DOUBLE),
        ("cs_net_profit", DOUBLE),
    ],
    "web_sales": [
        ("ws_sold_date_sk", BIGINT), ("ws_sold_time_sk", BIGINT),
        ("ws_ship_date_sk", BIGINT), ("ws_item_sk", BIGINT),
        ("ws_bill_customer_sk", BIGINT),
        ("ws_ship_customer_sk", BIGINT), ("ws_bill_addr_sk", BIGINT),
        ("ws_ship_addr_sk", BIGINT), ("ws_warehouse_sk", BIGINT),
        ("ws_ship_mode_sk", BIGINT), ("ws_ship_hdemo_sk", BIGINT),
        ("ws_web_page_sk", BIGINT),
        ("ws_web_site_sk", BIGINT), ("ws_promo_sk", BIGINT),
        ("ws_order_number", BIGINT), ("ws_quantity", INTEGER),
        ("ws_wholesale_cost", DOUBLE), ("ws_list_price", DOUBLE),
        ("ws_sales_price", DOUBLE), ("ws_ext_discount_amt", DOUBLE),
        ("ws_ext_sales_price", DOUBLE),
        ("ws_ext_wholesale_cost", DOUBLE),
        ("ws_ext_list_price", DOUBLE),
        ("ws_ext_ship_cost", DOUBLE),
        ("ws_net_paid", DOUBLE), ("ws_net_profit", DOUBLE),
    ],
    "inventory": [
        ("inv_date_sk", BIGINT), ("inv_item_sk", BIGINT),
        ("inv_warehouse_sk", BIGINT), ("inv_quantity_on_hand", INTEGER),
    ],
    "store_returns": [
        ("sr_returned_date_sk", BIGINT), ("sr_return_time_sk", BIGINT),
        ("sr_item_sk", BIGINT), ("sr_customer_sk", BIGINT),
        ("sr_cdemo_sk", BIGINT), ("sr_hdemo_sk", BIGINT),
        ("sr_addr_sk", BIGINT), ("sr_store_sk", BIGINT),
        ("sr_reason_sk", BIGINT), ("sr_ticket_number", BIGINT),
        ("sr_return_quantity", INTEGER), ("sr_return_amt", DOUBLE),
        ("sr_return_tax", DOUBLE), ("sr_return_amt_inc_tax", DOUBLE),
        ("sr_fee", DOUBLE), ("sr_return_ship_cost", DOUBLE),
        ("sr_refunded_cash", DOUBLE), ("sr_reversed_charge", DOUBLE),
        ("sr_store_credit", DOUBLE), ("sr_net_loss", DOUBLE),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", BIGINT), ("cr_returned_time_sk", BIGINT),
        ("cr_item_sk", BIGINT), ("cr_refunded_customer_sk", BIGINT),
        ("cr_returning_customer_sk", BIGINT),
        ("cr_returning_addr_sk", BIGINT), ("cr_call_center_sk", BIGINT),
        ("cr_catalog_page_sk", BIGINT), ("cr_reason_sk", BIGINT),
        ("cr_order_number", BIGINT), ("cr_return_quantity", INTEGER),
        ("cr_return_amount", DOUBLE), ("cr_return_tax", DOUBLE),
        ("cr_fee", DOUBLE), ("cr_return_ship_cost", DOUBLE),
        ("cr_refunded_cash", DOUBLE), ("cr_reversed_charge", DOUBLE),
        ("cr_store_credit", DOUBLE), ("cr_net_loss", DOUBLE),
    ],
    "web_returns": [
        ("wr_returned_date_sk", BIGINT), ("wr_returned_time_sk", BIGINT),
        ("wr_item_sk", BIGINT), ("wr_refunded_customer_sk", BIGINT),
        ("wr_refunded_cdemo_sk", BIGINT), ("wr_refunded_addr_sk", BIGINT),
        ("wr_returning_customer_sk", BIGINT),
        ("wr_returning_cdemo_sk", BIGINT),
        ("wr_returning_addr_sk", BIGINT), ("wr_web_page_sk", BIGINT),
        ("wr_reason_sk", BIGINT), ("wr_order_number", BIGINT),
        ("wr_return_quantity", INTEGER), ("wr_return_amt", DOUBLE),
        ("wr_return_tax", DOUBLE), ("wr_fee", DOUBLE),
        ("wr_return_ship_cost", DOUBLE), ("wr_refunded_cash", DOUBLE),
        ("wr_reversed_charge", DOUBLE), ("wr_account_credit", DOUBLE),
        ("wr_net_loss", DOUBLE),
    ],
    "reason": [
        ("r_reason_sk", BIGINT), ("r_reason_id", VARCHAR),
        ("r_reason_desc", VARCHAR),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", BIGINT), ("sm_ship_mode_id", VARCHAR),
        ("sm_type", VARCHAR), ("sm_code", VARCHAR),
        ("sm_carrier", VARCHAR),
    ],
    "income_band": [
        ("ib_income_band_sk", BIGINT), ("ib_lower_bound", INTEGER),
        ("ib_upper_bound", INTEGER),
    ],
    "web_page": [
        ("wp_web_page_sk", BIGINT), ("wp_web_page_id", VARCHAR),
        ("wp_url", VARCHAR), ("wp_type", VARCHAR),
        ("wp_char_count", INTEGER), ("wp_link_count", INTEGER),
    ],
    "web_site": [
        ("web_site_sk", BIGINT), ("web_site_id", VARCHAR),
        ("web_name", VARCHAR), ("web_manager", VARCHAR),
        ("web_company_name", VARCHAR), ("web_gmt_offset", DOUBLE),
    ],
    "call_center": [
        ("cc_call_center_sk", BIGINT), ("cc_call_center_id", VARCHAR),
        ("cc_name", VARCHAR), ("cc_manager", VARCHAR),
        ("cc_county", VARCHAR),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", BIGINT), ("cp_catalog_page_id", VARCHAR),
        ("cp_department", VARCHAR), ("cp_type", VARCHAR),
    ],
}

_D0 = days_from_civil(1900, 1, 1)
_D1 = days_from_civil(2100, 1, 1)
_DATE_SK0 = 2415022                       # julian day of 1900-01-01
_N_DATES = _D1 - _D0                      # 73049 rows, per spec
                                          # (1900-01-01 .. 2099-12-31)

_SALES_D0 = days_from_civil(1998, 1, 1)
_SALES_D1 = days_from_civil(2002, 12, 31)

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES_PER_CAT = 10
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
_STATES = ["AL", "CA", "CO", "FL", "GA", "IL", "IN", "KS", "KY", "LA",
           "MI", "MN", "MO", "NC", "NE", "NY", "OH", "OK", "OR", "PA",
           "SD", "TN", "TX", "VA", "WA", "WI"]
_COUNTIES = ["Ziebach County", "Walker County", "Daviess County",
             "Barrow County", "Fairfield County", "Luce County",
             "Richland County", "Bronx County", "Orange County",
             "Williamson County"]
_CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
           "Liberty", "Oakland", "Riverside", "Glendale", "Springdale",
           "Union", "Salem", "Greenfield", "Pleasant Hill", "Lakeview"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_MARITAL = ["M", "S", "D", "W", "U"]
_COLORS = ["slate", "blanched", "burnished", "peach", "saddle", "navy",
           "salmon", "powder", "metallic", "smoke", "misty", "frosted",
           "aquamarine", "dodger", "chiffon", "rose", "beige", "pale"]
_SIZES = ["small", "medium", "large", "extra large", "economy", "N/A",
          "petite"]
_UNITS = ["Ounce", "Oz", "Bunch", "Ton", "N/A", "Dozen", "Box", "Pound",
          "Pallet", "Gross", "Cup", "Dram", "Each", "Tbl", "Lb",
          "Bundle", "Case", "Carton"]
_MEALS = ["breakfast", "lunch", "dinner", ""]
_COUNTRIES = ["United States"]
_FIRST = ["James", "Mary", "John", "Linda", "Robert", "Susan", "Michael",
          "Karen", "William", "Lisa", "David", "Nancy", "Richard", "Betty"]
_LAST = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
         "Miller", "Davis", "Wilson", "Anderson", "Taylor", "Thomas"]

# spec row counts at SF1; fact tables scale linearly, dims sub-linearly
_SF1 = {"store_sales": 2_880_000, "catalog_sales": 1_440_000,
        "web_sales": 720_000, "item": 18_000, "customer": 100_000,
        "customer_address": 50_000, "store": 12, "warehouse": 5,
        "promotion": 300}


def _counts(sf: float) -> Dict[str, int]:
    def lin(base, floor):
        return max(floor, int(base * sf))
    return {
        "store_sales": lin(_SF1["store_sales"], 1000),
        "catalog_sales": lin(_SF1["catalog_sales"], 500),
        "web_sales": lin(_SF1["web_sales"], 250),
        "item": lin(_SF1["item"], 200),
        "customer": lin(_SF1["customer"], 300),
        "customer_address": lin(_SF1["customer_address"], 150),
        "store": max(4, int(_SF1["store"] * max(sf, 0.4))),
        "warehouse": max(3, int(_SF1["warehouse"] * max(sf, 0.6))),
        "promotion": lin(_SF1["promotion"], 30),
    }


def _seed(name: str, sf: float) -> int:
    import zlib
    return zlib.crc32(f"tpcds|{name}|{sf}".encode())


def _dictify(arrays, dicts, col, vals):
    d, codes = StringDict.build(vals)
    arrays[col], dicts[col] = codes, d


def _ht(name, n, arrays, dicts, nulls=None) -> HostTable:
    return HostTable(name, n, arrays, dict(TPCDS_SCHEMA[name]), dicts,
                     nulls)


@functools.lru_cache(maxsize=64)
def _gen(name: str, sf: float) -> HostTable:
    c = _counts(sf)
    rng = np.random.default_rng(_seed(name, sf))
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}

    def put_str(col, vals):
        _dictify(arrays, dicts, col, vals)

    if name == "date_dim":
        days = np.arange(_D0, _D1, dtype=np.int64)
        n = len(days)
        arrays["d_date_sk"] = _DATE_SK0 + (days - _D0)
        put_str("d_date_id", np.char.add(
            "D", (_DATE_SK0 + days - _D0).astype(str)).astype(object))
        arrays["d_date"] = days.astype(np.int32)
        # civil fields via numpy datetime64 (exact)
        dt = (days.astype("datetime64[D]"))
        y = dt.astype("datetime64[Y]").astype(int) + 1970
        m = dt.astype("datetime64[M]").astype(int) % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        arrays["d_year"] = y.astype(np.int32)
        arrays["d_moy"] = m.astype(np.int32)
        arrays["d_dom"] = dom.astype(np.int32)
        arrays["d_qoy"] = ((m - 1) // 3 + 1).astype(np.int32)
        # 1900-01-01 was a Monday; spec d_dow: 0=Sunday
        dow = ((days - _D0) + 1) % 7
        arrays["d_dow"] = dow.astype(np.int32)
        put_str("d_day_name",
                np.asarray(_DAY_NAMES, dtype=object)[dow])
        put_str("d_quarter_name", np.char.add(
            np.char.add(y.astype(str), "Q"),
            ((m - 1) // 3 + 1).astype(str)).astype(object))
        arrays["d_month_seq"] = ((y - 1900) * 12 + (m - 1)).astype(np.int32)
        arrays["d_week_seq"] = ((days - _D0) // 7 + 1).astype(np.int32)
        arrays["d_quarter_seq"] = ((y - 1900) * 4 + (m - 1) // 3 + 1
                                   ).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "time_dim":
        t = np.arange(86400, dtype=np.int64)
        arrays["t_time_sk"] = t
        arrays["t_time"] = t.astype(np.int32)
        hour = (t // 3600).astype(np.int32)
        arrays["t_hour"] = hour
        arrays["t_minute"] = ((t % 3600) // 60).astype(np.int32)
        arrays["t_second"] = (t % 60).astype(np.int32)
        meal = np.where(hour < 9, "breakfast",
                        np.where(hour < 14, "lunch",
                                 np.where(hour < 22, "dinner", "")))
        put_str("t_meal_time", meal.astype(object))
        return _ht(name, 86400, arrays, dicts)

    if name == "item":
        n = c["item"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["i_item_sk"] = sk
        put_str("i_item_id", np.char.add("AAAAAAAA",
                np.char.zfill(sk.astype(str), 8)).astype(object))
        put_str("i_item_desc", np.char.add("item description ",
                (sk % 997).astype(str)).astype(object))
        arrays["i_current_price"] = np.round(
            rng.uniform(0.09, 99.99, size=n), 2)
        arrays["i_wholesale_cost"] = np.round(
            arrays["i_current_price"] * rng.uniform(0.4, 0.8, size=n), 2)
        cat_id = rng.integers(1, len(_CATEGORIES) + 1, size=n)
        arrays["i_category_id"] = cat_id.astype(np.int32)
        put_str("i_category",
                np.asarray(_CATEGORIES, dtype=object)[cat_id - 1])
        class_id = rng.integers(1, _CLASSES_PER_CAT + 1, size=n)
        arrays["i_class_id"] = class_id.astype(np.int32)
        put_str("i_class", np.char.add(
            np.char.add(np.asarray(_CATEGORIES)[cat_id - 1].astype(str),
                        " class "),
            class_id.astype(str)).astype(object))
        brand_id = (cat_id * 1000000 + class_id * 10000
                    + rng.integers(1, 100, size=n)).astype(np.int32)
        arrays["i_brand_id"] = brand_id
        put_str("i_brand", np.char.add("brand#",
                brand_id.astype(str)).astype(object))
        man_id = rng.integers(1, 1001, size=n)
        arrays["i_manufact_id"] = man_id.astype(np.int32)
        put_str("i_manufact", np.char.add("manufact#",
                man_id.astype(str)).astype(object))
        arrays["i_manager_id"] = rng.integers(
            1, 101, size=n).astype(np.int32)
        put_str("i_color", np.asarray(_COLORS, dtype=object)[
            rng.integers(0, len(_COLORS), size=n)])
        put_str("i_units", np.asarray(_UNITS, dtype=object)[
            rng.integers(0, len(_UNITS), size=n)])
        put_str("i_size", np.asarray(_SIZES, dtype=object)[
            rng.integers(0, len(_SIZES), size=n)])
        put_str("i_product_name", np.char.add("product",
                np.char.zfill(sk.astype(str), 7)).astype(object))
        return _ht(name, n, arrays, dicts)

    if name == "store":
        n = c["store"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["s_store_sk"] = sk
        put_str("s_store_id", np.char.add("S", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("s_store_name", np.asarray(
            ["ought", "able", "pri", "ese", "anti", "cally", "ation",
             "eing", "n st", "bar", "ought2", "able2"],
            dtype=object)[(sk - 1) % 12])
        arrays["s_number_employees"] = rng.integers(
            200, 301, size=n).astype(np.int32)
        put_str("s_hours", np.asarray(["8AM-8AM", "8AM-4PM", "8AM-12AM"],
                                      dtype=object)[(sk - 1) % 3])
        put_str("s_manager", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        arrays["s_market_id"] = rng.integers(1, 11, size=n).astype(np.int32)
        arrays["s_company_id"] = np.ones(n, dtype=np.int32)
        put_str("s_company_name", np.asarray(["Unknown"], dtype=object)[
            np.zeros(n, dtype=np.int64)])
        put_str("s_city", np.asarray(_CITIES, dtype=object)[
            rng.integers(0, len(_CITIES), size=n)])
        put_str("s_county", np.asarray(_COUNTIES, dtype=object)[
            rng.integers(0, len(_COUNTIES), size=n)])
        put_str("s_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("s_zip", np.char.zfill(rng.integers(
            10000, 99999, size=n).astype(str), 5).astype(object))
        arrays["s_gmt_offset"] = np.full(n, -5.0)
        return _ht(name, n, arrays, dicts)

    if name == "warehouse":
        n = c["warehouse"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["w_warehouse_sk"] = sk
        put_str("w_warehouse_name", np.char.add("Warehouse ",
                sk.astype(str)).astype(object))
        arrays["w_warehouse_sq_ft"] = rng.integers(
            50000, 1000001, size=n).astype(np.int32)
        put_str("w_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("w_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        return _ht(name, n, arrays, dicts)

    if name == "promotion":
        n = c["promotion"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["p_promo_sk"] = sk
        put_str("p_promo_id", np.char.add("P", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        for col in ("p_channel_dmail", "p_channel_email", "p_channel_tv",
                    "p_channel_event"):
            put_str(col, np.where(rng.random(n) < 0.5, "Y", "N")
                    .astype(object))
        return _ht(name, n, arrays, dicts)

    if name == "customer_demographics":
        # cross product of (gender, marital, education, credit,
        # purchase_estimate) — a fixed dimension, as in the spec
        combos = [(g, m, e, cr, pe)
                  for g in ("M", "F") for m in _MARITAL
                  for e in _EDUCATION for cr in _CREDIT
                  for pe in range(500, 10001, 500)]
        n = len(combos)
        arrays["cd_demo_sk"] = np.arange(1, n + 1, dtype=np.int64)
        put_str("cd_gender", np.asarray([x[0] for x in combos],
                                        dtype=object))
        put_str("cd_marital_status", np.asarray([x[1] for x in combos],
                                                dtype=object))
        put_str("cd_education_status", np.asarray([x[2] for x in combos],
                                                  dtype=object))
        put_str("cd_credit_rating", np.asarray([x[3] for x in combos],
                                               dtype=object))
        arrays["cd_purchase_estimate"] = np.asarray(
            [x[4] for x in combos], dtype=np.int32)
        i = np.arange(n)
        arrays["cd_dep_count"] = (i % 7).astype(np.int32)
        arrays["cd_dep_employed_count"] = ((i // 7) % 7).astype(np.int32)
        arrays["cd_dep_college_count"] = ((i // 49) % 7).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "household_demographics":
        combos = [(ib, bp, dep, veh)
                  for ib in range(1, 21) for bp in _BUY_POTENTIAL
                  for dep in range(0, 10) for veh in range(-1, 5)]
        n = len(combos)
        arrays["hd_demo_sk"] = np.arange(1, n + 1, dtype=np.int64)
        arrays["hd_income_band_sk"] = np.asarray(
            [x[0] for x in combos], dtype=np.int64)
        put_str("hd_buy_potential", np.asarray([x[1] for x in combos],
                                               dtype=object))
        arrays["hd_dep_count"] = np.asarray([x[2] for x in combos],
                                            dtype=np.int32)
        arrays["hd_vehicle_count"] = np.asarray([x[3] for x in combos],
                                                dtype=np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "customer_address":
        n = c["customer_address"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["ca_address_sk"] = sk
        put_str("ca_address_id", np.char.add("A", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("ca_city", np.asarray(_CITIES, dtype=object)[
            rng.integers(0, len(_CITIES), size=n)])
        put_str("ca_county", np.asarray(_COUNTIES, dtype=object)[
            rng.integers(0, len(_COUNTIES), size=n)])
        put_str("ca_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("ca_zip", np.char.zfill(rng.integers(
            10000, 99999, size=n).astype(str), 5).astype(object))
        put_str("ca_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        arrays["ca_gmt_offset"] = rng.choice(
            [-5.0, -6.0, -7.0, -8.0], size=n)
        put_str("ca_location_type", np.asarray(
            ["apartment", "condo", "single family"], dtype=object)[
            rng.integers(0, 3, size=n)])
        return _ht(name, n, arrays, dicts)

    if name == "customer":
        n = c["customer"]
        ncd = _gen("customer_demographics", sf).num_rows
        nhd = _gen("household_demographics", sf).num_rows
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["c_customer_sk"] = sk
        put_str("c_customer_id", np.char.add("C", np.char.zfill(
            sk.astype(str), 15)).astype(object))
        arrays["c_current_cdemo_sk"] = rng.integers(
            1, ncd + 1, size=n).astype(np.int64)
        arrays["c_current_hdemo_sk"] = rng.integers(
            1, nhd + 1, size=n).astype(np.int64)
        arrays["c_current_addr_sk"] = rng.integers(
            1, c["customer_address"] + 1, size=n).astype(np.int64)
        put_str("c_salutation", np.asarray(
            ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"], dtype=object)[
            rng.integers(0, 6, size=n)])
        put_str("c_first_name", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        put_str("c_last_name", np.asarray(_LAST, dtype=object)[
            rng.integers(0, len(_LAST), size=n)])
        put_str("c_preferred_cust_flag",
                np.where(rng.random(n) < 0.5, "Y", "N").astype(object))
        arrays["c_birth_day"] = rng.integers(
            1, 29, size=n).astype(np.int32)
        arrays["c_birth_month"] = rng.integers(
            1, 13, size=n).astype(np.int32)
        arrays["c_birth_year"] = rng.integers(
            1924, 1993, size=n).astype(np.int32)
        put_str("c_birth_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        put_str("c_login", np.char.add("login", sk.astype(str))
                .astype(object))
        put_str("c_email_address", np.char.add(
            np.char.add("c", sk.astype(str)), "@example.com")
            .astype(object))
        arrays["c_last_review_date_sk"] = (
            _DATE_SK0 + (rng.integers(_SALES_D0, _SALES_D1 + 1, size=n)
                         - _D0)).astype(np.int64)
        return _ht(name, n, arrays, dicts)

    if name in ("store_sales", "catalog_sales", "web_sales"):
        return _gen_sales(name, sf)

    if name in ("store_returns", "catalog_returns", "web_returns"):
        return _gen_returns(name, sf)

    if name == "reason":
        descs = ["Package was damaged", "Stopped working",
                 "Did not get it on time", "Not the product ordered",
                 "Parts missing", "Does not work with a product bought",
                 "Gift exchange", "Did not like the color",
                 "Did not like the model", "Did not like the make",
                 "Found a better price", "Found a better extension",
                 "No service location", "Not working any more",
                 "Did not fit", "Wrong size", "Lost my job",
                 "unknown", "duplicate purchase", "its is a boy",
                 "its is a girl", "reason 22", "reason 23", "reason 24",
                 "reason 25", "reason 26", "reason 27", "reason 28",
                 "reason 29", "reason 30", "reason 31", "reason 32",
                 "reason 33", "reason 34", "reason 35"]
        n = len(descs)
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["r_reason_sk"] = sk
        put_str("r_reason_id", np.char.add("R", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("r_reason_desc", np.asarray(descs, dtype=object))
        return _ht(name, n, arrays, dicts)

    if name == "ship_mode":
        types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                 "TWO DAY"]
        carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS",
                    "ZHOU", "LATVIAN"]
        n = 20
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["sm_ship_mode_sk"] = sk
        put_str("sm_ship_mode_id", np.char.add("M", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("sm_type",
                np.asarray(types, dtype=object)[(sk - 1) % len(types)])
        put_str("sm_code", np.asarray(["AIR", "SURFACE", "SEA"],
                                      dtype=object)[(sk - 1) % 3])
        put_str("sm_carrier", np.asarray(carriers, dtype=object)[
            (sk - 1) % len(carriers)])
        return _ht(name, n, arrays, dicts)

    if name == "income_band":
        n = 20
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["ib_income_band_sk"] = sk
        arrays["ib_lower_bound"] = ((sk - 1) * 10000).astype(np.int32)
        arrays["ib_upper_bound"] = (sk * 10000).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "web_page":
        n = 60
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["wp_web_page_sk"] = sk
        put_str("wp_web_page_id", np.char.add("P", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("wp_url", np.asarray(["http://www.foo.com"],
                                     dtype=object)[np.zeros(n, np.int64)])
        put_str("wp_type", np.asarray(
            ["general", "order", "feedback", "ad", "welcome",
             "protected", "dynamic"], dtype=object)[(sk - 1) % 7])
        arrays["wp_char_count"] = rng.integers(
            300, 8000, size=n).astype(np.int32)
        arrays["wp_link_count"] = rng.integers(
            2, 25, size=n).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "web_site":
        names_ = ["site_0", "site_1", "site_2", "site_3"]
        n = 4 * 2
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["web_site_sk"] = sk
        put_str("web_site_id", np.char.add("W", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("web_name", np.asarray(names_, dtype=object)[
            (sk - 1) % len(names_)])
        put_str("web_manager", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        put_str("web_company_name", np.asarray(["pri"], dtype=object)[
            np.zeros(n, np.int64)])
        arrays["web_gmt_offset"] = np.full(n, -5.0)
        return _ht(name, n, arrays, dicts)

    if name == "call_center":
        names_ = ["NY Metro", "Mid Atlantic", "Pacific Northwest",
                  "North Midwest", "California", "New England"]
        n = len(names_)
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["cc_call_center_sk"] = sk
        put_str("cc_call_center_id", np.char.add("CC", np.char.zfill(
            sk.astype(str), 8)).astype(object))
        put_str("cc_name", np.asarray(names_, dtype=object))
        put_str("cc_manager", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        put_str("cc_county", np.asarray(_COUNTIES, dtype=object)[
            rng.integers(0, len(_COUNTIES), size=n)])
        return _ht(name, n, arrays, dicts)

    if name == "catalog_page":
        n = 300
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["cp_catalog_page_sk"] = sk
        put_str("cp_catalog_page_id", np.char.add("CP", np.char.zfill(
            sk.astype(str), 8)).astype(object))
        put_str("cp_department", np.asarray(["DEPARTMENT"],
                                            dtype=object)[
            np.zeros(n, np.int64)])
        put_str("cp_type", np.asarray(
            ["bi-annual", "quarterly", "monthly"], dtype=object)[
            (sk - 1) % 3])
        return _ht(name, n, arrays, dicts)

    if name == "inventory":
        # weekly snapshots over one year x items x warehouses (bounded)
        nit = min(c["item"], 400)
        nw = c["warehouse"]
        week_days = np.arange(_SALES_D0, _SALES_D0 + 364, 7,
                              dtype=np.int64)
        n = len(week_days) * nit * nw
        d = np.repeat(week_days, nit * nw)
        it = np.tile(np.repeat(np.arange(1, nit + 1, dtype=np.int64), nw),
                     len(week_days))
        wh = np.tile(np.arange(1, nw + 1, dtype=np.int64),
                     len(week_days) * nit)
        arrays["inv_date_sk"] = _DATE_SK0 + (d - _D0)
        arrays["inv_item_sk"] = it
        arrays["inv_warehouse_sk"] = wh
        q = rng.integers(0, 1001, size=n).astype(np.int32)
        arrays["inv_quantity_on_hand"] = q
        return _ht(name, n, arrays, dicts)

    raise KeyError(f"unknown tpcds table {name}")


_SALES_PREFIX = {"store_sales": "ss", "catalog_sales": "cs",
                 "web_sales": "ws"}

_RETURNS_OF = {"store_returns": "store_sales",
               "catalog_returns": "catalog_sales",
               "web_returns": "web_sales"}


@functools.lru_cache(maxsize=16)
def _gen_returns(name: str, sf: float) -> HostTable:
    """Returns facts derived from their sales tables (~9% return rate),
    so (ticket/order, item) join keys reference REAL sales rows — the
    spec's sales->returns lineage that q1/q17/q25/q94-style joins rely
    on."""
    sales = _gen_sales(_RETURNS_OF[name], sf)
    rng = np.random.default_rng(_seed(name, sf))
    n_sales = sales.num_rows
    mask = rng.random(n_sales) < 0.09
    idx = np.nonzero(mask)[0]
    n = len(idx)

    def scol(col):
        return sales.arrays[col][:n_sales][idx]

    def snull(col):
        m = (sales.nulls or {}).get(col)
        return None if m is None else m[:n_sales][idx]

    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}
    nulls: Dict[str, np.ndarray] = {}

    qty = scol({"store_returns": "ss_quantity",
                "catalog_returns": "cs_quantity",
                "web_returns": "ws_quantity"}[name])
    price = scol({"store_returns": "ss_sales_price",
                  "catalog_returns": "cs_sales_price",
                  "web_returns": "ws_sales_price"}[name])
    ret_qty = np.minimum(rng.integers(1, 101, size=n), qty).astype(
        np.int32)
    amt = np.round(price * ret_qty, 2)
    tax = np.round(amt * 0.05, 2)
    fee = np.round(rng.uniform(0.5, 100.0, size=n), 2)
    ship = np.round(amt * 0.12, 2)
    cash = np.round(amt * rng.uniform(0.0, 1.0, size=n), 2)
    reverse = np.round((amt - cash) * rng.uniform(0, 1, size=n), 2)
    credit = np.round(amt - cash - reverse, 2)
    loss = np.round(fee + ship + tax * 0.5, 2)
    n_reason = len(_gen("reason", sf).arrays["r_reason_sk"])
    reason = rng.integers(1, n_reason + 1, size=n).astype(np.int64)
    ret_time = rng.integers(0, 86400, size=n).astype(np.int64)

    def put(col, vals, null_src=None, null_rate=0.0):
        arrays[col] = vals
        m = snull(null_src) if null_src else None
        if null_rate > 0.0:
            extra = rng.random(n) < null_rate
            m = extra if m is None else (m | extra)
        if m is not None and m.any():
            nulls[col] = m

    if name == "store_returns":
        sold = scol("ss_sold_date_sk")
        put("sr_returned_date_sk",
            sold + rng.integers(1, 91, size=n), null_rate=0.01)
        put("sr_return_time_sk", ret_time)
        put("sr_item_sk", scol("ss_item_sk"))
        put("sr_customer_sk", scol("ss_customer_sk"),
            null_src="ss_customer_sk")
        put("sr_cdemo_sk", scol("ss_cdemo_sk"), null_src="ss_cdemo_sk")
        put("sr_hdemo_sk", scol("ss_hdemo_sk"), null_src="ss_hdemo_sk")
        put("sr_addr_sk", scol("ss_addr_sk"), null_src="ss_addr_sk")
        put("sr_store_sk", scol("ss_store_sk"), null_src="ss_store_sk")
        put("sr_reason_sk", reason, null_rate=0.02)
        put("sr_ticket_number", scol("ss_ticket_number"))
        put("sr_return_quantity", ret_qty)
        put("sr_return_amt", amt)
        put("sr_return_tax", tax)
        put("sr_return_amt_inc_tax", np.round(amt + tax, 2))
        put("sr_fee", fee)
        put("sr_return_ship_cost", ship)
        put("sr_refunded_cash", cash)
        put("sr_reversed_charge", reverse)
        put("sr_store_credit", credit)
        put("sr_net_loss", loss)
    elif name == "catalog_returns":
        sold = scol("cs_sold_date_sk")
        ncc = 6
        put("cr_returned_date_sk", sold + rng.integers(1, 91, size=n))
        put("cr_returned_time_sk", ret_time)
        put("cr_item_sk", scol("cs_item_sk"))
        put("cr_refunded_customer_sk", scol("cs_bill_customer_sk"),
            null_src="cs_bill_customer_sk")
        put("cr_returning_customer_sk", scol("cs_bill_customer_sk"),
            null_src="cs_bill_customer_sk")
        put("cr_returning_addr_sk", scol("cs_bill_addr_sk"),
            null_src="cs_bill_addr_sk")
        put("cr_call_center_sk",
            rng.integers(1, ncc + 1, size=n).astype(np.int64),
            null_rate=0.02)
        put("cr_catalog_page_sk",
            rng.integers(1, 301, size=n).astype(np.int64))
        put("cr_reason_sk", reason, null_rate=0.02)
        put("cr_order_number", scol("cs_order_number"))
        put("cr_return_quantity", ret_qty)
        put("cr_return_amount", amt)
        put("cr_return_tax", tax)
        put("cr_fee", fee)
        put("cr_return_ship_cost", ship)
        put("cr_refunded_cash", cash)
        put("cr_reversed_charge", reverse)
        put("cr_store_credit", credit)
        put("cr_net_loss", loss)
    else:
        sold = scol("ws_sold_date_sk")
        put("wr_returned_date_sk", sold + rng.integers(1, 91, size=n))
        put("wr_returned_time_sk", ret_time)
        put("wr_item_sk", scol("ws_item_sk"))
        put("wr_refunded_customer_sk", scol("ws_bill_customer_sk"),
            null_src="ws_bill_customer_sk")
        put("wr_refunded_cdemo_sk",
            rng.integers(1, _gen("customer_demographics", sf).num_rows
                         + 1, size=n).astype(np.int64), null_rate=0.02)
        put("wr_refunded_addr_sk", scol("ws_bill_addr_sk"),
            null_src="ws_bill_addr_sk")
        put("wr_returning_customer_sk", scol("ws_bill_customer_sk"),
            null_src="ws_bill_customer_sk")
        put("wr_returning_cdemo_sk",
            rng.integers(1, _gen("customer_demographics", sf).num_rows
                         + 1, size=n).astype(np.int64), null_rate=0.02)
        put("wr_returning_addr_sk", scol("ws_bill_addr_sk"),
            null_src="ws_bill_addr_sk")
        put("wr_web_page_sk",
            rng.integers(1, 61, size=n).astype(np.int64),
            null_rate=0.02)
        put("wr_reason_sk", reason, null_rate=0.02)
        put("wr_order_number", scol("ws_order_number"))
        put("wr_return_quantity", ret_qty)
        put("wr_return_amt", amt)
        put("wr_return_tax", tax)
        put("wr_fee", fee)
        put("wr_return_ship_cost", ship)
        put("wr_refunded_cash", cash)
        put("wr_reversed_charge", reverse)
        put("wr_account_credit", credit)
        put("wr_net_loss", loss)
    return _ht(name, n, arrays, dicts, nulls or None)


@functools.lru_cache(maxsize=16)
def _gen_sales(name: str, sf: float) -> HostTable:
    c = _counts(sf)
    rng = np.random.default_rng(_seed(name, sf))
    n = c[name]
    ncd = _gen("customer_demographics", sf).num_rows
    nhd = _gen("household_demographics", sf).num_rows
    nit = c["item"]

    days = rng.integers(_SALES_D0, _SALES_D1 + 1, size=n).astype(np.int64)
    date_sk = _DATE_SK0 + (days - _D0)
    time_sk = rng.integers(0, 86400, size=n).astype(np.int64)
    item = rng.integers(1, nit + 1, size=n).astype(np.int64)
    cust = rng.integers(1, c["customer"] + 1, size=n).astype(np.int64)
    cdemo = rng.integers(1, ncd + 1, size=n).astype(np.int64)
    hdemo = rng.integers(1, nhd + 1, size=n).astype(np.int64)
    addr = rng.integers(1, c["customer_address"] + 1,
                        size=n).astype(np.int64)
    promo = rng.integers(1, c["promotion"] + 1, size=n).astype(np.int64)
    qty = rng.integers(1, 101, size=n).astype(np.int32)
    wholesale = np.round(rng.uniform(1.0, 100.0, size=n), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, size=n), 2)
    sales_price = np.round(list_price * rng.uniform(0.0, 1.0, size=n), 2)
    ext_discount = np.round((list_price - sales_price) * qty, 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_whole = np.round(wholesale * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(ext_sales * rng.uniform(0, 0.5, size=n), 2),
                      0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    net_profit = np.round(net_paid - ext_whole, 2)

    # Fact FK columns are NULLable in the spec data — carry REAL null
    # masks (queries like q44/q76 select on `fk IS NULL`).
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}
    nulls: Dict[str, np.ndarray] = {}
    pre = _SALES_PREFIX[name]

    def put(col, vals, null_rate: float = 0.0):
        arrays[f"{pre}_{col}"] = vals
        if null_rate > 0.0:
            nulls[f"{pre}_{col}"] = rng.random(n) < null_rate

    put("sold_date_sk", date_sk)
    put("sold_time_sk", time_sk)
    ext_tax = np.round(ext_sales * 0.05, 2)
    if name == "store_sales":
        put("item_sk", item)
        put("customer_sk", cust, 0.01)
        put("cdemo_sk", cdemo, 0.04)
        put("hdemo_sk", hdemo, 0.04)
        put("addr_sk", addr, 0.01)
        put("store_sk", 1 + (item + cust) % _counts(sf)["store"], 0.01)
        put("promo_sk", promo, 0.04)
        put("ticket_number", np.arange(1, n + 1, dtype=np.int64))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_wholesale_cost", ext_whole)
        put("ext_list_price", ext_list)
        put("ext_tax", ext_tax)
        put("coupon_amt", coupon)
        put("net_paid", net_paid)
        put("net_profit", net_profit)
    elif name == "catalog_sales":
        put("ship_date_sk", date_sk + rng.integers(2, 91, size=n))
        put("bill_customer_sk", cust, 0.01)
        put("bill_cdemo_sk", cdemo, 0.04)
        put("bill_addr_sk", addr, 0.01)
        put("ship_addr_sk",
            rng.integers(1, c["customer_address"] + 1,
                         size=n).astype(np.int64), 0.01)
        put("ship_customer_sk",
            rng.integers(1, c["customer"] + 1,
                         size=n).astype(np.int64), 0.01)
        put("warehouse_sk",
            rng.integers(1, c["warehouse"] + 1,
                         size=n).astype(np.int64))
        put("ship_mode_sk",
            rng.integers(1, 21, size=n).astype(np.int64))
        put("call_center_sk",
            rng.integers(1, 7, size=n).astype(np.int64), 0.02)
        put("item_sk", item)
        put("promo_sk", promo, 0.04)
        # line items share orders (q16's multi-warehouse EXISTS shape)
        put("order_number", 1 + (np.arange(n, dtype=np.int64) // 3))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_wholesale_cost", ext_whole)
        put("ext_list_price", ext_list)
        put("ext_ship_cost", np.round(ext_list * 0.1, 2))
        put("coupon_amt", coupon)
        put("net_paid", net_paid)
        put("net_profit", net_profit)
    else:
        put("ship_date_sk", date_sk + rng.integers(1, 31, size=n))
        put("item_sk", item)
        put("bill_customer_sk", cust, 0.01)
        put("ship_customer_sk",
            rng.integers(1, c["customer"] + 1,
                         size=n).astype(np.int64), 0.01)
        put("bill_addr_sk", addr, 0.01)
        put("ship_addr_sk",
            rng.integers(1, c["customer_address"] + 1,
                         size=n).astype(np.int64), 0.01)
        put("warehouse_sk",
            rng.integers(1, c["warehouse"] + 1,
                         size=n).astype(np.int64))
        put("ship_mode_sk",
            rng.integers(1, 21, size=n).astype(np.int64))
        put("ship_hdemo_sk", hdemo, 0.04)
        put("web_page_sk",
            rng.integers(1, 61, size=n).astype(np.int64), 0.02)
        put("web_site_sk", 1 + item % 4)
        put("promo_sk", promo, 0.04)
        # several line items share one order (q94/q95 multi-warehouse
        # EXISTS shapes need real order groups)
        put("order_number",
            1 + (np.arange(n, dtype=np.int64) // 3))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_list_price", ext_list)
        put("ext_wholesale_cost", ext_whole)
        put("ext_ship_cost", np.round(ext_list * 0.1, 2))
        put("net_paid", net_paid)
        put("net_profit", net_profit)

    return _ht(name, n, arrays, dicts, nulls or None)


from presto_tpu.connectors.base import SplitSource


class TpcdsConnector(SplitSource):
    NAME = "tpcds"
    """Second fixture connector (reference: presto-tpcds). Same surface as
    TpchConnector: schema / row_count / partitioned table slices sharing
    one table-wide StringDict per string column."""

    def __init__(self, scale_factor: float = 0.01):
        self.scale_factor = scale_factor

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return TPCDS_SCHEMA[table]

    def row_count(self, table: str) -> int:
        if table == "date_dim":
            return _N_DATES
        if table == "time_dim":
            return 86400
        if table in _counts(self.scale_factor):
            return _counts(self.scale_factor)[table]
        return _gen(table, self.scale_factor).num_rows

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        if name not in TPCDS_SCHEMA:
            raise KeyError(f"unknown tpcds table {name}")
        full = _gen(name, self.scale_factor)
        if num_parts == 1:
            return full
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: a[lo:hi] for c, a in full.arrays.items()}
        nulls = ({c: m[lo:hi] for c, m in full.nulls.items()}
                 if full.nulls is not None else None)
        return HostTable(name, hi - lo, arrays, full.types, full.dicts,
                         nulls)
