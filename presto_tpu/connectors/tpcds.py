"""TPC-DS connector: deterministic in-memory data generation.

Reference role: presto-tpcds (presto-tpcds/src/main/java/com/facebook/
presto/tpcds/ — the second standard fixture connector; BASELINE.json names
the TPC-DS 99-query suite as a target harness, SURVEY.md §6).

Like the TPC-H generator (connectors/tpch.py), this is *spec-shaped*, not
bit-identical to dsdgen: table row-count ratios, surrogate-key ranges
(date_sk = julian day), dimension cross-products (customer/household
demographics), fact->dimension FK relationships, NULLable FK columns and
value distributions follow the TPC-DS spec so query selectivities are
realistic; exact values differ. Correctness tests compare against a
sqlite oracle over the SAME generated data.

Fixed-cardinality dimensions (date_dim 1900..2100, time_dim 86400,
demographics cross-products) are scale-independent, as in the spec; fact
tables scale with `scale_factor` (≈GB)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.expr.compile import days_from_civil
from presto_tpu.types import BIGINT, DATE, DOUBLE, INTEGER, VARCHAR, Type

# ---------------------------------------------------------------------------
# schema (column subset used by the implemented query set; same layout
# conventions as the reference's tpcds tables)
# ---------------------------------------------------------------------------

TPCDS_SCHEMA: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date_id", VARCHAR), ("d_date", DATE),
        ("d_month_seq", INTEGER), ("d_week_seq", INTEGER),
        ("d_quarter_seq", INTEGER), ("d_year", INTEGER), ("d_dow", INTEGER),
        ("d_moy", INTEGER), ("d_dom", INTEGER), ("d_qoy", INTEGER),
        ("d_day_name", VARCHAR),
    ],
    "time_dim": [
        ("t_time_sk", BIGINT), ("t_time", INTEGER), ("t_hour", INTEGER),
        ("t_minute", INTEGER), ("t_second", INTEGER),
        ("t_meal_time", VARCHAR),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VARCHAR),
        ("i_item_desc", VARCHAR), ("i_current_price", DOUBLE),
        ("i_brand_id", INTEGER), ("i_brand", VARCHAR),
        ("i_class_id", INTEGER), ("i_class", VARCHAR),
        ("i_category_id", INTEGER), ("i_category", VARCHAR),
        ("i_manufact_id", INTEGER), ("i_manufact", VARCHAR),
        ("i_manager_id", INTEGER), ("i_product_name", VARCHAR),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VARCHAR),
        ("s_store_name", VARCHAR), ("s_number_employees", INTEGER),
        ("s_hours", VARCHAR), ("s_manager", VARCHAR),
        ("s_market_id", INTEGER), ("s_company_id", INTEGER),
        ("s_city", VARCHAR), ("s_county", VARCHAR), ("s_state", VARCHAR),
        ("s_zip", VARCHAR), ("s_gmt_offset", DOUBLE),
    ],
    "warehouse": [
        ("w_warehouse_sk", BIGINT), ("w_warehouse_name", VARCHAR),
        ("w_warehouse_sq_ft", INTEGER), ("w_state", VARCHAR),
        ("w_country", VARCHAR),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VARCHAR),
        ("p_channel_dmail", VARCHAR), ("p_channel_email", VARCHAR),
        ("p_channel_tv", VARCHAR), ("p_channel_event", VARCHAR),
    ],
    "customer": [
        ("c_customer_sk", BIGINT), ("c_customer_id", VARCHAR),
        ("c_current_cdemo_sk", BIGINT), ("c_current_hdemo_sk", BIGINT),
        ("c_current_addr_sk", BIGINT), ("c_first_name", VARCHAR),
        ("c_last_name", VARCHAR), ("c_birth_year", INTEGER),
        ("c_birth_country", VARCHAR),
    ],
    "customer_address": [
        ("ca_address_sk", BIGINT), ("ca_address_id", VARCHAR),
        ("ca_city", VARCHAR), ("ca_county", VARCHAR), ("ca_state", VARCHAR),
        ("ca_zip", VARCHAR), ("ca_country", VARCHAR),
        ("ca_gmt_offset", DOUBLE), ("ca_location_type", VARCHAR),
    ],
    "customer_demographics": [
        ("cd_demo_sk", BIGINT), ("cd_gender", VARCHAR),
        ("cd_marital_status", VARCHAR), ("cd_education_status", VARCHAR),
        ("cd_purchase_estimate", INTEGER), ("cd_credit_rating", VARCHAR),
        ("cd_dep_count", INTEGER), ("cd_dep_employed_count", INTEGER),
        ("cd_dep_college_count", INTEGER),
    ],
    "household_demographics": [
        ("hd_demo_sk", BIGINT), ("hd_income_band_sk", BIGINT),
        ("hd_buy_potential", VARCHAR), ("hd_dep_count", INTEGER),
        ("hd_vehicle_count", INTEGER),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_sold_time_sk", BIGINT),
        ("ss_item_sk", BIGINT), ("ss_customer_sk", BIGINT),
        ("ss_cdemo_sk", BIGINT), ("ss_hdemo_sk", BIGINT),
        ("ss_addr_sk", BIGINT), ("ss_store_sk", BIGINT),
        ("ss_promo_sk", BIGINT), ("ss_ticket_number", BIGINT),
        ("ss_quantity", INTEGER), ("ss_wholesale_cost", DOUBLE),
        ("ss_list_price", DOUBLE), ("ss_sales_price", DOUBLE),
        ("ss_ext_discount_amt", DOUBLE), ("ss_ext_sales_price", DOUBLE),
        ("ss_ext_wholesale_cost", DOUBLE), ("ss_ext_list_price", DOUBLE),
        ("ss_coupon_amt", DOUBLE), ("ss_net_paid", DOUBLE),
        ("ss_net_profit", DOUBLE),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", BIGINT), ("cs_sold_time_sk", BIGINT),
        ("cs_ship_date_sk", BIGINT), ("cs_bill_customer_sk", BIGINT),
        ("cs_bill_cdemo_sk", BIGINT), ("cs_bill_addr_sk", BIGINT),
        ("cs_item_sk", BIGINT), ("cs_promo_sk", BIGINT),
        ("cs_order_number", BIGINT), ("cs_quantity", INTEGER),
        ("cs_wholesale_cost", DOUBLE), ("cs_list_price", DOUBLE),
        ("cs_sales_price", DOUBLE), ("cs_ext_discount_amt", DOUBLE),
        ("cs_ext_sales_price", DOUBLE), ("cs_ext_ship_cost", DOUBLE),
        ("cs_coupon_amt", DOUBLE), ("cs_net_paid", DOUBLE),
        ("cs_net_profit", DOUBLE),
    ],
    "web_sales": [
        ("ws_sold_date_sk", BIGINT), ("ws_sold_time_sk", BIGINT),
        ("ws_ship_date_sk", BIGINT), ("ws_item_sk", BIGINT),
        ("ws_bill_customer_sk", BIGINT), ("ws_bill_addr_sk", BIGINT),
        ("ws_web_site_sk", BIGINT), ("ws_promo_sk", BIGINT),
        ("ws_order_number", BIGINT), ("ws_quantity", INTEGER),
        ("ws_wholesale_cost", DOUBLE), ("ws_list_price", DOUBLE),
        ("ws_sales_price", DOUBLE), ("ws_ext_discount_amt", DOUBLE),
        ("ws_ext_sales_price", DOUBLE), ("ws_ext_ship_cost", DOUBLE),
        ("ws_net_paid", DOUBLE), ("ws_net_profit", DOUBLE),
    ],
    "inventory": [
        ("inv_date_sk", BIGINT), ("inv_item_sk", BIGINT),
        ("inv_warehouse_sk", BIGINT), ("inv_quantity_on_hand", INTEGER),
    ],
}

_D0 = days_from_civil(1900, 1, 1)
_D1 = days_from_civil(2100, 1, 1)
_DATE_SK0 = 2415022                       # julian day of 1900-01-01
_N_DATES = _D1 - _D0                      # 73049 rows, per spec
                                          # (1900-01-01 .. 2099-12-31)

_SALES_D0 = days_from_civil(1998, 1, 1)
_SALES_D1 = days_from_civil(2002, 12, 31)

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES_PER_CAT = 10
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
_STATES = ["AL", "CA", "CO", "FL", "GA", "IL", "IN", "KS", "KY", "LA",
           "MI", "MN", "MO", "NC", "NE", "NY", "OH", "OK", "OR", "PA",
           "SD", "TN", "TX", "VA", "WA", "WI"]
_COUNTIES = ["Ziebach County", "Walker County", "Daviess County",
             "Barrow County", "Fairfield County", "Luce County",
             "Richland County", "Bronx County", "Orange County",
             "Williamson County"]
_CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
           "Liberty", "Oakland", "Riverside", "Glendale", "Springdale",
           "Union", "Salem", "Greenfield", "Pleasant Hill", "Lakeview"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_MARITAL = ["M", "S", "D", "W", "U"]
_MEALS = ["breakfast", "lunch", "dinner", ""]
_COUNTRIES = ["United States"]
_FIRST = ["James", "Mary", "John", "Linda", "Robert", "Susan", "Michael",
          "Karen", "William", "Lisa", "David", "Nancy", "Richard", "Betty"]
_LAST = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
         "Miller", "Davis", "Wilson", "Anderson", "Taylor", "Thomas"]

# spec row counts at SF1; fact tables scale linearly, dims sub-linearly
_SF1 = {"store_sales": 2_880_000, "catalog_sales": 1_440_000,
        "web_sales": 720_000, "item": 18_000, "customer": 100_000,
        "customer_address": 50_000, "store": 12, "warehouse": 5,
        "promotion": 300}


def _counts(sf: float) -> Dict[str, int]:
    def lin(base, floor):
        return max(floor, int(base * sf))
    return {
        "store_sales": lin(_SF1["store_sales"], 1000),
        "catalog_sales": lin(_SF1["catalog_sales"], 500),
        "web_sales": lin(_SF1["web_sales"], 250),
        "item": lin(_SF1["item"], 200),
        "customer": lin(_SF1["customer"], 300),
        "customer_address": lin(_SF1["customer_address"], 150),
        "store": max(4, int(_SF1["store"] * max(sf, 0.4))),
        "warehouse": max(3, int(_SF1["warehouse"] * max(sf, 0.6))),
        "promotion": lin(_SF1["promotion"], 30),
    }


def _seed(name: str, sf: float) -> int:
    import zlib
    return zlib.crc32(f"tpcds|{name}|{sf}".encode())


def _dictify(arrays, dicts, col, vals):
    d, codes = StringDict.build(vals)
    arrays[col], dicts[col] = codes, d


def _ht(name, n, arrays, dicts) -> HostTable:
    return HostTable(name, n, arrays, dict(TPCDS_SCHEMA[name]), dicts)


@functools.lru_cache(maxsize=64)
def _gen(name: str, sf: float) -> HostTable:
    c = _counts(sf)
    rng = np.random.default_rng(_seed(name, sf))
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}

    def put_str(col, vals):
        _dictify(arrays, dicts, col, vals)

    if name == "date_dim":
        days = np.arange(_D0, _D1, dtype=np.int64)
        n = len(days)
        arrays["d_date_sk"] = _DATE_SK0 + (days - _D0)
        put_str("d_date_id", np.char.add(
            "D", (_DATE_SK0 + days - _D0).astype(str)).astype(object))
        arrays["d_date"] = days.astype(np.int32)
        # civil fields via numpy datetime64 (exact)
        dt = (days.astype("datetime64[D]"))
        y = dt.astype("datetime64[Y]").astype(int) + 1970
        m = dt.astype("datetime64[M]").astype(int) % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        arrays["d_year"] = y.astype(np.int32)
        arrays["d_moy"] = m.astype(np.int32)
        arrays["d_dom"] = dom.astype(np.int32)
        arrays["d_qoy"] = ((m - 1) // 3 + 1).astype(np.int32)
        # 1900-01-01 was a Monday; spec d_dow: 0=Sunday
        dow = ((days - _D0) + 1) % 7
        arrays["d_dow"] = dow.astype(np.int32)
        put_str("d_day_name",
                np.asarray(_DAY_NAMES, dtype=object)[dow])
        arrays["d_month_seq"] = ((y - 1900) * 12 + (m - 1)).astype(np.int32)
        arrays["d_week_seq"] = ((days - _D0) // 7 + 1).astype(np.int32)
        arrays["d_quarter_seq"] = ((y - 1900) * 4 + (m - 1) // 3 + 1
                                   ).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "time_dim":
        t = np.arange(86400, dtype=np.int64)
        arrays["t_time_sk"] = t
        arrays["t_time"] = t.astype(np.int32)
        hour = (t // 3600).astype(np.int32)
        arrays["t_hour"] = hour
        arrays["t_minute"] = ((t % 3600) // 60).astype(np.int32)
        arrays["t_second"] = (t % 60).astype(np.int32)
        meal = np.where(hour < 9, "breakfast",
                        np.where(hour < 14, "lunch",
                                 np.where(hour < 22, "dinner", "")))
        put_str("t_meal_time", meal.astype(object))
        return _ht(name, 86400, arrays, dicts)

    if name == "item":
        n = c["item"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["i_item_sk"] = sk
        put_str("i_item_id", np.char.add("AAAAAAAA",
                np.char.zfill(sk.astype(str), 8)).astype(object))
        put_str("i_item_desc", np.char.add("item description ",
                (sk % 997).astype(str)).astype(object))
        arrays["i_current_price"] = np.round(
            rng.uniform(0.09, 99.99, size=n), 2)
        cat_id = rng.integers(1, len(_CATEGORIES) + 1, size=n)
        arrays["i_category_id"] = cat_id.astype(np.int32)
        put_str("i_category",
                np.asarray(_CATEGORIES, dtype=object)[cat_id - 1])
        class_id = rng.integers(1, _CLASSES_PER_CAT + 1, size=n)
        arrays["i_class_id"] = class_id.astype(np.int32)
        put_str("i_class", np.char.add(
            np.char.add(np.asarray(_CATEGORIES)[cat_id - 1].astype(str),
                        " class "),
            class_id.astype(str)).astype(object))
        brand_id = (cat_id * 1000000 + class_id * 10000
                    + rng.integers(1, 100, size=n)).astype(np.int32)
        arrays["i_brand_id"] = brand_id
        put_str("i_brand", np.char.add("brand#",
                brand_id.astype(str)).astype(object))
        man_id = rng.integers(1, 1001, size=n)
        arrays["i_manufact_id"] = man_id.astype(np.int32)
        put_str("i_manufact", np.char.add("manufact#",
                man_id.astype(str)).astype(object))
        arrays["i_manager_id"] = rng.integers(
            1, 101, size=n).astype(np.int32)
        put_str("i_product_name", np.char.add("product",
                np.char.zfill(sk.astype(str), 7)).astype(object))
        return _ht(name, n, arrays, dicts)

    if name == "store":
        n = c["store"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["s_store_sk"] = sk
        put_str("s_store_id", np.char.add("S", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("s_store_name", np.asarray(
            ["ought", "able", "pri", "ese", "anti", "cally", "ation",
             "eing", "n st", "bar", "ought2", "able2"],
            dtype=object)[(sk - 1) % 12])
        arrays["s_number_employees"] = rng.integers(
            200, 301, size=n).astype(np.int32)
        put_str("s_hours", np.asarray(["8AM-8AM", "8AM-4PM", "8AM-12AM"],
                                      dtype=object)[(sk - 1) % 3])
        put_str("s_manager", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        arrays["s_market_id"] = rng.integers(1, 11, size=n).astype(np.int32)
        arrays["s_company_id"] = np.ones(n, dtype=np.int32)
        put_str("s_city", np.asarray(_CITIES, dtype=object)[
            rng.integers(0, len(_CITIES), size=n)])
        put_str("s_county", np.asarray(_COUNTIES, dtype=object)[
            rng.integers(0, len(_COUNTIES), size=n)])
        put_str("s_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("s_zip", np.char.zfill(rng.integers(
            10000, 99999, size=n).astype(str), 5).astype(object))
        arrays["s_gmt_offset"] = np.full(n, -5.0)
        return _ht(name, n, arrays, dicts)

    if name == "warehouse":
        n = c["warehouse"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["w_warehouse_sk"] = sk
        put_str("w_warehouse_name", np.char.add("Warehouse ",
                sk.astype(str)).astype(object))
        arrays["w_warehouse_sq_ft"] = rng.integers(
            50000, 1000001, size=n).astype(np.int32)
        put_str("w_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("w_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        return _ht(name, n, arrays, dicts)

    if name == "promotion":
        n = c["promotion"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["p_promo_sk"] = sk
        put_str("p_promo_id", np.char.add("P", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        for col in ("p_channel_dmail", "p_channel_email", "p_channel_tv",
                    "p_channel_event"):
            put_str(col, np.where(rng.random(n) < 0.5, "Y", "N")
                    .astype(object))
        return _ht(name, n, arrays, dicts)

    if name == "customer_demographics":
        # cross product of (gender, marital, education, credit,
        # purchase_estimate) — a fixed dimension, as in the spec
        combos = [(g, m, e, cr, pe)
                  for g in ("M", "F") for m in _MARITAL
                  for e in _EDUCATION for cr in _CREDIT
                  for pe in range(500, 10001, 500)]
        n = len(combos)
        arrays["cd_demo_sk"] = np.arange(1, n + 1, dtype=np.int64)
        put_str("cd_gender", np.asarray([x[0] for x in combos],
                                        dtype=object))
        put_str("cd_marital_status", np.asarray([x[1] for x in combos],
                                                dtype=object))
        put_str("cd_education_status", np.asarray([x[2] for x in combos],
                                                  dtype=object))
        put_str("cd_credit_rating", np.asarray([x[3] for x in combos],
                                               dtype=object))
        arrays["cd_purchase_estimate"] = np.asarray(
            [x[4] for x in combos], dtype=np.int32)
        i = np.arange(n)
        arrays["cd_dep_count"] = (i % 7).astype(np.int32)
        arrays["cd_dep_employed_count"] = ((i // 7) % 7).astype(np.int32)
        arrays["cd_dep_college_count"] = ((i // 49) % 7).astype(np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "household_demographics":
        combos = [(ib, bp, dep, veh)
                  for ib in range(1, 21) for bp in _BUY_POTENTIAL
                  for dep in range(0, 10) for veh in range(-1, 5)]
        n = len(combos)
        arrays["hd_demo_sk"] = np.arange(1, n + 1, dtype=np.int64)
        arrays["hd_income_band_sk"] = np.asarray(
            [x[0] for x in combos], dtype=np.int64)
        put_str("hd_buy_potential", np.asarray([x[1] for x in combos],
                                               dtype=object))
        arrays["hd_dep_count"] = np.asarray([x[2] for x in combos],
                                            dtype=np.int32)
        arrays["hd_vehicle_count"] = np.asarray([x[3] for x in combos],
                                                dtype=np.int32)
        return _ht(name, n, arrays, dicts)

    if name == "customer_address":
        n = c["customer_address"]
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["ca_address_sk"] = sk
        put_str("ca_address_id", np.char.add("A", np.char.zfill(
            sk.astype(str), 9)).astype(object))
        put_str("ca_city", np.asarray(_CITIES, dtype=object)[
            rng.integers(0, len(_CITIES), size=n)])
        put_str("ca_county", np.asarray(_COUNTIES, dtype=object)[
            rng.integers(0, len(_COUNTIES), size=n)])
        put_str("ca_state", np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), size=n)])
        put_str("ca_zip", np.char.zfill(rng.integers(
            10000, 99999, size=n).astype(str), 5).astype(object))
        put_str("ca_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        arrays["ca_gmt_offset"] = rng.choice(
            [-5.0, -6.0, -7.0, -8.0], size=n)
        put_str("ca_location_type", np.asarray(
            ["apartment", "condo", "single family"], dtype=object)[
            rng.integers(0, 3, size=n)])
        return _ht(name, n, arrays, dicts)

    if name == "customer":
        n = c["customer"]
        ncd = _gen("customer_demographics", sf).num_rows
        nhd = _gen("household_demographics", sf).num_rows
        sk = np.arange(1, n + 1, dtype=np.int64)
        arrays["c_customer_sk"] = sk
        put_str("c_customer_id", np.char.add("C", np.char.zfill(
            sk.astype(str), 15)).astype(object))
        arrays["c_current_cdemo_sk"] = rng.integers(
            1, ncd + 1, size=n).astype(np.int64)
        arrays["c_current_hdemo_sk"] = rng.integers(
            1, nhd + 1, size=n).astype(np.int64)
        arrays["c_current_addr_sk"] = rng.integers(
            1, c["customer_address"] + 1, size=n).astype(np.int64)
        put_str("c_first_name", np.asarray(_FIRST, dtype=object)[
            rng.integers(0, len(_FIRST), size=n)])
        put_str("c_last_name", np.asarray(_LAST, dtype=object)[
            rng.integers(0, len(_LAST), size=n)])
        arrays["c_birth_year"] = rng.integers(
            1924, 1993, size=n).astype(np.int32)
        put_str("c_birth_country", np.asarray(_COUNTRIES, dtype=object)[
            np.zeros(n, dtype=np.int64)])
        return _ht(name, n, arrays, dicts)

    if name in ("store_sales", "catalog_sales", "web_sales"):
        return _gen_sales(name, sf)

    if name == "inventory":
        # weekly snapshots over one year x items x warehouses (bounded)
        nit = min(c["item"], 400)
        nw = c["warehouse"]
        week_days = np.arange(_SALES_D0, _SALES_D0 + 364, 7,
                              dtype=np.int64)
        n = len(week_days) * nit * nw
        d = np.repeat(week_days, nit * nw)
        it = np.tile(np.repeat(np.arange(1, nit + 1, dtype=np.int64), nw),
                     len(week_days))
        wh = np.tile(np.arange(1, nw + 1, dtype=np.int64),
                     len(week_days) * nit)
        arrays["inv_date_sk"] = _DATE_SK0 + (d - _D0)
        arrays["inv_item_sk"] = it
        arrays["inv_warehouse_sk"] = wh
        q = rng.integers(0, 1001, size=n).astype(np.int32)
        arrays["inv_quantity_on_hand"] = q
        return _ht(name, n, arrays, dicts)

    raise KeyError(f"unknown tpcds table {name}")


_SALES_PREFIX = {"store_sales": "ss", "catalog_sales": "cs",
                 "web_sales": "ws"}


@functools.lru_cache(maxsize=16)
def _gen_sales(name: str, sf: float) -> HostTable:
    c = _counts(sf)
    rng = np.random.default_rng(_seed(name, sf))
    n = c[name]
    ncd = _gen("customer_demographics", sf).num_rows
    nhd = _gen("household_demographics", sf).num_rows
    nit = c["item"]

    days = rng.integers(_SALES_D0, _SALES_D1 + 1, size=n).astype(np.int64)
    date_sk = _DATE_SK0 + (days - _D0)
    time_sk = rng.integers(0, 86400, size=n).astype(np.int64)
    item = rng.integers(1, nit + 1, size=n).astype(np.int64)
    cust = rng.integers(1, c["customer"] + 1, size=n).astype(np.int64)
    cdemo = rng.integers(1, ncd + 1, size=n).astype(np.int64)
    hdemo = rng.integers(1, nhd + 1, size=n).astype(np.int64)
    addr = rng.integers(1, c["customer_address"] + 1,
                        size=n).astype(np.int64)
    promo = rng.integers(1, c["promotion"] + 1, size=n).astype(np.int64)
    qty = rng.integers(1, 101, size=n).astype(np.int32)
    wholesale = np.round(rng.uniform(1.0, 100.0, size=n), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, size=n), 2)
    sales_price = np.round(list_price * rng.uniform(0.0, 1.0, size=n), 2)
    ext_discount = np.round((list_price - sales_price) * qty, 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_whole = np.round(wholesale * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(ext_sales * rng.uniform(0, 0.5, size=n), 2),
                      0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    net_profit = np.round(net_paid - ext_whole, 2)

    # ~4% of fact demographic/promo FKs dangle (spec data has NULL FKs;
    # -1 here — inner joins drop them either way, and the generator keeps
    # nullable storage out of the fixture)
    for a in (cdemo, hdemo, promo):
        a[rng.random(n) < 0.04] = -1

    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}
    pre = _SALES_PREFIX[name]

    def put(col, vals):
        arrays[f"{pre}_{col}"] = vals

    put("sold_date_sk", date_sk)
    put("sold_time_sk", time_sk)
    if name == "store_sales":
        put("item_sk", item)
        put("customer_sk", cust)
        put("cdemo_sk", cdemo)
        put("hdemo_sk", hdemo)
        put("addr_sk", addr)
        put("store_sk", 1 + (item + cust) % _counts(sf)["store"])
        put("promo_sk", promo)
        put("ticket_number", np.arange(1, n + 1, dtype=np.int64))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_wholesale_cost", ext_whole)
        put("ext_list_price", ext_list)
        put("coupon_amt", coupon)
        put("net_paid", net_paid)
        put("net_profit", net_profit)
    elif name == "catalog_sales":
        put("ship_date_sk", date_sk + rng.integers(2, 91, size=n))
        put("bill_customer_sk", cust)
        put("bill_cdemo_sk", cdemo)
        put("bill_addr_sk", addr)
        put("item_sk", item)
        put("promo_sk", promo)
        put("order_number", np.arange(1, n + 1, dtype=np.int64))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_ship_cost", np.round(ext_list * 0.1, 2))
        put("coupon_amt", coupon)
        put("net_paid", net_paid)
        put("net_profit", net_profit)
    else:
        put("ship_date_sk", date_sk + rng.integers(1, 31, size=n))
        put("item_sk", item)
        put("bill_customer_sk", cust)
        put("bill_addr_sk", addr)
        put("web_site_sk", 1 + item % 4)
        put("promo_sk", promo)
        put("order_number", np.arange(1, n + 1, dtype=np.int64))
        put("quantity", qty)
        put("wholesale_cost", wholesale)
        put("list_price", list_price)
        put("sales_price", sales_price)
        put("ext_discount_amt", ext_discount)
        put("ext_sales_price", ext_sales)
        put("ext_ship_cost", np.round(ext_list * 0.1, 2))
        put("net_paid", net_paid)
        put("net_profit", net_profit)

    return _ht(name, n, arrays, dicts)


from presto_tpu.connectors.base import SplitSource


class TpcdsConnector(SplitSource):
    NAME = "tpcds"
    """Second fixture connector (reference: presto-tpcds). Same surface as
    TpchConnector: schema / row_count / partitioned table slices sharing
    one table-wide StringDict per string column."""

    def __init__(self, scale_factor: float = 0.01):
        self.scale_factor = scale_factor

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return TPCDS_SCHEMA[table]

    def row_count(self, table: str) -> int:
        if table == "date_dim":
            return _N_DATES
        if table == "time_dim":
            return 86400
        if table in ("customer_demographics", "household_demographics",
                     "inventory"):
            return _gen(table, self.scale_factor).num_rows
        return _counts(self.scale_factor)[table]

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        if name not in TPCDS_SCHEMA:
            raise KeyError(f"unknown tpcds table {name}")
        full = _gen(name, self.scale_factor)
        if num_parts == 1:
            return full
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: a[lo:hi] for c, a in full.arrays.items()}
        return HostTable(name, hi - lo, arrays, full.types, full.dicts)
