"""Connector split/identity surface.

Reference: presto-spi ConnectorSplitManager — the scheduler asks the
connector for splits instead of assuming a layout. This engine's
connectors are all host-table row-range sources, so the default split is
a (part, numParts) row range tagged with the connector's id; connectors
with other layouts override table_splits.
"""

from __future__ import annotations

from typing import List, Optional


class SplitSource:
    """Default row-range split source (mixed into every connector)."""

    NAME = "unknown"

    def connector_id(self, table: Optional[str] = None) -> str:
        return self.NAME

    def table_splits(self, table: str, n_splits: int) -> List[dict]:
        """ConnectorSplit payloads for scanning `table` with n_splits
        tasks (one split per task; the scheduler may subdivide)."""
        cid = self.connector_id(table)
        return [{"@type": cid, "part": i, "numParts": n_splits}
                for i in range(n_splits)]

    # ---------------------------------------------------- streaming scans
    def scan_runs(self, table: str, max_rows: int, part: int = 0,
                  num_parts: int = 1):
        """Yield one split's rows as a sequence of bounded host tables
        (streaming leaf scans — the scale-ladder contract): each run
        holds at most `max_rows` rows, so a consumer never needs the
        whole split resident at once. The default yields row-window
        VIEWS of the split table (numpy slices sharing the parent's
        buffers and StringDicts); connectors with natural unit
        boundaries (parquet row groups) override this to bound physical
        IO per run too."""
        t = self.table(table, part=part, num_parts=num_parts)
        n = int(t.num_rows)
        if max_rows <= 0 or n <= max_rows:
            yield t
            return
        for lo in range(0, n, max_rows):
            yield t.row_slice(lo, min(lo + max_rows, n))

    # ------------------------------------------------------- data versions
    # Per-table monotonic versions for the fragment result cache
    # (cache/): every write/INSERT/CTAS/drop bumps the version, which
    # changes every cache key that references the table, making stale
    # entries structurally unreachable (no invalidation broadcast to
    # race). Immutable connectors (tpch) never bump, so their results
    # cache forever — the correct semantics for generated data.

    def table_version(self, table: str) -> int:
        return getattr(self, "_table_versions", {}).get(table, 0)

    def bump_table_version(self, table: str) -> int:
        versions = getattr(self, "_table_versions", None)
        if versions is None:
            versions = {}
            self._table_versions = versions
        versions[table] = versions.get(table, 0) + 1
        return versions[table]
