"""ORC connector — the second lakehouse file format.

Reference role: presto-orc (the ORC->Page reader feeding Hive scans,
presto-orc/.../OrcReader.java) + presto-hive's directory layout. Same
TPU-first shape as the parquet connector (connectors/parquet.py):
columns decode lazily per stripe (projection pushdown), a table is one
file or a directory of files, and the split unit is (file, stripe) —
ORC's natural row-group analog. Decode is pyarrow.orc (the role the
reference delegates to its own ORC decoder); the lazy projection,
split construction, dictionary remap and type mapping are this
connector. pyarrow's ORC API exposes no per-stripe column statistics,
so there is no metadata min/max pruning here (the parquet path has it).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.parquet import (
    FileCatalogConnector, LazyFileTable, _LazyArrays, _arrow_to_type,
    _decode_column, rows_to_arrow_table,
)
from presto_tpu.data.column import StringDict


class OrcTable(LazyFileTable):
    """Lazily-loading HostTable over one or more ORC files; units are
    (file index, stripe index). Row counts come from file metadata
    (ORCFile.nrows) for whole files; slices get per-stripe lengths
    computed ONCE on the parent and passed down — never re-read."""

    def __init__(self, name: str, paths: List[str],
                 stripes: Optional[List[Tuple[int, int]]] = None,
                 files=None, stripe_rows=None):
        import pyarrow.orc as orc

        self.paths = paths
        self._files = (files if files is not None
                       else [orc.ORCFile(p) for p in paths])
        self.units = (stripes if stripes is not None
                      else [(fi, s) for fi, f in enumerate(self._files)
                            for s in range(f.nstripes)])
        self._stripe_rows = stripe_rows
        schema = self._files[0].schema
        types = {f.name: _arrow_to_type(f.type) for f in schema}
        if stripes is None:
            n = sum(f.nrows for f in self._files)
        else:
            n = sum(self.stripe_lengths()[u] for u in self.units)
        self._dicts: Dict[str, StringDict] = {}
        self._nulls: Dict[str, np.ndarray] = {}
        super().__init__(name, n, _LazyArrays(self._load_column),
                         types, self._dicts, self._nulls)

    def stripe_lengths(self) -> Dict[Tuple[int, int], int]:
        """(file, stripe) -> row count, computed once per table family
        (pyarrow exposes no per-stripe metadata; reading one narrow
        column per stripe is the cheapest measure and is shared with
        every slice via the `stripe_rows=` handoff)."""
        if self._stripe_rows is None:
            first_col = self._files[0].schema[0].name
            self._stripe_rows = {
                (fi, s): len(self._files[fi].read_stripe(
                    s, columns=[first_col]))
                for fi, f in enumerate(self._files)
                for s in range(f.nstripes)}
        return self._stripe_rows

    def _load_column(self, col: str):
        import pyarrow as pa

        t = self.types[col]
        chunks = []
        for fi, s in self.units:
            batch = self._files[fi].read_stripe(s, columns=[col])
            chunks.append(batch.column(0))
        merged = pa.chunked_array(chunks) if chunks \
            else pa.chunked_array([], type=pa.int64())
        vals, nulls, d = _decode_column(merged, t)
        if d is not None:
            self._dicts[col] = d
        self._nulls[col] = nulls
        return vals, nulls, d


def read_orc_table(path: str, name: str) -> OrcTable:
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".orc"))
        if not paths:
            raise FileNotFoundError(f"no orc files under {path}")
        return OrcTable(name, paths)
    return OrcTable(name, [path])


def write_orc_table(path: str, rows: List[tuple], schema,
                    stripe_size: Optional[int] = None) -> None:
    """Engine result rows -> one ORC file (write side for round trips;
    reference role: OrcWriter). Value coercion is the shared
    rows_to_arrow_table."""
    import pyarrow.orc as orc

    kw = {}
    if stripe_size:
        kw["stripe_size"] = stripe_size
    orc.write_table(rows_to_arrow_table(rows, schema), path, **kw)


class OrcConnector(FileCatalogConnector):
    NAME = "orc"
    EXT = "orc"

    def _open(self, path: str, name: str) -> OrcTable:
        return read_orc_table(path, name)

    def _slice(self, full, name: str, units) -> OrcTable:
        return OrcTable(name, full.paths, units, files=full._files,
                        stripe_rows=full.stripe_lengths())
