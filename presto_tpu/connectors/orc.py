"""ORC connector — the second lakehouse file format.

Reference role: presto-orc (the ORC->Page reader feeding Hive scans,
presto-orc/.../OrcReader.java) + presto-hive's directory layout. Same
TPU-first shape as the parquet connector (connectors/parquet.py):
columns decode lazily per stripe (projection pushdown), a table is one
file or a directory of files, and the split unit is (file, stripe) —
ORC's natural row-group analog. Decode is pyarrow.orc (the role the
reference delegates to its own ORC decoder); the lazy projection,
split construction, dictionary remap and type mapping are this
connector. pyarrow's ORC API exposes no per-stripe column statistics,
so there is no metadata min/max pruning here (the parquet path has it).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.base import SplitSource
from presto_tpu.connectors.parquet import (
    LazyFileTable, _LazyArrays, _arrow_to_type, _decode_column,
    _type_to_arrow,
)
from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.types import Type


class OrcTable(LazyFileTable):
    """Lazily-loading HostTable over one or more ORC files; units are
    (file index, stripe index). Row counts come from file metadata
    (ORCFile.nrows) for whole files; slices get per-stripe lengths
    computed ONCE on the parent and passed down — never re-read."""

    def __init__(self, name: str, paths: List[str],
                 stripes: Optional[List[Tuple[int, int]]] = None,
                 files=None, stripe_rows=None):
        import pyarrow.orc as orc

        self.paths = paths
        self._files = (files if files is not None
                       else [orc.ORCFile(p) for p in paths])
        self.units = (stripes if stripes is not None
                      else [(fi, s) for fi, f in enumerate(self._files)
                            for s in range(f.nstripes)])
        self._stripe_rows = stripe_rows
        schema = self._files[0].schema
        types = {f.name: _arrow_to_type(f.type) for f in schema}
        if stripes is None:
            n = sum(f.nrows for f in self._files)
        else:
            n = sum(self.stripe_lengths()[u] for u in self.units)
        self._dicts: Dict[str, StringDict] = {}
        self._nulls: Dict[str, np.ndarray] = {}
        super().__init__(name, n, _LazyArrays(self._load_column),
                         types, self._dicts, self._nulls)

    def stripe_lengths(self) -> Dict[Tuple[int, int], int]:
        """(file, stripe) -> row count, computed once per table family
        (pyarrow exposes no per-stripe metadata; reading one narrow
        column per stripe is the cheapest measure and is shared with
        every slice via the `stripe_rows=` handoff)."""
        if self._stripe_rows is None:
            first_col = self._files[0].schema[0].name
            self._stripe_rows = {
                (fi, s): len(self._files[fi].read_stripe(
                    s, columns=[first_col]))
                for fi, f in enumerate(self._files)
                for s in range(f.nstripes)}
        return self._stripe_rows

    def _load_column(self, col: str):
        import pyarrow as pa

        t = self.types[col]
        chunks = []
        for fi, s in self.units:
            batch = self._files[fi].read_stripe(s, columns=[col])
            chunks.append(batch.column(0))
        merged = pa.chunked_array(chunks) if chunks \
            else pa.chunked_array([], type=pa.int64())
        vals, nulls, d = _decode_column(merged, t)
        if d is not None:
            self._dicts[col] = d
        self._nulls[col] = nulls
        return vals, nulls, d


def read_orc_table(path: str, name: str) -> OrcTable:
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".orc"))
        if not paths:
            raise FileNotFoundError(f"no orc files under {path}")
        return OrcTable(name, paths)
    return OrcTable(name, [path])


def write_orc_table(path: str, rows: List[tuple], schema,
                    stripe_size: Optional[int] = None) -> None:
    """Engine result rows -> one ORC file (write side for round trips;
    reference role: OrcWriter)."""
    import pyarrow as pa
    import pyarrow.orc as orc

    cols, fields = [], []
    for i, (name, t) in enumerate(schema):
        vals = [r[i] for r in rows]
        if t.is_decimal:
            from decimal import Decimal
            vals = [None if v is None else
                    (v if isinstance(v, Decimal)
                     else Decimal(str(round(v, t.scale))))
                    for v in vals]
        if t.name == "date":
            import datetime
            epoch = datetime.date(1970, 1, 1)
            vals = [None if v is None else
                    (v if isinstance(v, datetime.date)
                     else epoch + datetime.timedelta(days=int(v)))
                    for v in vals]
        fields.append(pa.field(name, _type_to_arrow(t)))
        cols.append(pa.array(vals, type=_type_to_arrow(t)))
    kw = {}
    if stripe_size:
        kw["stripe_size"] = stripe_size
    orc.write_table(pa.Table.from_arrays(cols,
                                         schema=pa.schema(fields)),
                    path, **kw)


class OrcConnector(SplitSource):
    NAME = "orc"
    """Directory catalog: `<dir>/<table>.orc` or `<dir>/<table>/`
    (multi-file). Splits are stripe ranges."""

    def __init__(self, directory: str, fallback=None):
        self.directory = directory
        self.fallback = fallback
        self._cache: Dict[str, OrcTable] = {}

    def _path(self, table: str) -> Optional[str]:
        p = os.path.join(self.directory, f"{table}.orc")
        if os.path.exists(p):
            return p
        d = os.path.join(self.directory, table)
        if os.path.isdir(d):
            return d
        return None

    def _load(self, table: str) -> Optional[OrcTable]:
        if table in self._cache:
            return self._cache[table]
        p = self._path(table)
        if p is None:
            return None
        t = read_orc_table(p, table)
        self._cache[table] = t
        return t

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.schema(table)
            raise KeyError(f"unknown table {table}")
        return [(c, t.types[c]) for c in t.column_names()]

    def row_count(self, table: str) -> int:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.row_count(table)
            raise KeyError(f"unknown table {table}")
        return t.num_rows

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        full = self._load(name)
        if full is None:
            if self.fallback is not None:
                return self.fallback.table(name, part, num_parts)
            raise KeyError(f"unknown table {name}")
        if num_parts == 1:
            return full
        if len(full.units) >= num_parts:
            lo, hi = _slice_rows(len(full.units), part, num_parts)
            return OrcTable(name, full.paths, full.units[lo:hi],
                            files=full._files,
                            stripe_rows=full.stripe_lengths())
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: full.arrays[c][lo:hi] for c in full.column_names()}
        nulls = {c: full.null_mask(c)[lo:hi]
                 for c in full.column_names()
                 if full.null_mask(c) is not None}
        return HostTable(name, hi - lo, arrays, full.types, full.dicts,
                         nulls or None)

    def invalidate(self, table: Optional[str] = None):
        if table is None:
            self._cache.clear()
        else:
            self._cache.pop(table, None)
