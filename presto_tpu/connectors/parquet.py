"""Parquet connector — columnar files -> engine Pages.

Reference roles: presto-parquet's reader
(presto-parquet/.../reader/ParquetReader.java — predicate/projection
pushdown into row groups, dictionary pages, nested columns) +
presto-hive's directory/split model
(BackgroundHiveSplitLoader.java: a table is a directory of files, a
split is a file byte-range — here a row-group range, parquet's natural
split unit via ParquetPageSourceFactory).

TPU-first realization:
- **Projection pushdown**: columns load LAZILY — `page(columns=...)`
  touches only the requested columns, and each loads straight from the
  column chunk (never the whole file).
- **Dictionary pages**: string columns read as Arrow dictionary arrays
  (the parquet dictionary page survives decode), then remap into the
  engine's *sorted* StringDict codes — one vectorized indirection, no
  per-value python.
- **Row-group statistics**: `column_minmax()` serves min/max from file
  metadata without reading data; the lifespan dynamic filter and split
  pruning consult it.
- **Multi-file tables**: `<dir>/<table>/` holds N parquet files
  (Hive-style layout); `<dir>/<table>.parquet` stays the single-file
  form. Splits are (file, row-group) pairs.
- **Nested columns**: arrow list/map/struct map to the engine's
  ARRAY/MAP/ROW with offset-encoded NestedColumns.

The decode layer is pyarrow (in-image), playing the role the reference
delegates to its parquet-mr-derived decoder; everything above it —
lazy projection, split construction, statistics pruning, the
dictionary-code remap, type mapping — is this connector.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.connectors.base import SplitSource
from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT, TIMESTAMP,
    TINYINT, VARCHAR, ArrayType, DecimalType, MapType, RowType, Type,
)


def _arrow_to_type(t) -> Type:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return BOOLEAN
    if pa.types.is_int8(t):
        return TINYINT
    if pa.types.is_int16(t):
        return SMALLINT
    if pa.types.is_int32(t):
        return INTEGER
    if pa.types.is_int64(t):
        return BIGINT
    if pa.types.is_float32(t):
        return REAL
    if pa.types.is_float64(t):
        return DOUBLE
    if pa.types.is_date32(t) or pa.types.is_date64(t):
        return DATE
    if pa.types.is_timestamp(t):
        return TIMESTAMP
    if pa.types.is_decimal(t):
        return DecimalType(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return VARCHAR
    if pa.types.is_dictionary(t):
        return _arrow_to_type(t.value_type)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return ArrayType(_arrow_to_type(t.value_type))
    if pa.types.is_map(t):
        return MapType(_arrow_to_type(t.key_type),
                       _arrow_to_type(t.item_type))
    if pa.types.is_struct(t):
        return RowType(tuple(f.name for f in t),
                       tuple(_arrow_to_type(f.type) for f in t))
    raise NotImplementedError(f"arrow type {t}")


def _type_to_arrow(t: Type):
    import pyarrow as pa

    if isinstance(t, DecimalType):
        return pa.decimal128(t.precision, t.scale)
    if isinstance(t, ArrayType):
        return pa.list_(_type_to_arrow(t.element))
    if isinstance(t, MapType):
        return pa.map_(_type_to_arrow(t.key), _type_to_arrow(t.value))
    if isinstance(t, RowType):
        return pa.struct([pa.field(n, _type_to_arrow(ft))
                          for n, ft in zip(t.field_names, t.field_types)])
    return {
        "boolean": pa.bool_(), "tinyint": pa.int8(),
        "smallint": pa.int16(), "integer": pa.int32(),
        "bigint": pa.int64(), "real": pa.float32(),
        "double": pa.float64(), "date": pa.date32(),
        "timestamp": pa.timestamp("us"), "varchar": pa.string(),
        "char": pa.string(),
    }[t.name]


def _decode_column(col, t: Type):
    """One arrow ChunkedArray -> (values ndarray, nulls ndarray,
    StringDict|None). The engine's storage forms (codes into a sorted
    dictionary, unscaled decimal ints, epoch integers)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    col = col.combine_chunks()
    mask = np.asarray(col.is_null())
    if t.is_string:
        # dictionary-page path: decode keeps (indices, dictionary);
        # remap the file dictionary onto the engine's sorted dictionary
        # with one vectorized take
        if not pa.types.is_dictionary(col.type):
            col = pc.dictionary_encode(col)
        dict_words = col.dictionary.to_pylist()
        indices = np.asarray(col.indices.fill_null(0),
                             dtype=np.int32)
        d, remap = StringDict.build(
            ["" if w is None else w for w in dict_words] or [""])
        codes = np.asarray(remap, dtype=np.int32)[
            np.clip(indices, 0, max(len(dict_words) - 1, 0))]
        return codes, mask, d
    if t.is_decimal:
        vals = col.to_pylist()
        if t.uses_int128:
            arr = np.empty(len(vals), object)
            arr[:] = [0 if v is None else int(v.scaleb(t.scale))
                      for v in vals]
            return arr, mask, None
        return np.asarray(
            [0 if v is None else int(v.scaleb(t.scale)) for v in vals],
            dtype=np.int64), mask, None
    if t.name == "timestamp":
        us = col.cast(pa.timestamp("us")).cast(pa.int64())
        return np.asarray(us.fill_null(0), dtype=np.int64), mask, None
    if t.name == "date":
        return np.asarray(col.cast(pa.date32()).cast(pa.int32())
                          .fill_null(0), dtype=np.int32), mask, None
    if t.name in ("array", "map", "row"):
        arr = np.empty(len(col), object)
        arr[:] = col.to_pylist()
        return arr, mask, None
    if t.name == "boolean":
        return np.asarray(col.fill_null(False), dtype=bool), mask, None
    return (np.asarray(col.fill_null(0)).astype(t.dtype), mask, None)


class _LazyArrays(dict):
    """Column name -> ndarray, loaded from the file's column chunks on
    first access (projection pushdown: `page(columns=[...])` only ever
    touches the requested names). Shared by every lazy file-format
    table (parquet, orc)."""

    def __init__(self, loader):
        super().__init__()
        self._loader = loader

    def __missing__(self, key):
        vals, nulls, d = self._loader(key)
        self[key] = vals
        return vals


class LazyFileTable(HostTable):
    """Base for lazily-loading file-format tables: the null-mask cache
    rides the same lazy column load."""

    def null_mask(self, c: str):
        if c not in self._nulls:
            _ = self.arrays[c]          # triggers the lazy load
        m = self._nulls.get(c)
        return m[:self.num_rows] if m is not None else None


class ParquetTable(LazyFileTable):
    """Lazily-loading HostTable over one or more parquet files.
    `files` shares already-open ParquetFile handles (split/prune
    derivatives must not re-open and re-parse every file's metadata)."""

    def __init__(self, name: str, paths: List[str],
                 row_groups: Optional[List[Tuple[int, int]]] = None,
                 files=None):
        import pyarrow.parquet as pq

        self.paths = paths
        self._files = (files if files is not None
                       else [pq.ParquetFile(p) for p in paths])
        # (file index, row group index) units — the split currency
        self.units = (row_groups if row_groups is not None
                      else [(fi, g) for fi, f in enumerate(self._files)
                            for g in range(f.metadata.num_row_groups)])
        schema = self._files[0].schema_arrow
        types = {f.name: _arrow_to_type(f.type) for f in schema}
        n = sum(self._files[fi].metadata.row_group(g).num_rows
                for fi, g in self.units)
        self._dicts: Dict[str, StringDict] = {}
        self._nulls: Dict[str, np.ndarray] = {}
        super().__init__(name, n, _LazyArrays(self._load_column), types,
                         self._dicts, self._nulls)

    def unit_rows(self, unit: Tuple[int, int]) -> int:
        fi, g = unit
        return self._files[fi].metadata.row_group(g).num_rows

    # -- lazy column load (projection pushdown) -------------------------
    def _load_column(self, col: str):
        import pyarrow as pa

        t = self.types[col]
        chunks = []
        for fi, g in self.units:
            chunks.append(self._files[fi].read_row_group(
                g, columns=[col]).column(0))
        merged = pa.chunked_array([c for ch in chunks
                                   for c in ch.chunks]) \
            if chunks else pa.chunked_array([], type=pa.int64())
        vals, nulls, d = _decode_column(merged, t)
        if d is not None:
            self._dicts[col] = d
        self._nulls[col] = nulls
        return vals, nulls, d

    # -- row-group statistics (predicate pushdown support) --------------
    def _leaf_index(self, col: str) -> Optional[int]:
        """Row-group metadata enumerates FLATTENED LEAF columns (a
        nested column contributes one entry per leaf, e.g.
        'a.list.element'), so arrow top-level schema positions misalign
        the moment any earlier column is nested. Map by exact leaf
        path instead; a name that is not itself a leaf (nested column)
        has no usable scalar stats -> None."""
        md = self._files[0].metadata
        for i in range(md.num_columns):
            if md.schema.column(i).path == col:
                return i
        return None

    def _stat_value(self, v, col: str):
        """One parquet stat value -> the engine's storage
        representation for the column's type (epoch days / epoch
        microseconds / python str / unscaled decimal int), so callers
        can compare stats against engine values directly."""
        import datetime
        import decimal as _dec

        if v is None:
            return None
        t = self.types.get(col)
        if t is None:
            return v
        if t.name == "date":
            if isinstance(v, datetime.date) \
                    and not isinstance(v, datetime.datetime):
                return (v - datetime.date(1970, 1, 1)).days
            return int(v)
        if t.name == "timestamp":
            if isinstance(v, datetime.datetime):
                epoch = datetime.datetime(1970, 1, 1,
                                          tzinfo=v.tzinfo)
                return int((v - epoch) / datetime.timedelta(
                    microseconds=1))
            return int(v)
        if t.is_string:
            return v.decode("utf-8", "replace") \
                if isinstance(v, bytes) else str(v)
        if t.is_decimal:
            if isinstance(v, _dec.Decimal):
                return int(v.scaleb(t.scale))
            return v
        return v

    def column_minmax(self, col: str):
        """(min, max) from row-group metadata WITHOUT reading data, in
        engine representation; None when the column is nested or any
        unit lacks statistics. Reference: TupleDomainParquetPredicate
        over ColumnChunkMetaData stats."""
        idx = self._leaf_index(col)
        if idx is None:
            return None
        los, his = [], []
        for fi, g in self.units:
            meta = self._files[fi].metadata.row_group(g)
            st = meta.column(idx).statistics
            if st is None or not st.has_min_max:
                return None
            los.append(self._stat_value(st.min, col))
            his.append(self._stat_value(st.max, col))
        if not los:
            return None
        return min(los), max(his)

    def prune_units(self, col: str, lo, hi) -> "ParquetTable":
        """Row groups whose [min, max] cannot intersect [lo, hi] drop
        out of the split list (the reader's row-group skip). `lo`/`hi`
        are engine-representation values; stats normalize to match.
        Unknown/nested columns and incomparable stats keep every unit
        (pruning is an optimization, never a correctness gate)."""
        idx = self._leaf_index(col)
        if idx is None:
            return self
        kept = []
        for fi, g in self.units:
            st = self._files[fi].metadata.row_group(g).column(
                idx).statistics
            if st is None or not st.has_min_max:
                kept.append((fi, g))
                continue
            try:
                if self._stat_value(st.max, col) < lo \
                        or self._stat_value(st.min, col) > hi:
                    continue
            except TypeError:
                kept.append((fi, g))
                continue
            kept.append((fi, g))
        if len(kept) == len(self.units):
            return self
        return ParquetTable(self.name, self.paths, kept,
                            files=self._files)


def read_parquet_table(path: str, name: str) -> ParquetTable:
    """One parquet file (or a directory of them) -> lazy table."""
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".parquet"))
        if not paths:
            raise FileNotFoundError(f"no parquet files under {path}")
        return ParquetTable(name, paths)
    return ParquetTable(name, [path])


def rows_to_arrow_table(rows: List[tuple],
                        schema: Sequence[Tuple[str, Type]]):
    """Engine result rows (to_pylist shape) -> pa.Table with engine
    value coercion (python Decimals, epoch-day dates) — THE shared
    write-side conversion for every file format (parquet, orc)."""
    import pyarrow as pa

    cols = []
    fields = []
    for i, (name, t) in enumerate(schema):
        vals = [r[i] for r in rows]
        if isinstance(t, DecimalType):
            from decimal import Decimal
            vals = [None if v is None else
                    (v if isinstance(v, Decimal)
                     else Decimal(str(round(v, t.scale)))) for v in vals]
        if t.name == "date":
            import datetime
            epoch = datetime.date(1970, 1, 1)
            vals = [None if v is None else
                    (v if isinstance(v, datetime.date)
                     else epoch + datetime.timedelta(days=int(v)))
                    for v in vals]
        fields.append(pa.field(name, _type_to_arrow(t)))
        cols.append(pa.array(vals, type=_type_to_arrow(t)))
    return pa.Table.from_arrays(cols, schema=pa.schema(fields))


def write_parquet_table(path: str, rows: List[tuple],
                        schema: Sequence[Tuple[str, Type]],
                        row_group_size: Optional[int] = None):
    """Engine result rows (to_pylist shape) -> one parquet file."""
    import pyarrow.parquet as pq

    pq.write_table(rows_to_arrow_table(rows, schema), path,
                   row_group_size=row_group_size)


def write_host_table(table: HostTable, path: str,
                     row_group_size: Optional[int] = None) -> None:
    """Vectorized HostTable -> parquet (no per-row python): numeric
    arrays pass straight through; string columns become arrow
    DictionaryArrays from their codes (dictionary pages on disk)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = table.num_rows
    fields, cols = [], []
    for c in table.column_names():
        t = table.types[c]
        mask = table.null_mask(c)
        if t.is_string:
            codes = np.asarray(table.arrays[c][:n], dtype=np.int32)
            words = list(table.dicts[c].words)
            arr = pa.DictionaryArray.from_arrays(
                pa.array(codes, type=pa.int32(),
                         mask=None if mask is None else mask),
                pa.array(words or [""], type=pa.string()))
            fields.append(pa.field(c, arr.type))
        elif t.is_decimal and not t.uses_int128:
            from decimal import Decimal
            vals = [Decimal(int(v)).scaleb(-t.scale)
                    for v in np.asarray(table.arrays[c][:n])]
            arr = pa.array(vals, type=pa.decimal128(t.precision, t.scale),
                           mask=None if mask is None else mask)
            fields.append(pa.field(c, arr.type))
        elif t.name == "date":
            arr = pa.array(np.asarray(table.arrays[c][:n],
                                      dtype=np.int32),
                           type=pa.date32(),
                           mask=None if mask is None else mask)
            fields.append(pa.field(c, arr.type))
        else:
            arr = pa.array(np.asarray(table.arrays[c][:n]),
                           mask=None if mask is None else mask)
            fields.append(pa.field(c, arr.type))
        cols.append(arr)
    pq.write_table(
        pa.Table.from_arrays(cols, schema=pa.schema(fields)), path,
        row_group_size=row_group_size)


def materialize_connector(conn, directory: str, tables: List[str],
                          row_group_size: Optional[int] = None) -> None:
    """Serialize a connector's tables into a parquet directory catalog
    (the fixture -> lakehouse bridge the scan bench uses)."""
    os.makedirs(directory, exist_ok=True)
    for t in tables:
        out = os.path.join(directory, f"{t}.parquet")
        if not os.path.exists(out):
            write_host_table(conn.table(t), out,
                             row_group_size=row_group_size)


class FileCatalogConnector(SplitSource):
    """Shared directory-catalog mechanics for file formats:
    `<dir>/<table>.<ext>` (single file) or `<dir>/<table>/`
    (multi-file, Hive-style); splits are unit ranges; an optional
    fallback serves other names (multi-catalog facade). Subclasses
    supply EXT, `_open(path, name)` and `_slice(full, name, units)`."""

    EXT = ""

    def __init__(self, directory: str, fallback=None):
        self.directory = directory
        self.fallback = fallback
        self._cache: Dict[str, HostTable] = {}

    def _open(self, path: str, name: str) -> HostTable:
        raise NotImplementedError

    def _slice(self, full, name: str, units) -> HostTable:
        raise NotImplementedError

    def _path(self, table: str) -> Optional[str]:
        p = os.path.join(self.directory, f"{table}.{self.EXT}")
        if os.path.exists(p):
            return p
        d = os.path.join(self.directory, table)
        if os.path.isdir(d):
            return d
        return None

    def _load(self, table: str):
        if table in self._cache:
            return self._cache[table]
        p = self._path(table)
        if p is None:
            return None
        t = self._open(p, table)
        self._cache[table] = t
        return t

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.schema(table)
            raise KeyError(f"unknown table {table}")
        return [(c, t.types[c]) for c in t.column_names()]

    def row_count(self, table: str) -> int:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.row_count(table)
            raise KeyError(f"unknown table {table}")
        return t.num_rows

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        full = self._load(name)
        if full is None:
            if self.fallback is not None:
                return self.fallback.table(name, part, num_parts)
            raise KeyError(f"unknown table {name}")
        if num_parts == 1:
            return full
        # split by UNIT ranges (row groups / stripes) when the layout
        # allows it — a split then reads only its own column chunks —
        # falling back to row slices when there are fewer units
        if len(full.units) >= num_parts:
            lo, hi = _slice_rows(len(full.units), part, num_parts)
            return self._slice(full, name, full.units[lo:hi])
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: full.arrays[c][lo:hi] for c in full.column_names()}
        nulls = {c: full.null_mask(c)[lo:hi]
                 for c in full.column_names()
                 if full.null_mask(c) is not None}
        return HostTable(name, hi - lo, arrays, full.types, full.dicts,
                         nulls or None)

    def scan_runs(self, table: str, max_rows: int, part: int = 0,
                  num_parts: int = 1):
        """Streaming scans with bounded PHYSICAL IO: chunk the split's
        units (row groups / stripes) greedily so each run decodes only
        its own column chunks and holds ~max_rows rows (a single unit
        larger than max_rows still ships whole — the unit is the IO
        granularity). Splits that fell back to row slicing (fewer units
        than parts) stream by row windows instead."""
        if self._load(table) is None and self.fallback is not None:
            yield from self.fallback.scan_runs(
                table, max_rows, part=part, num_parts=num_parts)
            return
        t = self.table(table, part=part, num_parts=num_parts)
        units = getattr(t, "units", None)
        if units is not None and not units:   # empty split: one empty run
            yield t
            return
        if max_rows <= 0 or units is None:
            if max_rows > 0 and t.num_rows > max_rows:
                for lo in range(0, int(t.num_rows), max_rows):
                    yield t.row_slice(lo, min(lo + max_rows,
                                              int(t.num_rows)))
            else:
                yield t
            return
        chunk, rows = [], 0
        for u in units:
            r = t.unit_rows(u)
            if chunk and rows + r > max_rows:
                yield self._slice(t, table, chunk)
                chunk, rows = [], 0
            chunk.append(u)
            rows += r
        if chunk:
            yield self._slice(t, table, chunk)

    def invalidate(self, table: Optional[str] = None):
        """Drop cached handles after files changed on disk — the
        catalog's write signal, so it also bumps the data versions the
        fragment result cache keys on."""
        if table is None:
            for t in list(self._cache):
                self.bump_table_version(t)
            self._cache.clear()
        else:
            self._cache.pop(table, None)
            self.bump_table_version(table)

    def table_version(self, table: str) -> int:
        if self._path(table) is None and self.fallback is not None:
            return self.fallback.table_version(table)
        return super().table_version(table)


class ParquetConnector(FileCatalogConnector):
    NAME = "parquet"
    EXT = "parquet"

    def _open(self, path: str, name: str) -> "ParquetTable":
        return read_parquet_table(path, name)

    def _slice(self, full, name: str, units) -> "ParquetTable":
        return ParquetTable(name, full.paths, units,
                            files=full._files)
