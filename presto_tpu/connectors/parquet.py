"""Parquet connector — columnar files -> engine Pages via Arrow.

Reference roles: presto-parquet (the Parquet->Page reader feeding scans)
+ presto-hive's file-split model, realized the way SURVEY.md §7.2 step 8
prescribes: Parquet -> Arrow -> numpy -> the engine's dictionary-coded
HostTable form. Row-group boundaries are the natural split unit
(reference: ParquetPageSourceFactory splitting by row group).

Reads through pyarrow (in-image); the write side serializes engine rows
back to Parquet so CTAS-style round-trips are testable without external
files."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT, TIMESTAMP,
    TINYINT, VARCHAR, DecimalType, Type,
)


def _arrow_to_type(t) -> Type:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return BOOLEAN
    if pa.types.is_int8(t):
        return TINYINT
    if pa.types.is_int16(t):
        return SMALLINT
    if pa.types.is_int32(t):
        return INTEGER
    if pa.types.is_int64(t):
        return BIGINT
    if pa.types.is_float32(t):
        return REAL
    if pa.types.is_float64(t):
        return DOUBLE
    if pa.types.is_date32(t) or pa.types.is_date64(t):
        return DATE
    if pa.types.is_timestamp(t):
        return TIMESTAMP
    if pa.types.is_decimal(t):
        return DecimalType(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return VARCHAR
    raise NotImplementedError(f"arrow type {t}")


def _type_to_arrow(t: Type):
    import pyarrow as pa

    if isinstance(t, DecimalType):
        return pa.decimal128(t.precision, t.scale)
    return {
        "boolean": pa.bool_(), "tinyint": pa.int8(),
        "smallint": pa.int16(), "integer": pa.int32(),
        "bigint": pa.int64(), "real": pa.float32(),
        "double": pa.float64(), "date": pa.date32(),
        "timestamp": pa.timestamp("us"), "varchar": pa.string(),
        "char": pa.string(),
    }[t.name]


def read_parquet_table(path: str, name: str) -> HostTable:
    """One Parquet file -> HostTable (whole-file; splits are row slices
    of it so string codes share one file-wide dictionary)."""
    import pyarrow.parquet as pq

    at = pq.read_table(path)
    arrays: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDict] = {}
    nulls: Dict[str, np.ndarray] = {}
    types: Dict[str, Type] = {}
    n = at.num_rows
    for field in at.schema:
        col = at.column(field.name).combine_chunks()
        t = _arrow_to_type(field.type)
        types[field.name] = t
        mask = np.asarray(col.is_null())
        nulls[field.name] = mask
        if t.is_string:
            vals = col.to_pylist()
            d, codes = StringDict.build(
                ["" if v is None else v for v in vals])
            arrays[field.name] = codes
            dicts[field.name] = d
        elif t.is_decimal:
            vals = col.to_pylist()
            arrays[field.name] = np.asarray(
                [0 if v is None else int(v.scaleb(t.scale))
                 for v in vals], dtype=np.int64)
        elif t.name == "timestamp":
            import pyarrow as pa
            us = col.cast(pa.timestamp("us")).cast(pa.int64())
            arrays[field.name] = np.where(
                mask, 0, np.asarray(us.to_pandas(), dtype=np.int64))
        else:
            np_vals = col.to_pandas().to_numpy()
            if np_vals.dtype == object or np_vals.dtype.kind in "fmM":
                if t.name == "date":
                    np_vals = np.asarray(
                        col.cast("int32").to_pandas(), dtype=np.int32)
                elif t.is_floating:
                    np_vals = np.asarray(np_vals, dtype=t.dtype)
                else:
                    np_vals = np.asarray(
                        [0 if v is None else v
                         for v in col.to_pylist()], dtype=t.dtype)
            arrays[field.name] = np.where(
                mask, t.dtype.type(0), np_vals.astype(t.dtype)) \
                if np_vals.dtype != t.dtype else np.where(
                    mask, t.dtype.type(0), np_vals)
    return HostTable(name, n, arrays, types, dicts, nulls)


def write_parquet_table(path: str, rows: List[tuple],
                        schema: Sequence[Tuple[str, Type]]):
    """Engine result rows (to_pylist shape) -> one Parquet file."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    cols = []
    fields = []
    for i, (name, t) in enumerate(schema):
        vals = [r[i] for r in rows]
        if isinstance(t, DecimalType):
            from decimal import Decimal
            vals = [None if v is None else
                    Decimal(str(round(v, t.scale))) for v in vals]
        fields.append(pa.field(name, _type_to_arrow(t)))
        cols.append(pa.array(vals, type=_type_to_arrow(t)))
    pq.write_table(pa.Table.from_arrays(cols, schema=pa.schema(fields)),
                   path)


from presto_tpu.connectors.base import SplitSource


class ParquetConnector(SplitSource):
    NAME = "parquet"
    """Directory-of-files catalog: `<dir>/<table>.parquet`. Same surface
    as the generated-fixture connectors; an optional fallback serves
    other names (multi-catalog facade, as connectors/memory.py)."""

    def __init__(self, directory: str, fallback=None):
        self.directory = directory
        self.fallback = fallback
        self._cache: Dict[str, HostTable] = {}

    def _path(self, table: str) -> str:
        return os.path.join(self.directory, f"{table}.parquet")

    def _load(self, table: str) -> Optional[HostTable]:
        if table in self._cache:
            return self._cache[table]
        p = self._path(table)
        if not os.path.exists(p):
            return None
        t = read_parquet_table(p, table)
        self._cache[table] = t
        return t

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.schema(table)
            raise KeyError(f"unknown table {table}")
        return [(c, t.types[c]) for c in t.column_names()]

    def row_count(self, table: str) -> int:
        t = self._load(table)
        if t is None:
            if self.fallback is not None:
                return self.fallback.row_count(table)
            raise KeyError(f"unknown table {table}")
        return t.num_rows

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        full = self._load(name)
        if full is None:
            if self.fallback is not None:
                return self.fallback.table(name, part, num_parts)
            raise KeyError(f"unknown table {name}")
        if num_parts == 1:
            return full
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: a[lo:hi] for c, a in full.arrays.items()}
        nulls = ({c: m[lo:hi] for c, m in full.nulls.items()}
                 if full.nulls is not None else None)
        return HostTable(name, hi - lo, arrays, full.types, full.dicts,
                         nulls)

    def invalidate(self, table: Optional[str] = None):
        if table is None:
            self._cache.clear()
        else:
            self._cache.pop(table, None)
