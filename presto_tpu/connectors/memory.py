"""Memory connector — writable in-memory tables.

Reference role: presto-memory (presto-memory/src/main/java/com/facebook/
presto/plugin/memory/ — MemoryMetadata/MemoryPagesStore), the standard
writable test backend. Tables live as host numpy arrays in the same
HostTable shape scans use, so written tables are immediately scannable
with the table-wide-StringDict invariant preserved.

An optional `fallback` connector provides read-through for names not
written here (the multi-catalog surface collapsed into one facade: CTAS
from tpch into memory works through a single engine connector)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.connectors.base import SplitSource
from presto_tpu.connectors.tpch import HostTable, _slice_rows
from presto_tpu.data.column import StringDict
from presto_tpu.types import Type


class MemoryConnector(SplitSource):
    NAME = "memory"

    def __init__(self, fallback=None):
        import threading
        self.fallback = fallback
        self.tables: Dict[str, HostTable] = {}
        # concurrent TableWriter tasks append in parallel (reference:
        # MemoryPagesStore synchronization)
        self._write_lock = threading.Lock()

    def _record_watermark(self, name: str, version: int) -> None:
        """Pair the just-bumped version with the table's cumulative row
        count (stream/watermarks.py) so delta consumers can read "rows
        since version V". A vanished table (drop / staged-move source)
        resets its history — its row count is no longer append-only."""
        from presto_tpu.stream.watermarks import watermark_store
        store = watermark_store(self)
        t = self.tables.get(name)
        if t is None:
            store.forget(name)
        else:
            store.record(name, version, t.num_rows)

    def connector_id(self, table: str = None) -> str:
        if table is not None and table not in self.tables \
                and self.fallback is not None:
            return self.fallback.connector_id(table)
        return self.NAME

    def table_version(self, table: str) -> int:
        # locally-written tables version here; read-through names keep
        # the fallback's version stream (one facade, one version truth)
        if table not in self.tables and self.fallback is not None:
            return self.fallback.table_version(table)
        return super().table_version(table)

    # ------------------------------------------------------------- reads
    def schema(self, table: str) -> List[Tuple[str, Type]]:
        t = self.tables.get(table)
        if t is not None:
            return [(c, t.types[c]) for c in t.column_names()]
        if self.fallback is not None:
            return self.fallback.schema(table)
        raise KeyError(f"unknown table {table}")

    def row_count(self, table: str) -> int:
        t = self.tables.get(table)
        if t is not None:
            return t.num_rows
        if self.fallback is not None:
            return self.fallback.row_count(table)
        raise KeyError(f"unknown table {table}")

    def table(self, name: str, part: int = 0, num_parts: int = 1
              ) -> HostTable:
        full = self.tables.get(name)
        if full is None:
            if self.fallback is not None:
                return self.fallback.table(name, part, num_parts)
            raise KeyError(f"unknown table {name}")
        if num_parts == 1:
            return full
        lo, hi = _slice_rows(full.num_rows, part, num_parts)
        arrays = {c: a[lo:hi] for c, a in full.arrays.items()}
        nulls = ({c: m[lo:hi] for c, m in full.nulls.items()}
                 if full.nulls is not None else None)
        return HostTable(name, hi - lo, arrays, full.types, full.dicts,
                         nulls)

    # ------------------------------------------------------------ writes
    def exists(self, name: str) -> bool:
        return name in self.tables

    def create(self, name: str, schema: Sequence[Tuple[str, Type]]):
        if name in self.tables:
            raise ValueError(f"table {name} already exists")
        arrays: Dict[str, np.ndarray] = {}
        dicts: Dict[str, StringDict] = {}
        types = {}
        for c, t in schema:
            types[c] = t
            if t.name in ("array", "map", "row"):
                # nested values stored as python objects host-side;
                # page() builds offset-encoded NestedColumns
                arrays[c] = np.zeros(0, object)
            elif t.is_decimal and t.uses_int128:
                # python-int unscaled values (exact 38-digit range);
                # page() builds Decimal128Column limb lanes
                arrays[c] = np.zeros(0, object)
            elif t.is_string:
                arrays[c] = np.zeros(0, np.int32)
                dicts[c] = StringDict([])
            else:
                arrays[c] = np.zeros(0, t.dtype)
        self.tables[name] = HostTable(name, 0, arrays, types, dicts)
        self._record_watermark(name, self.bump_table_version(name))

    def drop(self, name: str, if_exists: bool = False):
        if name not in self.tables and not if_exists:
            raise KeyError(f"unknown table {name}")
        if self.tables.pop(name, None) is not None:
            self._record_watermark(name, self.bump_table_version(name))

    def append_rows(self, name: str, rows: List[tuple]) -> int:
        """Append python rows (strings decoded, decimals as python
        floats — the engine's to_pylist() shape). Reference role:
        ConnectorPageSink.appendPage (MemoryPagesStore.add)."""
        with self._write_lock:
            n = self._append_rows_locked(name, rows)
            if n:
                self._record_watermark(name, self.bump_table_version(name))
            return n

    def move_table_rows(self, src: str, dst: str) -> int:
        """Move every row of `src` into `dst` (identical schemas) by raw
        array concatenation — no python-value round trip, so DECIMAL
        limbs and dictionary codes stay exact. The staged-INSERT commit
        step (reference: TableFinishOperator making sink writes visible
        atomically). Drops `src`. Returns rows moved."""
        from presto_tpu.data.column import merge_string_dicts
        with self._write_lock:
            s, t = self.tables[src], self.tables[dst]
            n_new = s.num_rows
            if n_new:
                new_arrays: Dict[str, np.ndarray] = {}
                new_dicts: Dict[str, StringDict] = dict(t.dicts)
                new_nulls: Dict[str, np.ndarray] = {}
                for c in t.column_names():
                    typ = t.types[c]
                    old_null = (t.nulls or {}).get(
                        c, np.zeros(t.num_rows, dtype=bool))[:t.num_rows]
                    src_null = (s.nulls or {}).get(
                        c, np.zeros(n_new, dtype=bool))[:n_new]
                    new_nulls[c] = np.concatenate([old_null, src_null])
                    sa = s.arrays[c][:n_new]
                    if typ.is_string:
                        union, (remap_old, remap_new) = merge_string_dicts(
                            [t.dicts[c], s.dicts[c]])
                        old_codes = t.arrays[c][:t.num_rows]
                        new_arrays[c] = np.concatenate([
                            remap_old[old_codes] if len(remap_old)
                            else old_codes,
                            remap_new[sa] if len(remap_new) else sa])
                        new_dicts[c] = union
                    else:
                        new_arrays[c] = np.concatenate(
                            [t.arrays[c][:t.num_rows], sa])
                self.tables[dst] = HostTable(
                    dst, t.num_rows + n_new, new_arrays, t.types,
                    new_dicts, new_nulls)
            self.tables.pop(src, None)
            self._record_watermark(src, self.bump_table_version(src))
            self._record_watermark(dst, self.bump_table_version(dst))
            return n_new

    def register_row_slice(self, src: str, dst: str, lo: int,
                           hi: int) -> int:
        """Register rows [lo, hi) of `src` as a temp table `dst` — a
        zero-copy array view (dicts shared, arrays sliced) backing the
        incremental-MV delta scan: the maintenance query runs against
        `dst` through the ordinary scan path and sees exactly the rows
        one watermark interval appended. Returns the view's row count;
        drop `dst` normally when done."""
        with self._write_lock:
            if dst in self.tables:
                raise ValueError(f"table {dst} already exists")
            s = self.tables[src]
            lo = max(0, min(int(lo), s.num_rows))
            hi = max(lo, min(int(hi), s.num_rows))
            arrays = {c: a[lo:hi] for c, a in s.arrays.items()}
            nulls = ({c: m[lo:hi] for c, m in s.nulls.items()}
                     if s.nulls is not None else None)
            self.tables[dst] = HostTable(dst, hi - lo, arrays, s.types,
                                         s.dicts, nulls)
            self._record_watermark(dst, self.bump_table_version(dst))
            return hi - lo

    def _append_rows_locked(self, name: str, rows: List[tuple]) -> int:
        t = self.tables[name]
        cols = t.column_names()
        n_new = len(rows)
        if n_new == 0:
            return 0
        new_arrays: Dict[str, np.ndarray] = {}
        new_dicts: Dict[str, StringDict] = dict(t.dicts)
        new_nulls: Dict[str, np.ndarray] = {}
        for i, c in enumerate(cols):
            typ = t.types[c]
            vals = [r[i] for r in rows]
            old_null = (t.nulls or {}).get(
                c, np.zeros(t.num_rows, dtype=bool))[:t.num_rows]
            new_nulls[c] = np.concatenate(
                [old_null, np.asarray([v is None for v in vals], bool)])
            if typ.name in ("array", "map", "row"):
                arr = np.empty(n_new, object)
                arr[:] = vals
                new_arrays[c] = np.concatenate(
                    [t.arrays[c][:t.num_rows], arr])
            elif typ.is_string:
                # merge into one table-wide sorted dictionary, remapping
                # existing codes (the shared cross-page dictionary
                # machinery, data/column.merge_string_dicts)
                from presto_tpu.data.column import merge_string_dicts
                new_words, new_codes = StringDict.build(
                    ["" if v is None else v for v in vals])
                union, (remap_old, remap_new) = merge_string_dicts(
                    [t.dicts[c], new_words])
                if union.words == t.dicts[c].words:
                    # no new words: keep the OLD dict object — it is
                    # identity-hashed jit aux data, so swapping in an
                    # equal copy would invalidate every compiled
                    # program scanning this table (steady-state ingest
                    # would recompile per batch)
                    union = t.dicts[c]
                old_codes = t.arrays[c][:t.num_rows]
                old_new = (remap_old[old_codes] if len(remap_old)
                           else old_codes)
                new_arrays[c] = np.concatenate(
                    [old_new, remap_new[new_codes]])
                new_dicts[c] = union
            else:
                filled = [0 if v is None else v for v in vals]
                if typ.is_decimal and typ.uses_int128:
                    # DECIMAL(p>18): python-int unscaled values in an
                    # object array — exact for the full 38-digit range
                    # (int64 storage capped exactness at 2^63; the page
                    # builds four 32-bit limb lanes from these)
                    from presto_tpu.data.column import unscale_decimal
                    arr = np.empty(n_new, object)
                    arr[:] = [int(unscale_decimal(v, typ.scale))
                              for v in filled]
                elif typ.is_decimal:
                    # exact unscale, one shared rounding rule
                    from presto_tpu.data.column import unscale_decimal
                    arr = np.asarray(
                        [unscale_decimal(v, typ.scale) for v in filled],
                        np.int64)
                else:
                    arr = np.asarray(filled, dtype=typ.dtype)
                new_arrays[c] = np.concatenate(
                    [t.arrays[c][:t.num_rows], arr])
        self.tables[name] = HostTable(name, t.num_rows + n_new,
                                      new_arrays, t.types, new_dicts,
                                      new_nulls)
        return n_new
