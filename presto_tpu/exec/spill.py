"""Disk spill — file-backed partials and external sort.

Reference roles: spiller/FileSingleStreamSpiller.java (+ SpillerFactory,
GenericSpiller) writing serialized pages to spill files, and
MemoryRevokingScheduler revoking operator memory into those files. The
spill format here is the engine's own SerializedPage wire codec
(protocol/serde) with LZ4 — the same dogfooding the reference does with
its PagesSerde, so a spill file is bit-identical to an exchange stream
and every type (strings, DECIMAL(38) limb lanes, nested) round-trips.

Two consumers:
  - exec/lifespan.BatchedRunner: aggregation partials revoke to disk
    under `spill_enabled` + `spill_path` (was: host RAM only).
  - external_sort(): sorted run files + streaming k-way merge — the
    sort spill the reference gets from OrderByOperator + spiller.
"""

from __future__ import annotations

import heapq
import os
import sys
import tempfile
import uuid
from typing import Iterator, List, Optional, Sequence, Tuple

from presto_tpu.data.column import Page
from presto_tpu.obs.metrics import counter as _counter

_M_SPILLED = _counter(
    "presto_tpu_spilled_bytes_total",
    "Bytes written to disk spill files (sort runs, revoked "
    "aggregation partials, partitioned join builds)")
_M_SPILL_FAILURES = _counter(
    "presto_tpu_spill_failures_total",
    "Spill writes that failed on a disk error (ENOSPC / torn write); "
    "each one unlinked its partial run file and raised SpillError")


class SpillError(RuntimeError):
    """Classified spill-write failure (ENOSPC / torn write / EIO on a
    spill file). Carries the classification the client protocol needs:
    a query that dies here fails cleanly instead of surfacing a bare
    OSError from deep inside an operator."""

    def __init__(self, message: str):
        super().__init__(f"Spill failed: {message}")


def _disk_faults():
    """The installed testing.faults disk injector, without importing
    the testing package (no injector can exist if it was never
    imported, and production pays one dict lookup)."""
    mod = sys.modules.get("presto_tpu.testing.faults")
    return getattr(mod, "_DISK", None) if mod is not None else None


class SpillHandle:
    __slots__ = ("path", "num_rows", "types", "names", "bytes")

    def __init__(self, path: str, num_rows: int, types, names,
                 nbytes: int):
        self.path = path
        self.num_rows = num_rows
        self.types = types
        self.names = names
        self.bytes = nbytes


class FileSpiller:
    """Write pages to spill files; read them back page by page.
    One directory per spiller instance, deleted on close (the
    reference's per-query spill-path lifecycle)."""

    def __init__(self, directory: Optional[str] = None,
                 codec: str = "lz4"):
        self._own_dir = directory is None
        self.directory = directory or tempfile.mkdtemp(
            prefix="presto_tpu_spill_")
        os.makedirs(self.directory, exist_ok=True)
        self.codec = codec
        self.handles: List[SpillHandle] = []
        self.total_spilled_bytes = 0

    def spill(self, page: Page) -> SpillHandle:
        from presto_tpu.protocol.serde import (
            encode_serialized_page, page_to_wire_blocks,
        )
        frame = encode_serialized_page(
            page_to_wire_blocks(page), checksummed=True,
            compression=self.codec)
        path = os.path.join(self.directory,
                            f"run_{len(self.handles)}_{uuid.uuid4().hex[:8]}")
        inj = _disk_faults()
        try:
            with open(path, "wb") as f:
                if inj is None:
                    f.write(frame)
                else:
                    inj.write("spill", f, frame)
        except OSError as e:
            # a partial run file is unreadable garbage — it must not
            # outlive the failure (close() only knows recorded handles)
            try:
                os.unlink(path)
            except OSError:
                pass
            _M_SPILL_FAILURES.inc()
            raise SpillError(f"spill write failed: {e}") from e
        h = SpillHandle(path, int(page.num_rows),
                        [c.type for c in page.columns],
                        tuple(page.names), len(frame))
        self.handles.append(h)
        self.total_spilled_bytes += len(frame)
        _M_SPILLED.inc(len(frame))
        return h

    def read(self, handle: SpillHandle) -> Page:
        from presto_tpu.protocol.serde import (
            decode_serialized_page, wire_blocks_to_page,
        )
        with open(handle.path, "rb") as f:
            data = f.read()
        blocks, n, _ = decode_serialized_page(data)
        page = wire_blocks_to_page(blocks, list(handle.types), n)
        page.names = handle.names
        return page

    def read_rows(self, handle: SpillHandle) -> Iterator[tuple]:
        yield from self.read(handle).to_pylist()

    def close(self):
        """Idempotent teardown. An OWNED directory is removed whole
        (strays from a mid-spill crash included); a caller-supplied
        directory only loses the files this spiller recorded — never
        the caller's other contents."""
        for h in self.handles:
            try:
                os.unlink(h.path)
            except OSError:
                pass
        self.handles = []
        if self._own_dir:
            import shutil
            shutil.rmtree(self.directory, ignore_errors=True)

    # context-manager form: `with FileSpiller(...) as sp:` guarantees
    # close on every exit path (the FileSingleStreamSpiller closeable
    # contract)
    def __enter__(self) -> "FileSpiller":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def merge_sorted_rows(iters: Sequence[Iterator[tuple]], keys
                      ) -> Iterator[tuple]:
    """Streaming k-way merge of row iterators already sorted by `keys`
    (ops/keys.SortKey sequence) — SQL null ordering, per-key direction,
    total-order NaN placement. Shared by the external sort and the
    coordinator's ordered merge exchange."""

    class _Key:
        __slots__ = ("row",)

        def __init__(self, row):
            self.row = row

        def __lt__(self, other):
            for k in keys:
                a = self.row[k.field]
                b = other.row[k.field]
                if a is None or b is None:
                    if (a is None) != (b is None):
                        return (a is None) == k.nulls_sort_first
                    continue
                a_nan = isinstance(a, float) and a != a
                b_nan = isinstance(b, float) and b != b
                if a_nan or b_nan:
                    if a_nan != b_nan:
                        return b_nan
                    continue
                if a == b:
                    continue
                return (a < b) == k.ascending
            return False

    return heapq.merge(*iters, key=_Key)


def external_sort(ex, plan, driving: str, num_batches: int,
                  spill_dir: Optional[str] = None
                  ) -> Tuple[List[tuple], int]:
    """Disk-backed external sort: run the sort plan once per driving-scan
    lifespan (each run sorts its slice on device), spill every sorted
    run file, then stream-merge the runs. Peak device/host memory is one
    lifespan + the merge window, not the whole table (reference:
    OrderByOperator spilling through FileSingleStreamSpiller).

    `ex` is a SplitExecutor; `plan` must be the SORT subtree (its output
    is sorted rows). Returns (rows, spilled_bytes)."""
    spiller = FileSpiller(spill_dir)
    try:
        for b in range(num_batches):
            ex.set_splits({driving: [(b, num_batches)]})
            run = ex.execute(plan)
            spiller.spill(run)
        keys = plan.keys
        merged = merge_sorted_rows(
            [spiller.read_rows(h) for h in spiller.handles], keys)
        return list(merged), spiller.total_spilled_bytes
    finally:
        spiller.close()
