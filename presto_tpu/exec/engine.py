"""LocalEngine — single-process parse->plan->execute entry point.

Reference role: LocalQueryRunner
(presto-main-base/.../testing/LocalQueryRunner.java:311) — the full engine
in one process, no HTTP, used for tests, benchmarks and as the worker's
fragment-execution core."""

from __future__ import annotations

from typing import List, Tuple

from presto_tpu.exec.executor import Executor
from presto_tpu.plan.nodes import PlanNode, explain
from presto_tpu.sql.analyzer import Planner
from presto_tpu.sql.parser import parse_sql


class LocalEngine:
    def __init__(self, connector, session=None, history=None,
                 memory_pool=None, cluster_memory=None):
        from presto_tpu.config import Session

        s = session or Session()
        if s["cte_materialization_enabled"]:
            # temp tables for materialized CTEs live in a memory overlay
            # over the catalog (reference: PhysicalCteOptimizer writing
            # to the configured temp-table storage)
            from presto_tpu.connectors.memory import MemoryConnector
            if not hasattr(connector, "create"):
                connector = MemoryConnector(fallback=connector)
        self.connector = connector
        self.planner = Planner(connector)
        self.executor = Executor(connector, session=s)
        # memory-management hierarchy (exec/memory.py; reference:
        # MemoryPool.java + ClusterMemoryManager.java:106): reservations
        # per query, spill-before-fail revocation, cluster kill checks
        self.memory_pool = memory_pool
        self.cluster_memory = cluster_memory
        self.executor.memory_pool = memory_pool
        self._plans = {}
        # HBO store (plan/stats.HistoryStore): observed node row counts
        # recorded after execution, consulted by the next planning
        self.history = history
        self.last_join_reorders = 0
        self.last_memory_fallback_batches = 0
        # stats dict from the spillable-join fallback of the last query
        # that took it (exec/spill_join.py), None otherwise
        self.last_spill_join_stats = None

    @property
    def session(self):
        return self.executor.session

    def plan_sql(self, sql: str) -> PlanNode:
        if sql not in self._plans:
            plan = self.planner.plan_query(parse_sql(sql))
            if self.session["join_reordering_enabled"]:
                from presto_tpu.plan.iterative import reorder_joins
                plan, self.last_join_reorders = reorder_joins(
                    plan, self.connector, self.history)
            self._plans[sql] = plan
        return self._plans[sql]

    def explain_sql(self, sql: str) -> str:
        return explain(self.plan_sql(sql))

    def execute_sql(self, sql: str) -> List[tuple]:
        from presto_tpu.utils.tracing import query_lifecycle

        # plugin access control (spi/security SystemAccessControl):
        # query entry + every scanned table
        from presto_tpu.spi import manager as _plugins
        user = getattr(self, "user", "") or ""
        _plugins.check_can_execute(user, sql)

        LocalEngine._qid += 1
        qid = f"local_{LocalEngine._qid}"
        with query_lifecycle(qid, sql) as box:
            _plugins.check_statement_access(
                user, sql,
                plan_full=lambda: self.plan_sql(sql),
                plan_query=self.planner.plan_query)
            if self.memory_pool is None:
                box[0] = self._execute_sql_inner(sql, qid)
            else:
                box[0] = self._execute_under_pool(sql, qid)
        return box[0]

    def _execute_under_pool(self, sql: str, qid: str) -> List[tuple]:
        """Memory-governed execution (reference: MemoryPool admission +
        MemoryRevokingScheduler spill-before-fail + ClusterMemoryManager
        kill): reservations are static lowering footprints; an admission
        failure retries lifespan-batched under the pool's remaining
        headroom (partials leave HBM between lifespans — the revocation
        behavior) before surfacing an error; a cluster-level kill beats
        everything."""
        from presto_tpu.exec.memory import ExceededMemoryLimitError
        if self.cluster_memory is not None:
            self.cluster_memory.check_killed(qid)
        self.executor.pool_query_id = qid
        try:
            try:
                out = self._execute_sql_inner(sql, qid)
            except ExceededMemoryLimitError:
                if self.cluster_memory is not None:
                    self.cluster_memory.check_killed(qid)
                from presto_tpu.exec.executor import MemoryLimitExceeded
                from presto_tpu.exec.lifespan import execute_bounded
                plan = self.plan_sql(sql)
                # the aborted attempt's buffers are unwound — release
                # its reservations BEFORE sizing the batched retry
                self.memory_pool.free(qid)
                headroom = max(self.memory_pool.budget
                               - self.memory_pool.reserved, 1)
                try:
                    page, batches = execute_bounded(
                        self.connector, plan, headroom,
                        session=self.session)
                    self.last_memory_fallback_batches = batches
                except MemoryLimitExceeded as mle:
                    # join-rooted plans are unbatchable — partition both
                    # sides through the spiller instead (Grace hash join)
                    from presto_tpu.exec.spill_join import (
                        SpillJoinUnsupported, execute_spill_join)
                    try:
                        page, sj_stats = execute_spill_join(
                            self.connector, plan, headroom,
                            session=self.session)
                    except SpillJoinUnsupported:
                        raise mle
                    self.last_spill_join_stats = sj_stats
                out = page.to_pylist()
            if self.cluster_memory is not None:
                # kill sweep runs while this query's reservations are
                # still live; if WE are the biggest over-budget query,
                # the kill lands on us (mid-flight LowMemoryKiller
                # semantics in this sequential engine)
                self.cluster_memory.maybe_kill()
                self.cluster_memory.check_killed(qid)
            return out
        finally:
            self.memory_pool.free(qid)

    _qid = 0

    def _execute_sql_inner(self, sql: str, qid: str) -> List[tuple]:
        from presto_tpu.utils import TRACER

        head = sql.lstrip().split(None, 1)[0].lower() if sql.strip() else ""
        if head == "explain":
            # EXPLAIN [ANALYZE] <query> (reference: sql/tree/Explain ->
            # ExplainRewrite): one VARCHAR row per plan line
            rest = sql.lstrip()[len("explain"):].lstrip()
            if rest.lower().startswith("analyze"):
                text = self.explain_analyze_sql(
                    rest[len("analyze"):].lstrip())
            else:
                text = self.explain_sql(rest)
            return [(line,) for line in text.splitlines()]
        if head in ("create", "insert", "drop", "delete", "refresh"):
            return self._execute_statement(sql)
        if self.session["cte_materialization_enabled"]:
            q = parse_sql(sql)
            if q.ctes:
                # only WITH queries take the rewrite; CTE-free ones keep
                # the normal path (lifespan batching, HBO recording)
                return self._execute_with_cte_materialization(q, qid)
        with TRACER.span(qid, "plan"):
            plan = self.plan_sql(sql)
        n = self.session["lifespan_batches"]
        if n and n > 1:
            from presto_tpu.exec.lifespan import execute_batched
            self.last_lifespan_stats = {}
            with TRACER.span(qid, "execute", mode="lifespan", batches=n):
                page = execute_batched(
                    self.connector, plan, n,
                    self.session["query_max_memory_per_node"],
                    session=self.session, stats=self.last_lifespan_stats)
            # batched runs use their own executors — no per-node counters
            # here, and stale ones from an earlier direct execution must
            # not be re-recorded against this query
            self.executor.last_node_rows = {}
        else:
            with TRACER.span(qid, "execute", mode="direct"):
                page = self.executor.execute(plan)
            self._record_history()
        return page.to_pylist()

    def _record_history(self):
        """Feed observed per-node output rows into the HBO store
        (reference: HistoryBasedPlanStatisticsTracker.java:78 hooking
        query completion). Requires collect_stats (the EXPLAIN ANALYZE
        counters are the measurement source)."""
        if self.history is None or not self.executor.last_node_rows:
            return
        from presto_tpu.plan.stats import canonical_key
        for nid, rows in self.executor.last_node_rows.items():
            entry = self.executor._node_map.get(nid)
            if entry is not None:
                self.history.record(canonical_key(entry[0]), rows)
        self.history.save()     # no-op for in-memory stores

    def _execute_with_cte_materialization(self, q, qid: str
                                          ) -> List[tuple]:
        """Multiply-referenced CTEs execute once into memory-overlay temp
        tables (exec/cte.py; reference PhysicalCteOptimizer.java:126).
        `q` is the already-parsed ast.Select."""
        from presto_tpu.exec.cte import materialize_ctes
        from presto_tpu.utils import TRACER

        def run_select(sub_q):
            plan = self.planner.plan_query(sub_q)
            page = self.executor.execute(plan)
            return (page.to_pylist(), list(plan.output_names),
                    list(plan.output_types))

        with TRACER.span(qid, "materialize_ctes"):
            q, temps = materialize_ctes(q, run_select, self.connector)
        try:
            with TRACER.span(qid, "plan"):
                plan = self.planner.plan_query(q)
            with TRACER.span(qid, "execute", mode="direct",
                             materialized_ctes=len(temps)):
                page = self.executor.execute(plan)
            self._record_history()
            return page.to_pylist()
        finally:
            for t in temps:
                self.connector.drop(t, if_exists=True)

    # ------------------------------------------------------------ DDL/DML
    def _execute_statement(self, sql: str) -> List[tuple]:
        """CREATE TABLE [AS] / INSERT / DROP TABLE against a writable
        connector (connectors/memory.py). Reference roles: the engine DDL
        tasks (execution/CreateTableTask.java, coordinator-planned
        TableWriterNode/TableFinishNode -> ConnectorPageSink); the write
        itself is a host-side sink outside the jit fragment, fed by the
        inner query's result page."""
        from presto_tpu.expr.nodes import Literal
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.analyzer import AnalysisError
        from presto_tpu.sql.parser import parse_statement

        stmt = parse_statement(sql)
        conn = self.connector
        if isinstance(stmt, (A.CreateMaterializedView,
                             A.RefreshMaterializedView,
                             A.DropMaterializedView)):
            # materialized views need no writable catalog (full
            # recompute reads; delta scans degrade gracefully)
            return self._execute_mv(stmt)
        writable = hasattr(conn, "create")
        if isinstance(stmt, A.DropTable):
            if not writable:
                raise AnalysisError("connector is not writable")
            conn.drop(stmt.name, if_exists=stmt.if_exists)
            return [(0,)]
        if not writable:
            raise AnalysisError("connector is not writable")

        if isinstance(stmt, A.Delete):
            # DELETE FROM t WHERE pred (reference: sql/tree/Delete ->
            # DeleteNode + ConnectorMetadata.beginDelete): a row
            # survives iff pred IS NOT TRUE; the surviving rows rewrite
            # the table (memory-style connectors rewrite; the count row
            # is deleted rows, the TableWriter contract).
            if not conn.exists(stmt.name):
                raise AnalysisError(f"unknown table {stmt.name}")
            total = conn.table(stmt.name).num_rows
            if stmt.where is None:
                kept = []
            else:
                keep_pred = A.BinaryOp(
                    "or", A.UnaryOp("not", stmt.where),
                    A.IsNull(stmt.where))
                keep_q = A.Select(
                    items=(A.SelectItem(A.Star()),),
                    relations=(A.TableRef(stmt.name),),
                    where=keep_pred)
                plan = self.planner.plan_query(keep_q)
                page = self.executor.execute(plan)
                kept = page.to_pylist()
            schema = conn.schema(stmt.name)
            conn.drop(stmt.name)
            conn.create(stmt.name, schema)
            if kept:
                conn.append_rows(stmt.name, kept)
            return [(total - len(kept),)]

        if isinstance(stmt, A.CreateTable):
            if stmt.if_not_exists and conn.exists(stmt.name):
                return [(0,)]
            from presto_tpu.types import (
                ArrayType, MapType, RowType, parse_type as parse_sql_type,
            )
            cols = []
            for c, sig in stmt.columns:
                try:
                    t = parse_sql_type(sig)
                except (ValueError, NotImplementedError) as e:
                    raise AnalysisError(f"column {c!r}: {e}") from e
                if isinstance(t, (ArrayType, MapType, RowType)):
                    raise AnalysisError(
                        f"column {c!r}: type {t} is not supported for "
                        "table storage")
                cols.append((c, t))
            conn.create(stmt.name, cols)
            return [(0,)]

        if isinstance(stmt, A.CreateTableAs):
            if stmt.if_not_exists and conn.exists(stmt.name):
                return [(0,)]
            plan = self.planner.plan_query(stmt.query)
            rows = self.executor._page_rows(self.executor.execute(plan))
            conn.create(stmt.name, list(zip(plan.output_names,
                                            plan.output_types)))
            n = conn.append_rows(stmt.name, rows)
            return [(n,)]

        if isinstance(stmt, A.Insert):
            schema = conn.schema(stmt.name)
            names = [c for c, _t in schema]
            if stmt.query is not None:
                plan = self.planner.plan_query(stmt.query)
                rows = self.executor._page_rows(
                    self.executor.execute(plan))
            else:
                rows = []
                for r in stmt.rows:
                    vals = []
                    for e in r:
                        lit = self.planner.analyze(e, ())
                        if not isinstance(lit, Literal):
                            raise AnalysisError(
                                "INSERT VALUES must be literals")
                        v = lit.value
                        if v is not None and lit.type.is_decimal:
                            # exact: append_rows re-unscales via
                            # unscale_decimal, so no float64 round trip
                            from presto_tpu.data.column import \
                                scale_down_decimal
                            v = scale_down_decimal(int(v),
                                                   lit.type.scale)
                        vals.append(v)
                    rows.append(tuple(vals))
            if stmt.columns:
                unknown = [c for c in stmt.columns if c not in names]
                if unknown:
                    raise AnalysisError(
                        f"INSERT columns not in table: {unknown}")
                for r in rows:
                    if len(r) != len(stmt.columns):
                        raise AnalysisError(
                            f"INSERT arity {len(r)} != column list "
                            f"{len(stmt.columns)}")
                pos = {c: i for i, c in enumerate(stmt.columns)}
                rows = [tuple(r[pos[c]] if c in pos else None
                              for c in names) for r in rows]
            else:
                for r in rows:
                    if len(r) != len(names):
                        raise AnalysisError(
                            f"INSERT arity {len(r)} != table {len(names)}")
            n = conn.append_rows(stmt.name, rows)
            return [(n,)]

        raise AnalysisError(f"unsupported statement {type(stmt).__name__}")

    @property
    def mv_manager(self):
        """Lazy materialized-view manager (presto_tpu/mv/) — created on
        first MV statement so query-only engines pay nothing."""
        if getattr(self, "_mv_manager", None) is None:
            from presto_tpu.mv.manager import MaterializedViewManager
            self._mv_manager = MaterializedViewManager(
                self.connector, run_sql=self.execute_sql)
        return self._mv_manager

    def _execute_mv(self, stmt) -> List[tuple]:
        """CREATE/REFRESH/DROP MATERIALIZED VIEW (reference: the
        *MaterializedView*Task statement handlers); REFRESH returns the
        base rows scanned, the TableWriter-style count row."""
        from presto_tpu.mv.manager import MVError
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.analyzer import AnalysisError

        try:
            if isinstance(stmt, A.CreateMaterializedView):
                self.mv_manager.create(
                    stmt.name, stmt.sql,
                    if_not_exists=stmt.if_not_exists)
                return [(0,)]
            if isinstance(stmt, A.RefreshMaterializedView):
                _kind, n = self.mv_manager.refresh(stmt.name)
                return [(n,)]
            self.mv_manager.drop(stmt.name, if_exists=stmt.if_exists)
            return [(0,)]
        except MVError as e:
            raise AnalysisError(str(e)) from e

    def explain_analyze_sql(self, sql: str) -> str:
        from presto_tpu.exec.stats import explain_analyze
        return explain_analyze(self, sql)

    def column_names(self, sql: str) -> Tuple[str, ...]:
        return self.plan_sql(sql).output_names
