"""LocalEngine — single-process parse->plan->execute entry point.

Reference role: LocalQueryRunner
(presto-main-base/.../testing/LocalQueryRunner.java:311) — the full engine
in one process, no HTTP, used for tests, benchmarks and as the worker's
fragment-execution core."""

from __future__ import annotations

from typing import List, Tuple

from presto_tpu.exec.executor import Executor
from presto_tpu.plan.nodes import PlanNode, explain
from presto_tpu.sql.analyzer import Planner
from presto_tpu.sql.parser import parse_sql


class LocalEngine:
    def __init__(self, connector, session=None, history=None):
        self.connector = connector
        self.planner = Planner(connector)
        self.executor = Executor(connector, session=session)
        self._plans = {}
        # HBO store (plan/stats.HistoryStore): observed node row counts
        # recorded after execution, consulted by the next planning
        self.history = history

    @property
    def session(self):
        return self.executor.session

    def plan_sql(self, sql: str) -> PlanNode:
        if sql not in self._plans:
            self._plans[sql] = self.planner.plan_query(parse_sql(sql))
        return self._plans[sql]

    def explain_sql(self, sql: str) -> str:
        return explain(self.plan_sql(sql))

    def execute_sql(self, sql: str) -> List[tuple]:
        head = sql.lstrip().split(None, 1)[0].lower() if sql.strip() else ""
        if head in ("create", "insert", "drop"):
            return self._execute_statement(sql)
        n = self.session["lifespan_batches"]
        if n and n > 1:
            from presto_tpu.exec.lifespan import execute_batched
            self.last_lifespan_stats = {}
            page = execute_batched(
                self.connector, self.plan_sql(sql), n,
                self.session["query_max_memory_per_node"],
                session=self.session, stats=self.last_lifespan_stats)
            # batched runs use their own executors — no per-node counters
            # here, and stale ones from an earlier direct execution must
            # not be re-recorded against this query
            self.executor.last_node_rows = {}
        else:
            page = self.executor.execute(self.plan_sql(sql))
            self._record_history()
        return page.to_pylist()

    def _record_history(self):
        """Feed observed per-node output rows into the HBO store
        (reference: HistoryBasedPlanStatisticsTracker.java:78 hooking
        query completion). Requires collect_stats (the EXPLAIN ANALYZE
        counters are the measurement source)."""
        if self.history is None or not self.executor.last_node_rows:
            return
        from presto_tpu.plan.stats import canonical_key
        for nid, rows in self.executor.last_node_rows.items():
            entry = self.executor._node_map.get(nid)
            if entry is not None:
                self.history.record(canonical_key(entry[0]), rows)

    # ------------------------------------------------------------ DDL/DML
    def _execute_statement(self, sql: str) -> List[tuple]:
        """CREATE TABLE [AS] / INSERT / DROP TABLE against a writable
        connector (connectors/memory.py). Reference roles: the engine DDL
        tasks (execution/CreateTableTask.java, coordinator-planned
        TableWriterNode/TableFinishNode -> ConnectorPageSink); the write
        itself is a host-side sink outside the jit fragment, fed by the
        inner query's result page."""
        from presto_tpu.expr.nodes import Literal
        from presto_tpu.protocol.translate import parse_type
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.analyzer import AnalysisError
        from presto_tpu.sql.parser import parse_statement

        stmt = parse_statement(sql)
        conn = self.connector
        writable = hasattr(conn, "create")
        if isinstance(stmt, A.DropTable):
            if not writable:
                raise AnalysisError("connector is not writable")
            conn.drop(stmt.name, if_exists=stmt.if_exists)
            return [(0,)]
        if not writable:
            raise AnalysisError("connector is not writable")

        if isinstance(stmt, A.CreateTable):
            if stmt.if_not_exists and conn.exists(stmt.name):
                return [(0,)]
            conn.create(stmt.name, [(c, parse_type(sig))
                                    for c, sig in stmt.columns])
            return [(0,)]

        if isinstance(stmt, A.CreateTableAs):
            if stmt.if_not_exists and conn.exists(stmt.name):
                return [(0,)]
            plan = self.planner.plan_query(stmt.query)
            rows = self.executor._page_rows(self.executor.execute(plan))
            conn.create(stmt.name, list(zip(plan.output_names,
                                            plan.output_types)))
            n = conn.append_rows(stmt.name, rows)
            return [(n,)]

        if isinstance(stmt, A.Insert):
            schema = conn.schema(stmt.name)
            names = [c for c, _t in schema]
            if stmt.query is not None:
                plan = self.planner.plan_query(stmt.query)
                rows = self.executor._page_rows(
                    self.executor.execute(plan))
            else:
                rows = []
                for r in stmt.rows:
                    vals = []
                    for e in r:
                        lit = self.planner.analyze(e, ())
                        if not isinstance(lit, Literal):
                            raise AnalysisError(
                                "INSERT VALUES must be literals")
                        v = lit.value
                        if v is not None and lit.type.is_decimal:
                            v = v / 10 ** lit.type.scale
                        vals.append(v)
                    rows.append(tuple(vals))
            if stmt.columns:
                pos = {c: i for i, c in enumerate(stmt.columns)}
                rows = [tuple(r[pos[c]] if c in pos else None
                              for c in names) for r in rows]
            elif rows and len(rows[0]) != len(names):
                raise AnalysisError(
                    f"INSERT arity {len(rows[0])} != table {len(names)}")
            n = conn.append_rows(stmt.name, rows)
            return [(n,)]

        raise AnalysisError(f"unsupported statement {type(stmt).__name__}")

    def explain_analyze_sql(self, sql: str) -> str:
        from presto_tpu.exec.stats import explain_analyze
        return explain_analyze(self, sql)

    def column_names(self, sql: str) -> Tuple[str, ...]:
        return self.plan_sql(sql).output_names
