"""LocalEngine — single-process parse->plan->execute entry point.

Reference role: LocalQueryRunner
(presto-main-base/.../testing/LocalQueryRunner.java:311) — the full engine
in one process, no HTTP, used for tests, benchmarks and as the worker's
fragment-execution core."""

from __future__ import annotations

from typing import List, Tuple

from presto_tpu.exec.executor import Executor
from presto_tpu.plan.nodes import PlanNode, explain
from presto_tpu.sql.analyzer import Planner
from presto_tpu.sql.parser import parse_sql


class LocalEngine:
    def __init__(self, connector, session=None):
        self.connector = connector
        self.planner = Planner(connector)
        self.executor = Executor(connector, session=session)
        self._plans = {}

    @property
    def session(self):
        return self.executor.session

    def plan_sql(self, sql: str) -> PlanNode:
        if sql not in self._plans:
            self._plans[sql] = self.planner.plan_query(parse_sql(sql))
        return self._plans[sql]

    def explain_sql(self, sql: str) -> str:
        return explain(self.plan_sql(sql))

    def execute_sql(self, sql: str) -> List[tuple]:
        n = self.session["lifespan_batches"]
        if n and n > 1:
            from presto_tpu.exec.lifespan import execute_batched
            page = execute_batched(
                self.connector, self.plan_sql(sql), n,
                self.session["query_max_memory_per_node"])
        else:
            page = self.executor.execute(self.plan_sql(sql))
        return page.to_pylist()

    def explain_analyze_sql(self, sql: str) -> str:
        from presto_tpu.exec.stats import explain_analyze
        return explain_analyze(self, sql)

    def column_names(self, sql: str) -> Tuple[str, ...]:
        return self.plan_sql(sql).output_names
