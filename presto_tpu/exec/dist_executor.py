"""DistExecutor — the whole SQL plan as ONE shard_map program over a mesh.

Reference roles, fused into a single compiled unit:
  - AddExchanges/PlanFragmenter decide the distribution (plan/fragment.py)
  - each fragment's operator pipeline = the same local operator lowering
    the single-chip Executor uses (inherited)
  - every ExchangeNode lowers to an ICI collective: hash repartition ->
    lax.all_to_all, broadcast -> all_gather, single -> all_gather + only
    device 0 keeps rows (the coordinator-facing SINGLE distribution,
    reference SystemPartitioningHandle.SINGLE)

The reference runs fragments as separate tasks streaming pages over HTTP
(SqlStageExecution / ExchangeClient.java:71); on one multi-chip TPU worker
the fragments are instead fused into one XLA program so the compiler
overlaps compute with the collectives — the exchanges become program edges,
not network calls. Across hosts the same fragment tree maps onto the HTTP
pull protocol (protocol/, server/).

Overflow-retry: per-node counters (group counts, join duplicates, exchange
receive totals and per-peer send maxima) are pmax'd over the mesh and
fetched in one host sync; the generic retry loop re-lowers at bigger
buckets, exactly like the local executor.
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Page, bucket_capacity
from presto_tpu.exec.executor import Executor, ScanSpec
from presto_tpu.parallel.mesh import AXIS, run_sharded, stack_pages, \
    unstack_page
from presto_tpu.parallel.shuffle import all_gather_page, partition_ids, \
    repartition_page
from presto_tpu.plan.fragment import add_exchanges
from presto_tpu.plan.nodes import Partitioning, PlanNode, Step


class DistExecutor(Executor):
    """Executes plans distributed over an N-device mesh (CPU mesh in
    tests, TPU ICI in production)."""

    # the whole distributed plan lowers into ONE shard_map program
    # (exchanges are ICI collectives inside it) — island splitting does
    # not apply here
    _force_fused = True

    def __init__(self, connector, mesh, session=None, history=None):
        super().__init__(connector, session=session)
        self.mesh = mesh
        self.ndev = int(mesh.devices.size)
        # HBO store consulted by add_exchanges at _prepare time
        self.history = history

    # ---- fragment-by-fragment execution ---------------------------------
    # One XLA program per fragment (not one giant fused program): compile
    # sizes stay bounded — mirroring the reference's per-stage tasks —
    # and every cut exchange becomes a consumer-side collective over the
    # producer fragment's materialized sharded page (the pull model).
    def execute(self, plan: PlanNode) -> Page:
        plan = self._resolve_subqueries(plan)
        plan = self._prepare(plan)
        from presto_tpu.plan.fragment import create_fragments
        frags = create_fragments(plan)
        by_id = {f.fragment_id: f for f in frags}
        self._frag_results = {}
        done = set()

        def run(fid: int):
            if fid in done:
                return
            for c in by_id[fid].remote_sources:
                run(c)
            self._frag_results[fid] = self._execute_tree(by_id[fid].root)
            done.add(fid)

        try:
            run(0)
            return self._frag_results[0]
        finally:
            self._frag_results = {}

    def _remote_input(self, node, scans):
        from presto_tpu.exec.executor import RemoteSpec
        page = self._frag_results[node.remote_fragment]
        idx = len(scans)
        scans.append(RemoteSpec(node.remote_fragment, page.capacity))
        return (lambda pages: pages[idx]), page.capacity

    # ---- hook overrides -------------------------------------------------
    # Every device-mesh hook delegates to the single-device base path
    # when ndev == 1: a 1-device "mesh" still executes FRAGMENT-WISE
    # (bounded program sizes — the compile-service-friendly mode bench
    # uses for join-heavy queries) but needs no shard_map or collectives,
    # which matters on backends that only lower a subset of them (the
    # axon TPU tunnel supports Sum all-reduce only).
    def _prepare(self, plan: PlanNode) -> PlanNode:
        return add_exchanges(plan, self.connector, self.session,
                             self.history)

    def _wrap(self, fn: Callable) -> Callable:
        if self.ndev == 1:
            return super()._wrap(fn)

        def wrapped(pages):
            def local_fn(*locals_):
                out, counters = fn(list(locals_))
                if counters.shape[0]:
                    counters = jax.lax.pmax(counters, AXIS)
                return out, counters
            return run_sharded(self.mesh, local_fn, *pages,
                               with_needed=True)
        return wrapped

    def _page_rows(self, page: Page) -> List[tuple]:
        if self.ndev == 1:
            return super()._page_rows(page)
        rows: List[tuple] = []
        for p in unstack_page(page):
            rows.extend(p.to_pylist())
        return rows

    def _scan_rows(self, node) -> int:
        if self.ndev == 1:
            return super()._scan_rows(node)
        t = self.connector.table(node.table)
        per = (t.num_rows + self.ndev - 1) // self.ndev
        return max(per, 1)

    def _fetch(self, s) -> Page:
        from presto_tpu.exec.executor import RemoteSpec
        if isinstance(s, RemoteSpec):
            return self._frag_results[s.fragment_id]
        if self.ndev == 1:
            return super()._fetch(s)
        pages = [self.connector.table(s.table, part=d,
                                      num_parts=self.ndev)
                 .page(columns=list(s.columns), capacity=s.capacity)
                 for d in range(self.ndev)]
        return stack_pages(pages)

    def _unique_ids(self, p: Page) -> jnp.ndarray:
        if self.ndev == 1:
            return super()._unique_ids(p)
        d = jax.lax.axis_index(AXIS).astype(jnp.int64)
        return d * p.capacity + jnp.arange(p.capacity, dtype=jnp.int64)

    def _finish_values(self, out: Page) -> Page:
        if self.ndev == 1:
            return super()._finish_values(out)
        # VALUES is a single stream: device 0 emits, the rest are empty
        # (the fragmenter marks it SINGLE-partitioned).
        on0 = jnp.where(jax.lax.axis_index(AXIS) == 0, out.num_rows, 0)
        return Page(out.columns, on0.astype(jnp.int32), out.names)

    def _finish_agg(self, node, out: Page) -> Page:
        if self.ndev == 1:
            return super()._finish_agg(node, out)
        if node.group_fields or node.step == Step.PARTIAL:
            return out
        # Global FINAL aggregation after a SINGLE exchange: every device
        # ran the (empty-input-tolerant) one-row aggregation, but only
        # device 0 received rows — only its row is the answer.
        on0 = jnp.where(jax.lax.axis_index(AXIS) == 0, out.num_rows, 0)
        return Page(out.columns, on0.astype(jnp.int32), out.names)

    def _lower_exchange(self, node, nid, src, cap, caps, watch, _needed):
        if self.ndev == 1:
            # exchanges between fragments are identity relabels on one
            # device; the fragment-wise materialization still happens
            return super()._lower_exchange(node, nid, src, cap, caps,
                                           watch, _needed)
        ndev = self.ndev
        if node.partitioning in (Partitioning.HASH, Partitioning.RANGE):
            from presto_tpu.parallel.shuffle import range_partition_ids
            if node.partitioning == Partitioning.HASH:
                pid_fn = lambda p: partition_ids(p, node.keys, ndev)  # noqa: E731
            else:
                pid_fn = lambda p: range_partition_ids(  # noqa: E731
                    p, node.sort_keys[0], ndev)
            out_cap = caps.get((nid, "cap")) or bucket_capacity(2 * cap)
            factor = self.session["exchange_chunk_factor"]
            chunk = caps.get((nid, "chunk")) \
                or max(factor * cap // ndev, 64)
            caps[(nid, "cap")] = out_cap
            caps[(nid, "chunk")] = chunk
            watch.append((nid, "cap"))
            watch.append((nid, "chunk"))

            def repart_fn(pages, node=node, out_cap=out_cap, chunk=chunk):
                p = src(pages)
                out, total, max_send = repartition_page(
                    p, pid_fn(p), ndev, out_cap, chunk)
                _needed.append(total)
                _needed.append(max_send)
                return Page(out.columns, out.num_rows, node.output_names)
            return repart_fn, out_cap

        if node.partitioning == Partitioning.BROADCAST:
            def bcast_fn(pages, node=node):
                p = src(pages)
                out = all_gather_page(p, ndev)
                return Page(out.columns, out.num_rows, node.output_names)
            return bcast_fn, ndev * cap

        if node.partitioning == Partitioning.SINGLE:
            def single_fn(pages, node=node):
                p = src(pages)
                out = all_gather_page(p, ndev)
                on0 = jnp.where(jax.lax.axis_index(AXIS) == 0,
                                out.num_rows, 0)
                return Page(out.columns, on0.astype(jnp.int32),
                            node.output_names)
            return single_fn, ndev * cap

        raise NotImplementedError(f"exchange {node.partitioning}")


class DistEngine:
    """Parse -> plan -> distributed execute over a mesh. Reference role:
    DistributedQueryRunner (presto-tests/.../DistributedQueryRunner.java:114)
    — N workers in one process, real exchanges between them."""

    def __init__(self, connector, mesh, session=None, history=None):
        from presto_tpu.sql.analyzer import Planner

        self.connector = connector
        self.planner = Planner(connector)
        self.executor = DistExecutor(connector, mesh, session=session,
                                     history=history)
        self._plans = {}

    def plan_sql(self, sql: str) -> PlanNode:
        if sql not in self._plans:
            from presto_tpu.sql.parser import parse_sql
            self._plans[sql] = self.planner.plan_query(parse_sql(sql))
        return self._plans[sql]

    def execute_sql(self, sql: str) -> List[tuple]:
        stacked = self.executor.execute(self.plan_sql(sql))
        rows = self.executor._page_rows(stacked)
        self._record_history()
        return rows

    def _record_history(self):
        """Feed observed per-node rows into the HBO store after execution
        (mirrors LocalEngine._record_history; requires collect_stats)."""
        ex = self.executor
        if ex.history is None or not getattr(ex, "last_node_rows", None):
            return
        from presto_tpu.plan.stats import canonical_key
        for nid, rows_n in ex.last_node_rows.items():
            entry = ex._node_map.get(nid)
            if entry is not None:
                ex.history.record(canonical_key(entry[0]), rows_n)
