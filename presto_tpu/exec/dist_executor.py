"""DistExecutor — the whole SQL plan as ONE shard_map program over a mesh.

Reference roles, fused into a single compiled unit:
  - AddExchanges/PlanFragmenter decide the distribution (plan/fragment.py)
  - each fragment's operator pipeline = the same local operator lowering
    the single-chip Executor uses (inherited)
  - every ExchangeNode lowers to an ICI collective: hash repartition ->
    lax.all_to_all, broadcast -> all_gather, single -> all_gather + only
    device 0 keeps rows (the coordinator-facing SINGLE distribution,
    reference SystemPartitioningHandle.SINGLE)

The reference runs fragments as separate tasks streaming pages over HTTP
(SqlStageExecution / ExchangeClient.java:71); on one multi-chip TPU worker
the fragments are instead fused into one XLA program so the compiler
overlaps compute with the collectives — the exchanges become program edges,
not network calls. Across hosts the same fragment tree maps onto the HTTP
pull protocol (protocol/, server/).

Overflow-retry: per-node counters (group counts, join duplicates, exchange
receive totals and per-peer send maxima) are pmax'd over the mesh and
fetched in one host sync; the generic retry loop re-lowers at bigger
buckets, exactly like the local executor.
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Page, bucket_capacity
from presto_tpu.exec.executor import Executor, ScanSpec
from presto_tpu.obs.metrics import counter as _metric_counter
from presto_tpu.parallel.mesh import AXIS, run_sharded, stack_pages, \
    unstack_page
from presto_tpu.parallel.shuffle import ExchangeLayout, all_gather_page, \
    partition_ids, repartition_page
from presto_tpu.plan.fragment import add_exchanges
from presto_tpu.plan.nodes import Partitioning, PlanNode, Step

#: ICI exchange observability (the mesh analog of the HTTP "Exchange:"
#: counters): static wire-buffer bytes and collective launches per
#: exchange kind, exchange-driven overflow re-lowers, and distinct
#: fragment programs compiled. All feed /v1/metrics and the "Mesh:"
#: line in EXPLAIN ANALYZE.
_M_MESH_BYTES = _metric_counter(
    "presto_tpu_mesh_exchange_bytes_total",
    "Static wire-buffer bytes moved by packed ICI collectives",
    ("kind",))
_M_MESH_LAUNCHES = _metric_counter(
    "presto_tpu_mesh_collective_launches_total",
    "Packed ICI collectives launched (one per distinct lane dtype)",
    ("kind",))
_M_MESH_OVERFLOW = _metric_counter(
    "presto_tpu_mesh_exchange_overflow_retries_total",
    "Exchange re-lowers forced by per-peer chunk or receive-capacity "
    "overflow")
_M_MESH_COMPILES = _metric_counter(
    "presto_tpu_mesh_fragment_compiles_total",
    "Distinct fragment programs compiled by the mesh executor")


class DistExecutor(Executor):
    """Executes plans distributed over an N-device mesh (CPU mesh in
    tests, TPU ICI in production)."""

    # the whole distributed plan lowers into ONE shard_map program
    # (exchanges are ICI collectives inside it) — island splitting does
    # not apply here
    _force_fused = True

    def __init__(self, connector, mesh, session=None, history=None):
        super().__init__(connector, session=session)
        self.mesh = mesh
        self.ndev = int(mesh.devices.size)
        # HBO store consulted by add_exchanges at _prepare time
        self.history = history
        # id(exchange node) -> ExchangeLayout, recorded at trace time by
        # the packed collectives; _trace_credit marks exchanges whose
        # first dispatch still owes its metric increment to the trace.
        self._exchange_layout = {}
        self._trace_credit = set()
        # per-query mesh counters behind the EXPLAIN ANALYZE "Mesh:" line
        self.last_mesh_stats = None

    # ---- fragment-by-fragment execution ---------------------------------
    # One XLA program per fragment (not one giant fused program): compile
    # sizes stay bounded — mirroring the reference's per-stage tasks —
    # and every cut exchange becomes a consumer-side collective over the
    # producer fragment's materialized sharded page (the pull model).
    def execute(self, plan: PlanNode) -> Page:
        import time
        budget = self.session["query_max_execution_time"]
        self._deadline = (time.time() + budget) if budget else None
        self.last_node_rows = {}
        self._node_map = {}
        plan = self._resolve_subqueries(plan)
        plan = self._prepare(plan)
        return self._execute_prepared(plan)

    def _execute_prepared(self, plan: PlanNode) -> Page:
        from presto_tpu.plan.fragment import create_fragments
        frags = create_fragments(plan)
        by_id = {f.fragment_id: f for f in frags}
        self.last_mesh_stats = {
            "ndev": self.ndev, "fragments": len(frags),
            "collectives": 0, "wire_bytes": 0,
            "overflow_retries": 0, "fragment_compiles": 0}
        # donation analog for the repartition scratch: a fragment result
        # is freed as soon as its last consumer converged (the retry
        # loop re-reads inputs, so true jit donation is unsafe — but a
        # converged consumer never re-reads its upstream)
        refs = {}
        for f in frags:
            for c in set(f.remote_sources):
                refs[c] = refs.get(c, 0) + 1
        self._frag_results = {}
        done = set()

        def run(fid: int):
            if fid in done:
                return
            for c in by_id[fid].remote_sources:
                run(c)
            # stats ids must not collide across fragments: give each
            # fragment its own id space (the island-mode mechanism)
            self._stats_base = (fid + 1) << 20
            self._frag_results[fid] = self._execute_tree(by_id[fid].root)
            done.add(fid)
            for c in set(by_id[fid].remote_sources):
                refs[c] -= 1
                if refs[c] == 0 and c != 0:
                    self._free_page(self._frag_results.pop(c))

        try:
            run(0)
            return self._frag_results[0]
        finally:
            self._frag_results = {}
            self._stats_base = 0

    @staticmethod
    def _free_page(page: Page) -> None:
        """Release a dead fragment result's device buffers eagerly
        instead of waiting for GC (jit outputs — never aliased with
        connector-cached scan pages, so deletion cannot corrupt them)."""
        for leaf in jax.tree_util.tree_leaves(page):
            delete = getattr(leaf, "delete", None)
            if delete is not None:
                try:
                    delete()
                except Exception:   # noqa: BLE001 — freeing is advisory
                    pass

    def _remote_input(self, node, scans):
        from presto_tpu.exec.executor import RemoteSpec
        page = self._frag_results[node.remote_fragment]
        idx = len(scans)
        scans.append(RemoteSpec(node.remote_fragment, page.capacity))
        return (lambda pages: pages[idx]), page.capacity

    # ---- hook overrides -------------------------------------------------
    # Every device-mesh hook delegates to the single-device base path
    # when ndev == 1: a 1-device "mesh" still executes FRAGMENT-WISE
    # (bounded program sizes — the compile-service-friendly mode bench
    # uses for join-heavy queries) but needs no shard_map or collectives,
    # which matters on backends that only lower a subset of them (the
    # axon TPU tunnel supports Sum all-reduce only).
    def _prepare(self, plan: PlanNode) -> PlanNode:
        return add_exchanges(plan, self.connector, self.session,
                             self.history)

    def _wrap(self, fn: Callable) -> Callable:
        if self.ndev == 1:
            return super()._wrap(fn)

        def wrapped(pages):
            def local_fn(*locals_):
                out, counters = fn(list(locals_))
                if counters.shape[0]:
                    counters = jax.lax.pmax(counters, AXIS)
                return out, counters
            return run_sharded(self.mesh, local_fn, *pages,
                               with_needed=True)
        return wrapped

    def _page_rows(self, page: Page) -> List[tuple]:
        if self.ndev == 1:
            return super()._page_rows(page)
        rows: List[tuple] = []
        for p in unstack_page(page):
            rows.extend(p.to_pylist())
        return rows

    def _scan_rows(self, node) -> int:
        if self.ndev == 1:
            return super()._scan_rows(node)
        t = self.connector.table(node.table)
        per = (t.num_rows + self.ndev - 1) // self.ndev
        return max(per, 1)

    def _fetch(self, s) -> Page:
        from presto_tpu.exec.executor import RemoteSpec
        if isinstance(s, RemoteSpec):
            return self._frag_results[s.fragment_id]
        if self.ndev == 1:
            return super()._fetch(s)
        pages = [self.connector.table(s.table, part=d,
                                      num_parts=self.ndev)
                 .page(columns=list(s.columns), capacity=s.capacity)
                 for d in range(self.ndev)]
        return stack_pages(pages)

    def _unique_ids(self, p: Page) -> jnp.ndarray:
        if self.ndev == 1:
            return super()._unique_ids(p)
        d = jax.lax.axis_index(AXIS).astype(jnp.int64)
        return d * p.capacity + jnp.arange(p.capacity, dtype=jnp.int64)

    def _finish_values(self, out: Page) -> Page:
        if self.ndev == 1:
            return super()._finish_values(out)
        # VALUES is a single stream: device 0 emits, the rest are empty
        # (the fragmenter marks it SINGLE-partitioned).
        on0 = jnp.where(jax.lax.axis_index(AXIS) == 0, out.num_rows, 0)
        return Page(out.columns, on0.astype(jnp.int32), out.names)

    def _finish_agg(self, node, out: Page) -> Page:
        if self.ndev == 1:
            return super()._finish_agg(node, out)
        if node.group_fields or node.step == Step.PARTIAL:
            return out
        # Global FINAL aggregation after a SINGLE exchange: every device
        # ran the (empty-input-tolerant) one-row aggregation, but only
        # device 0 received rows — only its row is the answer.
        on0 = jnp.where(jax.lax.axis_index(AXIS) == 0, out.num_rows, 0)
        return Page(out.columns, on0.astype(jnp.int32), out.names)

    # ---- mesh observability --------------------------------------------
    def _mesh_sink(self, node, kind: str):
        """Per-dispatch exchange accounting. The packed layout (launch
        count, wire bytes) is only known at trace time; once recorded it
        is charged host-side on every later dispatch, and the first
        dispatch's charge is deferred to its own trace (`_trace_credit`)
        so retraces after capacity growth never double-count."""
        key = id(node)

        def sink(layout, key=key, kind=kind):
            self._exchange_layout[key] = ExchangeLayout(
                kind, layout.collectives, layout.wire_bytes)
            if key in self._trace_credit:
                self._trace_credit.discard(key)
                self._account_exchange(key)
        if key in self._exchange_layout:
            self._account_exchange(key)
        else:
            self._trace_credit.add(key)
        return sink

    def _account_exchange(self, key) -> None:
        lay = self._exchange_layout[key]
        _M_MESH_LAUNCHES.inc(lay.collectives, kind=lay.kind)
        _M_MESH_BYTES.inc(lay.wire_bytes, kind=lay.kind)
        st = self.last_mesh_stats
        if st is not None:
            st["collectives"] += lay.collectives
            st["wire_bytes"] += lay.wire_bytes

    def _grow_caps(self, pending, needed) -> bool:
        if self.ndev > 1:
            caps = pending["caps"]
            if any(isinstance(k, tuple) and int(n) > caps[k]
                   for k, n in zip(pending["watch"], needed)):
                _M_MESH_OVERFLOW.inc()
                if self.last_mesh_stats is not None:
                    self.last_mesh_stats["overflow_retries"] += 1
        return super()._grow_caps(pending, needed)

    def _note_compile(self, plan: PlanNode) -> None:
        _M_MESH_COMPILES.inc()
        if self.last_mesh_stats is not None:
            self.last_mesh_stats["fragment_compiles"] += 1

    def _lower_exchange(self, node, nid, src, cap, caps, watch, _needed):
        if self.ndev == 1:
            # exchanges between fragments are identity relabels on one
            # device; the fragment-wise materialization still happens
            return super()._lower_exchange(node, nid, src, cap, caps,
                                           watch, _needed)
        ndev = self.ndev
        if node.partitioning in (Partitioning.HASH, Partitioning.RANGE):
            from presto_tpu.parallel.shuffle import range_partition_ids
            if node.partitioning == Partitioning.HASH:
                pid_fn = lambda p: partition_ids(p, node.keys, ndev)  # noqa: E731
                kind = "hash"
            else:
                pid_fn = lambda p: range_partition_ids(  # noqa: E731
                    p, node.sort_keys[0], ndev)
                kind = "range"
            out_cap = caps.get((nid, "cap")) or bucket_capacity(2 * cap)
            factor = self.session["exchange_chunk_factor"]
            chunk = caps.get((nid, "chunk")) \
                or max(factor * cap // ndev, 64)
            caps[(nid, "cap")] = out_cap
            caps[(nid, "chunk")] = chunk
            watch.append((nid, "cap"))
            watch.append((nid, "chunk"))
            sink = self._mesh_sink(node, kind)

            def repart_fn(pages, node=node, out_cap=out_cap, chunk=chunk,
                          sink=sink):
                p = src(pages)
                out, total, max_send = repartition_page(
                    p, pid_fn(p), ndev, out_cap, chunk,
                    layout_sink=sink)
                _needed.append(total)
                _needed.append(max_send)
                return Page(out.columns, out.num_rows, node.output_names)
            return repart_fn, out_cap

        if node.partitioning == Partitioning.BROADCAST:
            sink = self._mesh_sink(node, "broadcast")

            def bcast_fn(pages, node=node, sink=sink):
                p = src(pages)
                out = all_gather_page(p, ndev, layout_sink=sink)
                return Page(out.columns, out.num_rows, node.output_names)
            return bcast_fn, ndev * cap

        if node.partitioning == Partitioning.SINGLE:
            sink = self._mesh_sink(node, "single")

            def single_fn(pages, node=node, sink=sink):
                p = src(pages)
                out = all_gather_page(p, ndev, layout_sink=sink)
                on0 = jnp.where(jax.lax.axis_index(AXIS) == 0,
                                out.num_rows, 0)
                return Page(out.columns, on0.astype(jnp.int32),
                            node.output_names)
            return single_fn, ndev * cap

        raise NotImplementedError(f"exchange {node.partitioning}")


class DistSplitExecutor(DistExecutor):
    """Mesh executor with lifespan splits: the batched driver assigns one
    (part, num_parts) split of the driving table per lifespan; each mesh
    device then reads sub-split `part*ndev + d` of `num_parts*ndev`, so a
    lifespan's working set stays bounded PER DEVICE. This is the
    composition of exec/lifespan.BatchedRunner's driving-scan streaming
    with the distributed exchange lowering (grouped execution over
    lifespans, run on the mesh)."""

    def __init__(self, connector, mesh, session=None, history=None):
        super().__init__(connector, mesh, session=session,
                         history=history)
        self.splits = {}

    def set_splits(self, by_table) -> None:
        self.splits = by_table

    def _split_tables(self, name):
        parts = self.splits.get(name)
        if parts is None:
            return None
        # each assigned split (b, n) subdivides across the mesh: device
        # d reads part b*ndev+d of n*ndev. A task holding SEVERAL
        # lifespan splits (the fused cluster-mesh plan concentrates a
        # whole stage's splits on one task) gives each device one
        # subpart per split, merged at fetch time.
        out = []
        for d in range(self.ndev):
            ts = [self.connector.table(name, part=b * self.ndev + d,
                                       num_parts=n * self.ndev)
                  for b, n in parts]
            out.append(ts[0] if len(ts) == 1 else _MultiPartTable(ts))
        return out

    def _scan_rows(self, node) -> int:
        ts = self._split_tables(node.table)
        if ts is None:
            return super()._scan_rows(node)
        return max(max(t.num_rows for t in ts), 1)

    def _fetch(self, s) -> Page:
        from presto_tpu.exec.executor import RemoteSpec
        ts = None
        if not isinstance(s, RemoteSpec) and hasattr(s, "table"):
            ts = self._split_tables(s.table)
        if ts is None:
            return super()._fetch(s)
        pages = [t.page(columns=list(s.columns), capacity=s.capacity)
                 for t in ts]
        return pages[0] if self.ndev == 1 else stack_pages(pages)


class _MultiPartTable:
    """Several connector part-tables presented as one: a device's view
    of a task that holds multiple lifespan splits of one table."""

    def __init__(self, tables):
        self.tables = tables
        self.num_rows = sum(t.num_rows for t in tables)

    def page(self, columns=None, capacity=None):
        from presto_tpu.data.column import concat_pages_host
        pages = [t.page(columns=columns) for t in self.tables]
        return concat_pages_host(pages, capacity=capacity)


class DistEngine:
    """Parse -> plan -> distributed execute over a mesh. Reference role:
    DistributedQueryRunner (presto-tests/.../DistributedQueryRunner.java:114)
    — N workers in one process, real exchanges between them."""

    def __init__(self, connector, mesh, session=None, history=None):
        from presto_tpu.sql.analyzer import Planner

        self.connector = connector
        self.planner = Planner(connector)
        self.executor = DistExecutor(connector, mesh, session=session,
                                     history=history)
        self._plans = {}

    def plan_sql(self, sql: str) -> PlanNode:
        if sql not in self._plans:
            from presto_tpu.sql.parser import parse_sql
            self._plans[sql] = self.planner.plan_query(parse_sql(sql))
        return self._plans[sql]

    def explain_sql(self, sql: str) -> str:
        from presto_tpu.plan.nodes import explain
        return explain(self.plan_sql(sql))

    def explain_analyze_sql(self, sql: str) -> str:
        from presto_tpu.exec.stats import explain_analyze
        return explain_analyze(self, sql)

    @property
    def session(self):
        return self.executor.session

    def execute_sql(self, sql: str) -> List[tuple]:
        head = sql.lstrip().split(None, 1)[0].lower() if sql.strip() \
            else ""
        if head == "explain":
            # EXPLAIN [ANALYZE] over the distributed plan — the mesh
            # analog of LocalEngine's dispatch
            rest = sql.lstrip()[len("explain"):].lstrip()
            if rest.lower().startswith("analyze"):
                text = self.explain_analyze_sql(
                    rest[len("analyze"):].lstrip())
            else:
                text = self.explain_sql(rest)
            return [(line,) for line in text.splitlines()]
        stacked = self.executor.execute(self.plan_sql(sql))
        rows = self.executor._page_rows(stacked)
        self._record_history()
        return rows

    def _record_history(self):
        """Feed observed per-node rows into the HBO store after execution
        (mirrors LocalEngine._record_history; requires collect_stats)."""
        ex = self.executor
        if ex.history is None or not getattr(ex, "last_node_rows", None):
            return
        from presto_tpu.plan.stats import canonical_key
        for nid, rows_n in ex.last_node_rows.items():
            entry = ex._node_map.get(nid)
            if entry is not None:
                ex.history.record(canonical_key(entry[0]), rows_n)
