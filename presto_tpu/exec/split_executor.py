"""SplitExecutor — scans read ASSIGNED splits (row ranges), not whole
tables: the worker-side contract (splits arrive in
TaskUpdateRequest.sources; reference ScheduledSplit / ConnectorSplit) and
the building block of lifespan-batched execution (exec/lifespan.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from presto_tpu.data.column import Column, Page
from presto_tpu.exec.executor import Executor, ScanSpec


class SplitExecutor(Executor):
    def __init__(self, connector, session=None):
        super().__init__(connector, session=session)
        self.splits: Dict[str, List[Tuple[int, int]]] = {}

    def set_splits(self, by_table: Dict[str, List[Tuple[int, int]]]):
        self.splits = by_table

    def _scan_rows(self, node) -> int:
        parts = self.splits.get(node.table)
        if parts is None:
            return self.connector.table(node.table).num_rows
        return max(1, sum(
            self.connector.table(node.table, part=p, num_parts=n).num_rows
            for p, n in parts))

    def _fetch(self, s: ScanSpec) -> Page:
        parts = self.splits.get(s.table)
        if parts is None:
            return super()._fetch(s)
        tables = [self.connector.table(s.table, part=p, num_parts=n)
                  for p, n in parts]
        n_rows = sum(t.num_rows for t in tables)
        cols = []
        for c in s.columns:
            t0 = tables[0]
            arr = np.concatenate([t.arrays[c][:t.num_rows] for t in tables])
            cols.append(Column.from_numpy(
                arr, t0.types[c], dictionary=t0.dicts.get(c),
                capacity=s.capacity))
        return Page.from_columns(cols, n_rows, s.columns)
