"""SplitExecutor — scans read ASSIGNED splits (row ranges), not whole
tables: the worker-side contract (splits arrive in
TaskUpdateRequest.sources; reference ScheduledSplit / ConnectorSplit) and
the building block of lifespan-batched execution (exec/lifespan.py)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np

from presto_tpu.data.column import Column, Page
from presto_tpu.exec.executor import Executor, ScanSpec


@dataclasses.dataclass
class RemotePageSpec:
    """Scan-slot placeholder for an input pulled from upstream tasks
    (bound by node id; reference: RemoteSourceNode -> ExchangeOperator)."""
    node_id: str
    capacity: int


class SplitExecutor(Executor):
    def __init__(self, connector, session=None):
        super().__init__(connector, session=session)
        self.splits: Dict[str, List[Tuple[int, int]]] = {}
        # table -> pre-materialized host table (one streaming scan run);
        # consulted BEFORE split (part, numParts) resolution so lifespan
        # streaming can feed bounded page runs through an unchanged plan.
        self.split_tables: Dict[str, object] = {}
        # node_id -> concatenated engine Page pulled over the HTTP
        # exchange before execution (data/column.concat_pages_host).
        self.remote_pages: Dict[str, "Page"] = {}

    def set_splits(self, by_table: Dict[str, List[Tuple[int, int]]]):
        self.splits = by_table

    def set_split_tables(self, by_table: Dict[str, object]):
        """Bind host tables (streaming scan runs) directly to leaf
        scans; pass {} to fall back to split-range resolution."""
        self.split_tables = by_table

    def set_remote_pages(self, by_node: Dict[str, Page]):
        self.remote_pages = by_node

    def _remote_source(self, node, scans):
        page = self.remote_pages.get(node.node_id)
        if page is None:
            raise RuntimeError(
                f"no remote pages bound for plan node {node.node_id!r}")
        idx = len(scans)
        scans.append(RemotePageSpec(node.node_id, page.capacity))
        return (lambda pages: pages[idx]), page.capacity

    def _scan_rows(self, node) -> int:
        t = self.split_tables.get(node.table)
        if t is not None:
            return max(1, int(t.num_rows))
        parts = self.splits.get(node.table)
        if parts is None:
            return self.connector.table(node.table).num_rows
        return max(1, sum(
            self.connector.table(node.table, part=p, num_parts=n).num_rows
            for p, n in parts))

    def _fetch(self, s) -> Page:
        if isinstance(s, RemotePageSpec):
            return self.remote_pages[s.node_id]
        if not hasattr(s, "table"):       # island PageInputSpec
            return super()._fetch(s)
        t = self.split_tables.get(s.table)
        if t is not None:
            return t.page(columns=list(s.columns), capacity=s.capacity)
        parts = self.splits.get(s.table)
        if parts is None:
            return super()._fetch(s)
        tables = [self.connector.table(s.table, part=p, num_parts=n)
                  for p, n in parts]
        n_rows = sum(t.num_rows for t in tables)
        cols = []
        for c in s.columns:
            t0 = tables[0]
            if t0.types[c].name in ("array", "map", "row"):
                from presto_tpu.data.column import NestedColumn
                vals = [v for t in tables
                        for v in t.arrays[c][:t.num_rows]]
                cols.append(NestedColumn.from_pylist(
                    vals, t0.types[c], s.capacity))
                continue
            if t0.types[c].is_string and len(tables) > 1:
                # materialize FIRST: lazy tables (parquet) only build
                # their dictionary on column access, so comparing dicts
                # before the load sees None==None and would skip the
                # remap
                for t in tables:
                    _ = t.arrays[c]
            if t0.types[c].is_string and len(tables) > 1 and any(
                    t.dicts.get(c) is not tables[0].dicts.get(c)
                    for t in tables[1:]):
                # splits with PER-SPLIT dictionaries (parquet row-group
                # units decode their own dictionary pages): remap all
                # code spaces into one union dictionary
                from presto_tpu.data.column import merge_string_dicts
                union, remaps = merge_string_dicts(
                    [t.dicts.get(c) for t in tables])
                parts = []
                for t, remap in zip(tables, remaps):
                    codes = np.asarray(t.arrays[c][:t.num_rows])
                    parts.append(remap[codes] if len(remap) else codes)
                arr = np.concatenate(parts)
                masks = [t.null_mask(c) for t in tables]
                nulls = (np.concatenate(
                    [m if m is not None else np.zeros(t.num_rows, bool)
                     for m, t in zip(masks, tables)])
                    if any(m is not None for m in masks) else None)
                cols.append(Column.from_numpy(
                    arr, t0.types[c], nulls=nulls, dictionary=union,
                    capacity=s.capacity))
                continue
            arr = np.concatenate([t.arrays[c][:t.num_rows] for t in tables])
            masks = [t.null_mask(c) for t in tables]
            nulls = (np.concatenate(
                [m if m is not None else np.zeros(t.num_rows, bool)
                 for m, t in zip(masks, tables)])
                if any(m is not None for m in masks) else None)
            if getattr(t0.types[c], "uses_int128", False):
                # DECIMAL(p>18) at rest: python-int unscaled values ->
                # limb lanes (see HostTable.page)
                from presto_tpu.data.column import Decimal128Column
                cols.append(Decimal128Column.from_unscaled_ints(
                    list(arr), t0.types[c], nulls=nulls,
                    capacity=s.capacity))
                continue
            cols.append(Column.from_numpy(
                arr, t0.types[c], nulls=nulls, dictionary=t0.dicts.get(c),
                capacity=s.capacity))
        return Page.from_columns(cols, n_rows, s.columns)
