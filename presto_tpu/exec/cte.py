"""CTE materialization — WITH subqueries referenced more than once
execute ONCE into a temp table instead of being inlined per reference.

Reference: sql/planner/optimizations/PhysicalCteOptimizer.java:126 (CTEs
written to temp tables and re-scanned, sequenced by
CTEMaterializationTracker). Here the temp store is the writable memory
connector (connectors/memory.py) layered over the engine's catalog; the
rewrite runs on the AST before planning:

  1. count TableRef references to each CTE across the main query and
     every nested subquery that doesn't shadow the name;
  2. for each CTE referenced >= 2 times, execute its query (CTEs may
     reference earlier CTEs — processed in declaration order) and write
     the rows to a unique temp table;
  3. rewrite references to the temp name and drop the CTE binding.

Single-reference CTEs keep the inlining path (no materialization cost),
exactly like the reference's heuristic default."""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Tuple

from presto_tpu.sql import ast

_ids = itertools.count()


def _count_refs(node, name: str) -> int:
    """TableRef occurrences of `name`, honoring shadowing by nested WITH."""
    if isinstance(node, ast.TableRef):
        return int(node.name == name)
    if isinstance(node, ast.Select):
        if any(n == name for n, _q in node.ctes):
            # Shadowed — but WITH bindings are sequential: definition
            # queries up to AND INCLUDING the shadowing declaration still
            # see the OUTER name (a non-recursive CTE cannot reference
            # itself). Only later definitions and the body see the inner
            # binding.
            n = 0
            for cn, cq in node.ctes:
                n += _count_refs(cq, name)
                if cn == name:
                    break
            return n
        n = 0
        for _cn, cq in node.ctes:
            n += _count_refs(cq, name)
        for f in dataclasses.fields(node):
            if f.name == "ctes":
                continue
            n += _count_refs(getattr(node, f.name), name)
        return n
    if dataclasses.is_dataclass(node):
        return sum(_count_refs(getattr(node, f.name), name)
                   for f in dataclasses.fields(node))
    if isinstance(node, tuple):
        return sum(_count_refs(x, name) for x in node)
    return 0


def _rename_refs(node, old: str, new: str):
    if isinstance(node, ast.TableRef):
        return (dataclasses.replace(node, name=new)
                if node.name == old else node)
    if isinstance(node, ast.Select) and \
            any(n == old for n, _q in node.ctes):
        # Shadowed: rename only inside definition queries up to and
        # including the shadowing declaration (sequential-WITH scoping,
        # mirroring _count_refs); the body keeps the inner binding.
        new_ctes, hit = [], False
        for cn, cq in node.ctes:
            if not hit:
                cq = _rename_refs(cq, old, new)
            new_ctes.append((cn, cq))
            if cn == old:
                hit = True
        return dataclasses.replace(node, ctes=tuple(new_ctes))
    if dataclasses.is_dataclass(node):
        return dataclasses.replace(node, **{
            f.name: _rename_refs(getattr(node, f.name), old, new)
            for f in dataclasses.fields(node)})
    if isinstance(node, tuple):
        return tuple(_rename_refs(x, old, new) for x in node)
    return node


def materialize_ctes(q: ast.Select, run_select, temp_store
                     ) -> Tuple[ast.Select, list]:
    """Rewrite `q`, executing multiply-referenced CTEs into temp tables.

    run_select(ast.Select) -> (rows, names, types); temp_store is a
    writable connector (create/append_rows/drop). Returns the rewritten
    query and the temp table names created (caller drops them)."""
    if not q.ctes:
        return q, []
    temps = []
    remaining = []
    bindings: Dict[str, str] = {}

    def rebind(sub_q: ast.Select) -> ast.Select:
        for old, new in bindings.items():
            sub_q = _rename_refs(sub_q, old, new)
        return sub_q

    try:
        for name, cq in q.ctes:
            body = dataclasses.replace(q, ctes=())
            later = [c for c in q.ctes if c[0] != name]
            refs = _count_refs(body, name) + sum(
                _count_refs(c[1], name) for c in later)
            if refs < 2:
                remaining.append((name, rebind(cq)))
                continue
            # Prepend the outer still-inlined bindings to the body's OWN
            # nested WITH (inner declarations win on name collision) —
            # overwriting would drop the body's nested CTEs entirely.
            bound = rebind(cq)
            inner_names = {n for n, _q in bound.ctes}
            merged = tuple(c for c in remaining
                           if c[0] not in inner_names) + tuple(bound.ctes)
            rows, names, types = run_select(
                dataclasses.replace(bound, ctes=merged))
            tmp = f"__cte_{next(_ids)}_{name}"
            temp_store.create(tmp, list(zip(names, types)))
            temp_store.append_rows(tmp, rows)
            temps.append(tmp)
            bindings[name] = tmp
    except BaseException:
        # a later CTE failed: don't leak the temps created so far
        for t in temps:
            temp_store.drop(t, if_exists=True)
        raise

    out = dataclasses.replace(q, ctes=())
    for old, new in bindings.items():
        out = _rename_refs(out, old, new)
    return dataclasses.replace(out, ctes=tuple(
        (n, c) for n, c in remaining)), temps
