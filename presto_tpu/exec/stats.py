"""EXPLAIN ANALYZE — the executed plan annotated with measured stats.

Reference roles: the QueryStats -> OperatorStats tree
(presto-main-base/.../operator/OperatorStats.java) rendered by
ExplainAnalyzeOperator. TPU reinterpretation: operators fuse into one XLA
program per fragment, so per-operator WALL TIME does not exist — what is
real and reported is per-node output cardinality (traced counters riding
the overflow-counter transfer), static capacity/memory footprint per
node, and per-execution wall/compile time. Fused nodes (filter/project
chains absorbed into aggregations) are marked as such.
"""

from __future__ import annotations

import time
from typing import Dict

from presto_tpu.exec.executor import _row_bytes
from presto_tpu.plan import nodes as P


def _detail(node) -> str:
    if isinstance(node, P.TableScanNode):
        return f" {node.table}{list(node.columns)}"
    if isinstance(node, P.FilterNode):
        return f" {node.predicate}"
    if isinstance(node, P.AggregationNode):
        return (f" keys={list(node.group_fields)} "
                f"aggs={[a.kind for a in node.aggs]} "
                f"step={node.step.value}")
    if isinstance(node, P.JoinNode):
        return (f" {node.join_type.value} "
                f"probe{list(node.probe_keys)}=build{list(node.build_keys)}")
    if isinstance(node, P.WindowNode):
        return (f" partition={list(node.partition_fields)} "
                f"fns={[s.kind for s in node.specs]}")
    if isinstance(node, (P.TopNNode, P.LimitNode)):
        return f" n={node.count}"
    if isinstance(node, P.ExchangeNode):
        return f" {node.partitioning.value} keys={list(node.keys)}"
    return ""


def render_analyzed(plan, node_map: Dict[int, tuple],
                    node_rows: Dict[int, int], wall_s: float,
                    memory_bytes: int, alias: Dict[int, int] = None,
                    island_profile=None, mesh_stats=None,
                    est=None) -> str:
    """Annotate the plan tree with executed row counts + footprints.
    `alias` maps island-copy node identities back to the user-facing
    plan's nodes (island mode rebuilds subtrees with
    dataclasses.replace); `island_profile` carries per-island wall
    times — the per-operator profile fused execution cannot have.
    `est` (node -> estimated rows) puts the planner's estimate next to
    each observed count so HBO drift is visible in one rendering."""
    alias = alias or {}
    by_identity = {}
    for nid, (n, cap) in node_map.items():
        by_identity[alias.get(id(n), id(n))] = (nid, cap)
    lines = []

    def est_of(node) -> str:
        if est is None:
            return ""
        try:
            return f"est_rows={int(est(node))} "
        except Exception:       # noqa: BLE001 — estimate must never fail EXPLAIN
            return ""

    def walk(node, depth):
        pad = "  " * depth
        name = type(node).__name__.replace("Node", "")
        info = by_identity.get(id(node))
        if info is None:
            annot = f"(fused into parent) {est_of(node)}".rstrip()
        else:
            nid, cap = info
            rows = node_rows.get(nid)
            bytes_ = cap * _row_bytes(node.output_types)
            annot = (f"rows={rows if rows is not None else '?'} "
                     f"{est_of(node)}"
                     f"cap={cap} ~{bytes_ // 1024} KiB")
        lines.append(f"{pad}{name}{_detail(node)}  [{annot}]")
        for c in node.children():
            if c is not None:
                walk(c, depth + 1)

    walk(plan, 0)
    if island_profile:
        lines.append("-- island profile (one XLA program per heavy "
                     "operator):")
        for i, p in enumerate(island_profile):
            lines.append(
                f"   island {i}: {p['root']}  "
                f"{p['seconds'] * 1000:.1f} ms  rows={p['rows']}  "
                f"~{p['memory_bytes'] // (1 << 20)} MiB")
    if mesh_stats:
        # ICI-mesh analog of the cluster renderer's "Exchange:" line
        # (server/cluster.py): what the device exchanges actually cost.
        lines.append(
            f"Mesh: ndev={mesh_stats['ndev']} "
            f"fragments={mesh_stats['fragments']} "
            f"collectives={mesh_stats['collectives']} "
            f"wire={mesh_stats['wire_bytes'] // 1024} KiB "
            f"overflow_retries={mesh_stats['overflow_retries']} "
            f"fragment_compiles={mesh_stats['fragment_compiles']}")
    lines.append(f"-- wall {wall_s * 1000:.1f} ms, "
                 f"plan footprint ~{memory_bytes // (1 << 20)} MiB")
    return "\n".join(lines)


def explain_analyze(engine, sql: str) -> str:
    """Execute `sql` with stats collection and render the analyzed plan
    (reference: EXPLAIN ANALYZE via ExplainAnalyzeOperator)."""
    ex = engine.executor
    plan = ex._resolve_subqueries(engine.plan_sql(sql))
    plan = ex._prepare(plan)
    old = ex.session.values["collect_stats"]
    ex.session.values["collect_stats"] = True
    # collect_stats changes the traced program: bypass stale compiles.
    compiled, ex._compiled = ex._compiled, {}
    try:
        t0 = time.perf_counter()
        ex.last_node_rows = {}
        ex._node_map = {}
        # the hook the distributed executor fragments through, so the
        # analyzed run measures the real (fragment-wise, mesh) shape
        ex._execute_prepared(plan)
        wall = time.perf_counter() - t0
        from presto_tpu.plan.stats import estimate_rows
        history = getattr(engine, "history", None)
        return render_analyzed(
            plan, ex._node_map, ex.last_node_rows, wall,
            ex.last_memory_estimate,
            alias=getattr(ex, "_island_alias", None),
            island_profile=getattr(ex, "last_island_profile", None),
            mesh_stats=getattr(ex, "last_mesh_stats", None),
            est=lambda n: estimate_rows(n, engine.connector, history))
    finally:
        ex.session.values["collect_stats"] = old
        ex._compiled = compiled
