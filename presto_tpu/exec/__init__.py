from presto_tpu.exec.executor import Executor
from presto_tpu.exec.engine import LocalEngine

__all__ = ["Executor", "LocalEngine"]
