"""Memory-management hierarchy: pools, revocation, cluster-level kill.

Reference roles:
- `MemoryPool.java` (presto-main-base/.../memory/): per-node pool with
  per-query reservations and a hard budget;
- `MemoryRevokingScheduler.java:60`: when pool usage crosses a
  threshold, ask the largest revocable operators to SPILL before the
  pool is exhausted;
- `ClusterMemoryManager.java:106` (presto-main): cluster-wide view;
  on pool exhaustion, kill the single biggest query
  (`resource-overcommit` / LowMemoryKiller) with EXCEEDED_MEMORY_LIMIT.

TPU-native shape: reservations are page/program byte estimates from the
executor's static lowering (capacity x dtype — exact for padded device
arrays, known BEFORE execution because shapes are static; the JVM has
to sample at runtime, we can admission-check at compile time). The
revocation hook drives the existing lifespan spill machinery
(exec/lifespan.py `spill_path` partial revocation).
"""

import threading
from typing import Callable, Dict, List, Optional

from presto_tpu.obs.metrics import (counter as _counter,
                                    gauge as _gauge)

#: pool pressure as a fraction so one alert threshold works for every
#: budget size; set on reserve/free, scraped into telemetry history
_M_POOL_FRACTION = _gauge(
    "presto_tpu_memory_pool_reserved_fraction",
    "Reserved bytes over budget for the node memory pool (1.0 = "
    "exhausted; crossing revoke_threshold starts spill-before-fail)")

_M_REVOCATIONS = _counter(
    "presto_tpu_memory_revocations_total",
    "Revoke-hook firings that actually freed bytes (spill-before-fail "
    "under memory pressure)")
_M_REVOKED = _counter(
    "presto_tpu_memory_revoked_bytes_total",
    "Bytes freed by revocation hooks (spilled out of pool-accounted "
    "memory)")
_M_KILLED = _counter(
    "presto_tpu_memory_killed_queries_total",
    "Queries killed by the cluster low-memory killer "
    "(EXCEEDED_MEMORY_LIMIT class)")


class ExceededMemoryLimitError(RuntimeError):
    """PrestoException(EXCEEDED_GLOBAL_MEMORY_LIMIT) analog."""

    def __init__(self, query_id: str, reserved: int, budget: int,
                 killed_by: str = "node"):
        self.query_id = query_id
        self.reserved = reserved
        self.budget = budget
        super().__init__(
            f"Query {query_id} exceeded {killed_by} memory limit: "
            f"reserved {reserved} bytes, budget {budget} bytes")


class MemoryPool:
    """Per-node pool: queries reserve/free bytes against one budget.

    `revoke_hook(query_id, bytes_needed)` is consulted when a
    reservation would cross `revoke_threshold` (fraction of budget):
    it should spill revocable state and return the bytes it freed —
    the MemoryRevokingScheduler contract."""

    def __init__(self, budget_bytes: int,
                 revoke_threshold: float = 0.8):
        self.budget = int(budget_bytes)
        self.revoke_threshold = revoke_threshold
        self._lock = threading.Lock()
        self._by_query: Dict[str, int] = {}
        self._revoke_hooks: List[Callable[[str, int], int]] = []
        self.revocations = 0            # observability counters
        self.revoked_bytes = 0

    @property
    def reserved(self) -> int:
        with self._lock:
            return sum(self._by_query.values())

    def query_reserved(self, query_id: str) -> int:
        """Bytes reserved for a query. Workers key reservations by task
        id (`{qid}.{stage}.{...}`), so a query's total is the exact key
        plus every dotted-prefix task key."""
        pfx = query_id + "."
        with self._lock:
            return sum(b for k, b in self._by_query.items()
                       if k == query_id or k.startswith(pfx))

    def add_revoke_hook(self, hook: Callable[[str, int], int]) -> None:
        self._revoke_hooks.append(hook)

    def reserve(self, query_id: str, nbytes: int) -> None:
        """Reserve or raise ExceededMemoryLimitError for THIS query.
        Crossing the revoke threshold first runs the revocation hooks
        (largest-reservation queries first — spill-before-fail)."""
        nbytes = int(nbytes)
        with self._lock:
            total = sum(self._by_query.values())
        if total + nbytes > self.budget * self.revoke_threshold:
            self._try_revoke(total + nbytes
                             - int(self.budget * self.revoke_threshold))
        with self._lock:
            total = sum(self._by_query.values())
            if total + nbytes > self.budget:
                raise ExceededMemoryLimitError(
                    query_id,
                    self._by_query.get(query_id, 0) + nbytes,
                    self.budget)
            self._by_query[query_id] = \
                self._by_query.get(query_id, 0) + nbytes
            self._set_fraction_locked()

    def _set_fraction_locked(self) -> None:
        if self.budget > 0:
            _M_POOL_FRACTION.set(
                sum(self._by_query.values()) / self.budget)

    def _try_revoke(self, need: int) -> int:
        freed = 0
        # biggest reservations revoke first (MemoryRevokingScheduler's
        # TaskRevocableMemoryComparator order)
        with self._lock:
            order = sorted(self._by_query, key=self._by_query.get,
                           reverse=True)
        for qid in order:
            if freed >= need:
                break
            for hook in self._revoke_hooks:
                got = int(hook(qid, need - freed) or 0)
                if got > 0:
                    freed += got
                    self.revocations += 1
                    self.revoked_bytes += got
                    _M_REVOCATIONS.inc()
                    _M_REVOKED.inc(got)
                    with self._lock:
                        self._by_query[qid] = max(
                            0, self._by_query.get(qid, 0) - got)
        return freed

    def free(self, query_id: str, nbytes: Optional[int] = None) -> None:
        pfx = query_id + "."
        with self._lock:
            if nbytes is None:
                # full release drops the query's task-keyed
                # reservations too (worker pools key by task id)
                for k in [k for k in self._by_query
                          if k == query_id or k.startswith(pfx)]:
                    self._by_query.pop(k, None)
            else:
                cur = self._by_query.get(query_id, 0)
                nxt = max(0, cur - int(nbytes))
                if nxt:
                    self._by_query[query_id] = nxt
                else:
                    self._by_query.pop(query_id, None)
            self._set_fraction_locked()


class ClusterMemoryManager:
    """Coordinator-side view over every worker pool. On sustained
    exhaustion, kills the single biggest query cluster-wide
    (ClusterMemoryManager.java:106 + LowMemoryKiller)."""

    def __init__(self, pools: List[MemoryPool],
                 budget_bytes: Optional[int] = None):
        """`budget_bytes` is the CLUSTER query-memory limit
        (query_max_memory) — independent of the per-node pool budgets,
        exactly like the reference's general-pool accounting; defaults
        to the sum of node budgets."""
        self.pools = pools
        self._budget = budget_bytes
        self.killed: Dict[str, ExceededMemoryLimitError] = {}
        self.kills = 0      # lifetime victim count (observability)

    def cluster_reserved(self) -> int:
        return sum(p.reserved for p in self.pools)

    def cluster_budget(self) -> int:
        if self._budget is not None:
            return self._budget
        return sum(p.budget for p in self.pools)

    def biggest_query(self) -> Optional[str]:
        totals: Dict[str, int] = {}
        for p in self.pools:
            with p._lock:
                for key, b in p._by_query.items():
                    # task-keyed worker reservations roll up to the
                    # owning query (task id = `{qid}.{stage}.{...}`)
                    qid = key.split(".", 1)[0]
                    totals[qid] = totals.get(qid, 0) + b
        if not totals:
            return None
        return max(totals, key=totals.get)

    def maybe_kill(self) -> Optional[str]:
        """If the cluster is over budget, mark the biggest query killed
        and free its reservations everywhere. Returns the victim id."""
        if self.cluster_reserved() <= self.cluster_budget():
            return None
        victim = self.biggest_query()
        if victim is None:
            return None
        reserved = sum(p.query_reserved(victim) for p in self.pools)
        self.killed[victim] = ExceededMemoryLimitError(
            victim, reserved, self.cluster_budget(), killed_by="cluster")
        for p in self.pools:
            p.free(victim)
        self.kills += 1
        _M_KILLED.inc()
        return victim

    def check_killed(self, query_id: str) -> None:
        err = self.killed.pop(query_id, None)
        if err is not None:
            raise err
