"""Lifespan-batched execution: bounded working sets for big scans.

Reference roles: grouped execution over bucket lifespans
(presto-main-base/.../execution/Lifespan.java,
sql/planner/GroupedExecutionTagger.java) and the split-streaming driver
loop (SqlTaskExecution.java:509): instead of materializing the whole
driving table, stream K row-range lifespans of it through the compiled
fragment, accumulating PARTIAL aggregation states, and finish with one
FINAL aggregation over the concatenated partials. Memory is bounded by
the per-lifespan capacity — the executor's static accounting
(MemoryLimitExceeded) decides when batching is needed.

Applies to plans whose root path is
Output -> [Sort|TopN|Limit]* -> Aggregation(single) -> <pipeline over the
driving scan> — the shape of every aggregation-rooted TPC-H query.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from presto_tpu.data.column import Column, Page, bucket_capacity
from presto_tpu.exec.executor import MemoryLimitExceeded
from presto_tpu.exec.split_executor import SplitExecutor
from presto_tpu.ops.aggregate import grouped_aggregate
from presto_tpu.ops.sort import limit_page, sort_page, top_n
from presto_tpu.plan.nodes import (
    AggregationNode, FilterNode, LimitNode, OutputNode, PlanNode,
    ProjectNode, SortNode, Step, TableScanNode, TopNNode,
)


def _root_chain(plan: PlanNode):
    """(above_chain, agg) where above_chain are the row-wise/ordering
    nodes over the root aggregation (Output, Sort, TopN, Limit, the final
    projection, HAVING filters); None if the plan has no such shape."""
    above: List[PlanNode] = []
    node = plan
    while isinstance(node, (OutputNode, SortNode, TopNNode, LimitNode,
                            ProjectNode, FilterNode)):
        above.append(node)
        node = node.source
    if isinstance(node, AggregationNode) and node.step == Step.SINGLE:
        return above, node
    return None


def _driving_scan(connector, plan: PlanNode) -> Optional[str]:
    """The largest table scanned — the one worth streaming."""
    best, best_rows = None, -1

    def walk(n):
        nonlocal best, best_rows
        if isinstance(n, TableScanNode):
            rows = connector.table(n.table).num_rows
            if rows > best_rows:
                best, best_rows = n.table, rows
        for c in n.children():
            if c is not None:
                walk(c)
    walk(plan)
    return best


def _streamable(below_agg: PlanNode, driving: str) -> bool:
    """True iff every occurrence of the driving scan reaches the root
    aggregation only through row-preserving paths: filters, projections
    and the PROBE side of inner/left joins. A driving scan under a nested
    aggregation, a join build/filtering side, a window or a sort would
    make per-batch partials non-additive — batching would silently
    corrupt results, so those shapes fall back to single-shot."""
    return _streamable_from(
        below_agg,
        lambda n: isinstance(n, TableScanNode) and n.table == driving)


def _streamable_from(below_agg: PlanNode, is_driving) -> bool:
    """Generalized additivity check: `is_driving(node)` marks the
    streamed input (a table scan lifespan, or a RemoteSourceNode whose
    pages arrive in chunks — server/task_manager's non-leaf streaming)."""
    from presto_tpu.plan.nodes import JoinNode, JoinType, RemoteSourceNode

    def has_driving(n) -> bool:
        if is_driving(n):
            return True
        return any(c is not None and has_driving(c)
                   for c in n.children())

    def ok(n) -> bool:
        if is_driving(n):
            return True
        if isinstance(n, (TableScanNode, RemoteSourceNode)):
            return True
        if isinstance(n, (FilterNode, ProjectNode)):
            return ok(n.source)
        if isinstance(n, JoinNode):
            if has_driving(n.build):
                return False
            if n.join_type not in (JoinType.INNER, JoinType.LEFT,
                                   JoinType.SEMI, JoinType.ANTI,
                                   JoinType.ANTI_EXISTS):
                return False
            return ok(n.probe)
        # Any other node (nested aggregation, window, sort, unique-id)
        # between the driving input and the root agg is non-streamable.
        return not has_driving(n)

    return ok(below_agg)


def _dynamic_filter(connector, ex: SplitExecutor, agg_source: PlanNode,
                    driving: str):
    """Build-side dynamic filter (reference: DynamicFilterSourceOperator +
    LocalDynamicFilter feeding probe-side scans). TPU-shaped realization:
    the compiled fragment's shapes are static, so the win is HOST-side —
    execute the topmost non-driving build subtree once, take its join-key
    [min, max], and skip whole lifespans whose driving-scan key slice
    cannot intersect. Returns (scan column name, lo, hi, build_empty) or
    None when no eligible join exists."""
    from presto_tpu.expr.nodes import InputRef
    from presto_tpu.plan.nodes import JoinNode, JoinType

    def scans_driving(n) -> bool:
        if isinstance(n, TableScanNode):
            return n.table == driving
        return any(c is not None and scans_driving(c)
                   for c in n.children())

    def scan_column(n, channel: int):
        """Resolve `channel` of n's output to a raw driving-scan column
        name through Filter/Project/probe-side-join chains."""
        if isinstance(n, TableScanNode):
            return n.columns[channel] if n.table == driving else None
        if isinstance(n, FilterNode):
            return scan_column(n.source, channel)
        if isinstance(n, ProjectNode):
            e = n.expressions[channel]
            if isinstance(e, InputRef):
                return scan_column(n.source, e.field)
            return None
        if isinstance(n, JoinNode):
            if channel < len(n.probe.output_types):
                return scan_column(n.probe, channel)
            return None
        return None

    def find(n):
        if isinstance(n, JoinNode) \
                and n.join_type in (JoinType.INNER, JoinType.SEMI) \
                and len(n.probe_keys) >= 1 \
                and not scans_driving(n.build):
            col = scan_column(n.probe, n.probe_keys[0])
            if col is not None:
                return n, col
        for c in n.children():
            if c is not None and scans_driving(c):
                r = find(c)
                if r is not None:
                    return r
        return None

    hit = find(agg_source)
    if hit is None:
        return None
    join, col = hit
    # string keys: dictionary codes are only comparable for aligned
    # dictionaries; restrict the filter to numeric/date keys
    if join.build.output_types[join.build_keys[0]].is_string:
        return None
    build_page = ex.execute(join.build)
    if getattr(ex, "ndev", 1) > 1:
        from presto_tpu.parallel.mesh import unstack_page
        pages = unstack_page(build_page)
    else:
        pages = [build_page]
    parts = []
    for p in pages:
        key = p.columns[join.build_keys[0]]
        n = int(p.num_rows)
        if n:
            v = np.asarray(key.values)[:n][~np.asarray(key.nulls)[:n]]
            if len(v):
                parts.append(v)
    if not parts:
        return (col, 0, -1, True)
    v = np.concatenate(parts)
    return (col, v.min(), v.max(), False)


@dataclasses.dataclass
class _HostPartial:
    """A spilled partial: plain numpy, no device residency. The TPU spill
    analog (reference: spiller/FileSingleStreamSpiller +
    MemoryRevokingScheduler): HBM holds only the in-flight lifespan;
    accumulated partials live in host RAM until the final merge."""
    columns: List[tuple]       # (values np, nulls np, Type, StringDict)
    num_rows: int
    names: tuple


def _dec128_host(c, n: int):
    """Exact host image of a Decimal128Column's limb lanes (the float
    image to_numpy produces loses exactness past 2^53 — the round-4
    `_HostPartial` hole). Marker tuple:
    ("dec128", (l3, l2, l1, l0), count|None)."""
    lanes, nl, cnt = c._host()
    return (("dec128", tuple(np.array(x[:n]) for x in lanes),
             None if cnt is None else np.array(cnt[:n])),
            np.array(nl[:n]), c.type, None)


def _spill_to_host(p: Page) -> _HostPartial:
    from presto_tpu.data.column import Decimal128Column
    n = int(p.num_rows)
    cols = []
    for c in p.columns:
        if isinstance(c, Decimal128Column):
            cols.append(_dec128_host(c, n))
            continue
        v, nl = c.to_numpy(n)
        cols.append((np.array(v), np.array(nl), c.type, c.dictionary))
    return _HostPartial(cols, n, p.names)


def _part_cols(p, spiller=None):
    from presto_tpu.data.column import Decimal128Column
    from presto_tpu.exec.spill import SpillHandle
    if isinstance(p, SpillHandle):
        p = spiller.read(p)            # disk -> device page
    if isinstance(p, _HostPartial):
        return p.columns
    n = int(p.num_rows)
    return [(_dec128_host(c, n) if isinstance(c, Decimal128Column)
             else (np.asarray(c.values)[:n], np.asarray(c.nulls)[:n],
                   c.type, c.dictionary)) for c in p.columns]


def _concat_pages(pages: List, spiller=None) -> Page:
    """Host-side concatenation of the valid rows of several partials
    (device Pages, host-RAM _HostPartials, or disk SpillHandles) with
    identical schemas. Decimal128 limb lanes concatenate exactly."""
    from presto_tpu.data.column import Decimal128Column
    parts = [_part_cols(p, spiller) for p in pages]
    total = sum(int(p.num_rows) for p in pages)
    cap = bucket_capacity(max(total, 1))
    cols = []
    for i, (v0, _n0, t0, d0) in enumerate(parts[0]):
        nulls = np.concatenate([pc[i][1] for pc in parts])
        if isinstance(v0, tuple) and v0 and v0[0] == "dec128":
            def lane(j):
                a = np.concatenate([pc[i][0][1][j] for pc in parts])
                out = np.zeros(cap, dtype=np.int64)
                out[:total] = a
                return jnp.asarray(out)
            cnts = [pc[i][0][2] for pc in parts]
            count = None
            if cnts[0] is not None:
                ca = np.concatenate(cnts)
                cout = np.zeros(cap, dtype=np.int64)
                cout[:total] = ca
                count = jnp.asarray(cout)
            nl = np.ones(cap, dtype=bool)
            nl[:total] = nulls
            cols.append(Decimal128Column(
                lane(0), lane(1), lane(2), lane(3),
                jnp.asarray(nl), t0, count))
            continue
        vals = np.concatenate([pc[i][0] for pc in parts])
        cols.append(Column.from_numpy(vals, t0, nulls=nulls,
                                      dictionary=d0, capacity=cap))
    return Page.from_columns(cols, total, pages[0].names)


class BatchedRunner:
    """Prepared lifespan-batched execution: plan analysis, partial-plan
    construction and the SplitExecutor (with its compiled-program memo)
    are built ONCE; run() executes all lifespans and the final merge.
    Repeat run() calls reuse the jitted programs — the shape the bench
    needs for warm timing, and the worker for repeated tasks."""

    def __init__(self, connector, plan: PlanNode, num_batches: int,
                 memory_limit_bytes: Optional[int] = None, session=None,
                 mesh=None):
        from presto_tpu.plan.fragment import (
            _UNSPLITTABLE, _partial_agg_layout,
        )

        self.connector = connector
        self.num_batches = num_batches
        resolver = SplitExecutor(connector)
        plan = resolver._resolve_subqueries(plan)
        self.plan = plan
        chain = _root_chain(plan)
        driving = _driving_scan(connector, plan)
        self.batchable = not (
            chain is None or driving is None or num_batches <= 1
            or not _streamable(chain[1].source, driving)
            # sketch aggregates have no column-shaped partial state —
            # same rule as the fragmenter's reshard-instead-of-split
            or any(a.kind in _UNSPLITTABLE for a in chain[1].aggs))
        if mesh is not None:
            # distributed lifespan batching: each lifespan's partial
            # runs on the device mesh, sub-split per device
            from presto_tpu.exec.dist_executor import DistSplitExecutor
            self.ex = DistSplitExecutor(connector, mesh, session=session)
        else:
            self.ex = SplitExecutor(connector, session=session)
        self.ex.memory_limit_bytes = memory_limit_bytes
        self.driving = driving
        if not self.batchable:
            return
        self.above, self.agg = chain
        partial_specs, final_specs, pnames, ptypes = \
            _partial_agg_layout(self.agg)
        self.final_specs = final_specs
        self.partial_plan = AggregationNode(
            pnames, ptypes, source=self.agg.source,
            group_fields=self.agg.group_fields, aggs=tuple(partial_specs),
            step=Step.PARTIAL, group_count_hint=self.agg.group_count_hint)
        self.dyn = None
        if self.ex.session["dynamic_filtering_enabled"]:
            self.dyn = _dynamic_filter(connector, self.ex,
                                       self.agg.source, driving)
        self.spill = bool(self.ex.session["spill_enabled"])
        # spill_path set -> partials revoke to DISK files
        # (FileSingleStreamSpiller role); empty -> host RAM offload
        self.spill_dir = self.ex.session["spill_path"] or None
        # streaming scans (the scale ladder): bound the rows one leaf
        # scan materializes, so a lifespan's working set is the run size,
        # not the split size. Mesh executors keep whole-split splits
        # (their sub-split sharding already bounds per-device rows).
        self.stream_rows = int(self.ex.session["streaming_scan_rows"] or 0)

    def _host_pages(self, p: Page) -> List[Page]:
        """A mesh executor returns a stacked sharded page — split it into
        per-device host pages; single-device pages pass through."""
        if getattr(self.ex, "ndev", 1) > 1:
            from presto_tpu.parallel.mesh import unstack_page
            return unstack_page(p)
        return [p]

    def run(self, stats: Optional[dict] = None) -> Page:
        if not self.batchable:
            out = self.ex.execute(self.plan)
            pages = self._host_pages(out)
            return pages[0] if len(pages) == 1 else _concat_pages(pages)
        connector, ex = self.connector, self.ex
        driving, num_batches = self.driving, self.num_batches
        spiller = None
        if self.spill and self.spill_dir:
            from presto_tpu.exec.spill import FileSpiller
            spiller = FileSpiller(self.spill_dir)
        try:
            merged = self._run_batches(stats, spiller)
        finally:
            # a query failing mid-spill must not leak run files (or, for
            # a spiller-owned tempdir, the directory itself)
            if spiller is not None:
                spiller.close()
        k = len(self.agg.group_fields)
        out_cap = bucket_capacity(max(int(merged.num_rows), 256))
        page, _groups = grouped_aggregate(merged, tuple(range(k)),
                                          tuple(self.final_specs),
                                          out_cap)
        page = Page(page.columns, page.num_rows, self.agg.output_names)
        return self._finish_above(page)

    def _run_batches(self, stats, spiller) -> Page:
        """Per-lifespan partial aggregation, spilled partials included;
        returns the concatenated partial page (spill files still live)."""
        connector, ex = self.connector, self.ex
        driving, num_batches = self.driving, self.num_batches
        skipped = 0
        partials: List[Page] = []
        for b in range(num_batches):
            if self.dyn is not None:
                col, lo, hi, empty = self.dyn
                t = connector.table(driving, part=b,
                                    num_parts=num_batches)
                if t.num_rows:
                    if empty:
                        skipped += 1
                        continue
                    # metadata min/max first (parquet row-group stats:
                    # prunes the lifespan WITHOUT reading the column);
                    # stats arrive normalized to engine representation,
                    # but a source that still yields raw logical values
                    # (dates/timestamps/varchar vs engine ints) must
                    # fall back to the column scan, never TypeError out
                    mm = (t.column_minmax(col)
                          if hasattr(t, "column_minmax") else None)
                    pruned = None
                    if mm is not None:
                        try:
                            pruned = bool(mm[0] > hi or mm[1] < lo)
                        except TypeError:
                            pruned = None
                    if pruned is None:
                        sv = t.arrays[col][:t.num_rows]
                        pruned = bool(sv.min() > hi or sv.max() < lo)
                    if pruned:
                        skipped += 1
                        continue
            for p in self._partial_pages(b):
                if self.spill:
                    if spiller is not None:
                        p = spiller.spill(p)
                    else:
                        p = _spill_to_host(p)
                partials.append(p)
        if stats is not None:
            stats.update(batches=num_batches, skipped=skipped)
        if not partials:
            # every lifespan pruned: run one anyway — pruned means its
            # join cannot match, so it yields the correct zero-state
            # partial (global aggregates still emit their count=0 row)
            ex.set_splits({driving: [(0, num_batches)]})
            partials.extend(
                self._host_pages(ex.execute(self.partial_plan)))

        if stats is not None and spiller is not None:
            stats.update(spilled_bytes=spiller.total_spilled_bytes,
                         spill_files=len(spiller.handles))
        return _concat_pages(partials, spiller)

    def _partial_pages(self, b: int):
        """Execute the partial plan over lifespan `b`, yielding its
        output pages. With streaming_scan_rows set (single-device
        executors only), the driving split flows through in bounded
        scan runs — connector.scan_runs — so the lifespan never holds
        its whole split resident; otherwise one whole-split shot."""
        ex = self.ex
        if (self.stream_rows > 0 and getattr(ex, "ndev", 1) == 1
                and hasattr(ex, "set_split_tables")
                and hasattr(self.connector, "scan_runs")):
            try:
                for run in self.connector.scan_runs(
                        self.driving, self.stream_rows, part=b,
                        num_parts=self.num_batches):
                    ex.set_split_tables({self.driving: run})
                    for p in self._host_pages(
                            ex.execute(self.partial_plan)):
                        yield p
            finally:
                ex.set_split_tables({})
            return
        ex.set_splits({self.driving: [(b, self.num_batches)]})
        for p in self._host_pages(ex.execute(self.partial_plan)):
            yield p

    def _finish_above(self, page: Page) -> Page:
        # Interpret the small chain above the aggregation.
        from presto_tpu.data.column import compact
        from presto_tpu.expr.compile import compile_expr

        for node in reversed(self.above):
            if isinstance(node, SortNode):
                page = sort_page(page, node.keys)
            elif isinstance(node, TopNNode):
                page = top_n(page, node.keys, node.count)
            elif isinstance(node, LimitNode):
                page = limit_page(page, node.count)
            elif isinstance(node, ProjectNode):
                cols = tuple(compile_expr(e)(page)
                             for e in node.expressions)
                page = Page(cols, page.num_rows, node.output_names)
            elif isinstance(node, FilterNode):         # HAVING
                c = compile_expr(node.predicate)(page)
                page = compact(page, ~c.nulls & c.values.astype(bool))
            else:  # OutputNode
                page = Page(page.columns, page.num_rows,
                            node.output_names)
        return page


def execute_batched(connector, plan: PlanNode, num_batches: int,
                    memory_limit_bytes: Optional[int] = None,
                    session=None, mesh=None,
                    stats: Optional[dict] = None) -> Page:
    """Execute `plan` streaming the driving scan in `num_batches`
    lifespans. Falls back to single-shot execution when the plan shape
    does not support batching (no root aggregation). With a `mesh`, each
    lifespan's partial runs distributed over the device mesh (sub-split
    per device). `stats` (if given) records {"batches", "skipped"} —
    dynamic-filter effectiveness."""
    return BatchedRunner(connector, plan, num_batches,
                         memory_limit_bytes, session, mesh=mesh).run(stats)


def execute_bounded(connector, plan: PlanNode,
                    memory_limit_bytes: int,
                    max_batches: int = 64,
                    session=None) -> Tuple[Page, int]:
    """Execute under a hard memory limit, doubling the lifespan count
    until the static plan footprint fits. Returns (page, batches_used).
    Reference role: the memory-pool + grouped-execution pairing that lets
    a bounded worker run arbitrarily large scans."""
    chain = _root_chain(plan)
    driving = _driving_scan(connector, plan)
    batchable = (chain is not None and driving is not None
                 and _streamable(chain[1].source, driving))
    batches = 1
    while True:
        try:
            return (execute_batched(connector, plan, batches,
                                    memory_limit_bytes,
                                    session=session), batches)
        except MemoryLimitExceeded:
            if not batchable or batches >= max_batches:
                raise
            batches *= 2
