"""Lifespan-batched execution: bounded working sets for big scans.

Reference roles: grouped execution over bucket lifespans
(presto-main-base/.../execution/Lifespan.java,
sql/planner/GroupedExecutionTagger.java) and the split-streaming driver
loop (SqlTaskExecution.java:509): instead of materializing the whole
driving table, stream K row-range lifespans of it through the compiled
fragment, accumulating PARTIAL aggregation states, and finish with one
FINAL aggregation over the concatenated partials. Memory is bounded by
the per-lifespan capacity — the executor's static accounting
(MemoryLimitExceeded) decides when batching is needed.

Applies to plans whose root path is
Output -> [Sort|TopN|Limit]* -> Aggregation(single) -> <pipeline over the
driving scan> — the shape of every aggregation-rooted TPC-H query.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from presto_tpu.data.column import Column, Page, bucket_capacity
from presto_tpu.exec.executor import MemoryLimitExceeded
from presto_tpu.exec.split_executor import SplitExecutor
from presto_tpu.ops.aggregate import grouped_aggregate
from presto_tpu.ops.sort import limit_page, sort_page, top_n
from presto_tpu.plan.nodes import (
    AggregationNode, FilterNode, LimitNode, OutputNode, PlanNode,
    ProjectNode, SortNode, Step, TableScanNode, TopNNode,
)


def _root_chain(plan: PlanNode):
    """(above_chain, agg) where above_chain are the row-wise/ordering
    nodes over the root aggregation (Output, Sort, TopN, Limit, the final
    projection, HAVING filters); None if the plan has no such shape."""
    above: List[PlanNode] = []
    node = plan
    while isinstance(node, (OutputNode, SortNode, TopNNode, LimitNode,
                            ProjectNode, FilterNode)):
        above.append(node)
        node = node.source
    if isinstance(node, AggregationNode) and node.step == Step.SINGLE:
        return above, node
    return None


def _driving_scan(connector, plan: PlanNode) -> Optional[str]:
    """The largest table scanned — the one worth streaming."""
    best, best_rows = None, -1

    def walk(n):
        nonlocal best, best_rows
        if isinstance(n, TableScanNode):
            rows = connector.table(n.table).num_rows
            if rows > best_rows:
                best, best_rows = n.table, rows
        for c in n.children():
            if c is not None:
                walk(c)
    walk(plan)
    return best


def _streamable(below_agg: PlanNode, driving: str) -> bool:
    """True iff every occurrence of the driving scan reaches the root
    aggregation only through row-preserving paths: filters, projections
    and the PROBE side of inner/left joins. A driving scan under a nested
    aggregation, a join build/filtering side, a window or a sort would
    make per-batch partials non-additive — batching would silently
    corrupt results, so those shapes fall back to single-shot."""
    from presto_tpu.plan.nodes import JoinNode, JoinType

    def scans_driving(n) -> bool:
        if isinstance(n, TableScanNode):
            return n.table == driving
        return any(c is not None and scans_driving(c)
                   for c in n.children())

    def ok(n) -> bool:
        if isinstance(n, TableScanNode):
            return True
        if isinstance(n, (FilterNode, ProjectNode)):
            return ok(n.source)
        if isinstance(n, JoinNode):
            if scans_driving(n.build):
                return False
            if n.join_type not in (JoinType.INNER, JoinType.LEFT,
                                   JoinType.SEMI, JoinType.ANTI,
                                   JoinType.ANTI_EXISTS):
                return False
            return ok(n.probe)
        # Any other node (nested aggregation, window, sort, unique-id)
        # between the driving scan and the root agg is non-streamable.
        return not scans_driving(n)

    return ok(below_agg)


def _concat_pages(pages: List[Page]) -> Page:
    """Host-side concatenation of the valid rows of several pages with
    identical schemas (partial-state pages are small)."""
    total = sum(int(p.num_rows) for p in pages)
    cap = bucket_capacity(max(total, 1))
    cols = []
    for i, c0 in enumerate(pages[0].columns):
        vals = np.concatenate([
            np.asarray(p.columns[i].values)[:int(p.num_rows)]
            for p in pages])
        nulls = np.concatenate([
            np.asarray(p.columns[i].nulls)[:int(p.num_rows)]
            for p in pages])
        cols.append(Column.from_numpy(vals, c0.type, nulls=nulls,
                                      dictionary=c0.dictionary,
                                      capacity=cap))
    return Page.from_columns(cols, total, pages[0].names)


def execute_batched(connector, plan: PlanNode, num_batches: int,
                    memory_limit_bytes: Optional[int] = None) -> Page:
    """Execute `plan` streaming the driving scan in `num_batches`
    lifespans. Falls back to single-shot execution when the plan shape
    does not support batching (no root aggregation)."""
    from presto_tpu.plan.fragment import _partial_agg_layout

    # Resolve scalar subqueries ONCE over the full tables (a per-batch
    # resolution would compute them over split slices).
    resolver = SplitExecutor(connector)
    plan = resolver._resolve_subqueries(plan)

    chain = _root_chain(plan)
    driving = _driving_scan(connector, plan)
    if (chain is None or driving is None or num_batches <= 1
            or not _streamable(chain[1].source, driving)):
        ex = SplitExecutor(connector)
        ex.memory_limit_bytes = memory_limit_bytes
        return ex.execute(plan)

    above, agg = chain
    partial_specs, final_specs, pnames, ptypes = _partial_agg_layout(agg)
    partial_plan = AggregationNode(
        pnames, ptypes, source=agg.source,
        group_fields=agg.group_fields, aggs=tuple(partial_specs),
        step=Step.PARTIAL, group_count_hint=agg.group_count_hint)

    ex = SplitExecutor(connector)
    ex.memory_limit_bytes = memory_limit_bytes
    partials: List[Page] = []
    for b in range(num_batches):
        ex.set_splits({driving: [(b, num_batches)]})
        partials.append(ex.execute(partial_plan))

    merged = _concat_pages(partials)
    k = len(agg.group_fields)
    out_cap = bucket_capacity(max(int(merged.num_rows), 256))
    page, _groups = grouped_aggregate(merged, tuple(range(k)),
                                      tuple(final_specs), out_cap)
    page = Page(page.columns, page.num_rows, agg.output_names)

    # Interpret the small chain above the aggregation.
    from presto_tpu.data.column import compact
    from presto_tpu.expr.compile import compile_expr

    for node in reversed(above):
        if isinstance(node, SortNode):
            page = sort_page(page, node.keys)
        elif isinstance(node, TopNNode):
            page = top_n(page, node.keys, node.count)
        elif isinstance(node, LimitNode):
            page = limit_page(page, node.count)
        elif isinstance(node, ProjectNode):
            cols = tuple(compile_expr(e)(page) for e in node.expressions)
            page = Page(cols, page.num_rows, node.output_names)
        elif isinstance(node, FilterNode):         # HAVING
            c = compile_expr(node.predicate)(page)
            page = compact(page, ~c.nulls & c.values.astype(bool))
        else:  # OutputNode
            page = Page(page.columns, page.num_rows, node.output_names)
    return page


def execute_bounded(connector, plan: PlanNode,
                    memory_limit_bytes: int,
                    max_batches: int = 64) -> Tuple[Page, int]:
    """Execute under a hard memory limit, doubling the lifespan count
    until the static plan footprint fits. Returns (page, batches_used).
    Reference role: the memory-pool + grouped-execution pairing that lets
    a bounded worker run arbitrarily large scans."""
    chain = _root_chain(plan)
    driving = _driving_scan(connector, plan)
    batchable = (chain is not None and driving is not None
                 and _streamable(chain[1].source, driving))
    batches = 1
    while True:
        try:
            return (execute_batched(connector, plan, batches,
                                    memory_limit_bytes), batches)
        except MemoryLimitExceeded:
            if not batchable or batches >= max_batches:
                raise
            batches *= 2
