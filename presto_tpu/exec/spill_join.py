"""Grace-style spillable hash join: build-side (and probe-side)
partitioning through FileSpiller when the build does not fit the
memory pool budget.

Reference roles: HashBuilderOperator's revocable build memory spilling
through GenericPartitioningSpiller (spiller/PartitioningSpillerFactory)
and LookupJoinOperator's unspilled-then-spilled probe passes — the
"spill-everywhere" half of the reference's memory arbitration story.
The spill format is the engine's own SerializedPage+LZ4 frames
(exec/spill.FileSpiller), bit-identical to an exchange stream.

Shape handled: a plan whose root path is
Output -> [Sort|TopN|Limit|Project|Filter]* -> Join(INNER) where each
join side is a Filter/Project chain over ONE table scan. Both sides
stream in row-range lifespans; every chunk is hash-partitioned on the
join keys and spilled, then partitions probe one at a time — peak
memory is one lifespan chunk plus one partition pair plus its join
output, never a whole build side. String join keys are refused
(dictionary codes are not comparable across sides), as is anything
fancier than the shape above — callers fall back to the error the
memory pool already raised.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from presto_tpu.data.column import Page, bucket_capacity, compact
from presto_tpu.exec.executor import _row_bytes
from presto_tpu.exec.spill import FileSpiller
from presto_tpu.exec.split_executor import SplitExecutor
from presto_tpu.plan.nodes import (
    FilterNode, JoinNode, JoinType, LimitNode, OutputNode, PlanNode,
    ProjectNode, SortNode, TableScanNode, TopNNode,
)


class SpillJoinUnsupported(Exception):
    """The plan does not have the partitionable join shape — the
    caller should surface its original memory error instead."""


def _root_join(plan: PlanNode):
    """(above_chain, join) for Output -> rowwise* -> Join(INNER),
    else None."""
    above: List[PlanNode] = []
    node = plan
    while isinstance(node, (OutputNode, SortNode, TopNNode, LimitNode,
                            ProjectNode, FilterNode)):
        above.append(node)
        node = node.source
    if isinstance(node, JoinNode) and node.join_type == JoinType.INNER \
            and node.probe_keys and not node.emit_flag:
        return above, node
    return None


def _single_table(n: PlanNode) -> Optional[str]:
    """Table name when `n` is a Filter/Project chain over one scan —
    the shape whose row-range splits partition its output exactly."""
    if isinstance(n, TableScanNode):
        return n.table
    if isinstance(n, (FilterNode, ProjectNode)):
        return _single_table(n.source)
    return None


def _host_pages(ex, page: Page) -> List[Page]:
    if getattr(ex, "ndev", 1) > 1:
        from presto_tpu.parallel.mesh import unstack_page
        return unstack_page(page)
    return [page]


def _batches_for(connector, table: str, types, limit: int) -> int:
    """Lifespans needed so one chunk's static footprint stays well
    under the budget (quarter-budget target, capped at 64)."""
    est = max(connector.table(table).num_rows, 1) * _row_bytes(types)
    nb = 1
    while est / nb > max(limit, 1) / 4 and nb < 64:
        nb *= 2
    return nb


def _partition_and_spill(ex, subtree: PlanNode, table: str, nb: int,
                         key_fields, n_parts: int, spiller: FileSpiller,
                         parts: Dict[int, list]) -> None:
    """Stream `subtree` in `nb` lifespans of `table`; hash-partition
    every chunk on `key_fields` and spill each non-empty partition."""
    from presto_tpu.ops.keys import hash_columns

    for b in range(nb):
        ex.set_splits({table: [(b, nb)]})
        for page in _host_pages(ex, ex.execute(subtree)):
            if not int(page.num_rows):
                continue
            h = np.asarray(hash_columns(
                [page.columns[f] for f in key_fields]))
            valid = np.asarray(page.row_valid())
            pids = (h % np.uint64(n_parts)).astype(np.int64)
            for p in range(n_parts):
                keep = valid & (pids == p)
                if not keep.any():
                    continue
                part = compact(page, jnp.asarray(keep))
                if int(part.num_rows):
                    parts.setdefault(p, []).append(spiller.spill(part))


def _join_partition(probe: Page, build: Page, join: JoinNode) -> Page:
    """hash_join one partition pair, growing the output capacity on
    overflow (the executor's capacity-retry contract, host-side)."""
    from presto_tpu.ops.join import hash_join

    p_rows, b_rows = int(probe.num_rows), int(build.num_rows)
    cap = bucket_capacity(max(p_rows + b_rows, 256))
    while True:
        page, total = hash_join(probe, build, join.probe_keys,
                                join.build_keys, cap, "inner")
        total = int(total)
        if total <= cap:
            return Page(page.columns, page.num_rows, join.output_names)
        cap = bucket_capacity(total)


def _apply_rowwise(above: List[PlanNode], page: Page) -> Page:
    """Interpret the small chain above the join (same discipline as
    lifespan.BatchedRunner._finish_above)."""
    from presto_tpu.data.column import compact as _compact
    from presto_tpu.expr.compile import compile_expr
    from presto_tpu.ops.sort import limit_page, sort_page, top_n

    for node in reversed(above):
        if isinstance(node, SortNode):
            page = sort_page(page, node.keys)
        elif isinstance(node, TopNNode):
            page = top_n(page, node.keys, node.count)
        elif isinstance(node, LimitNode):
            page = limit_page(page, node.count)
        elif isinstance(node, ProjectNode):
            cols = tuple(compile_expr(e)(page)
                         for e in node.expressions)
            page = Page(cols, page.num_rows, node.output_names)
        elif isinstance(node, FilterNode):
            c = compile_expr(node.predicate)(page)
            page = _compact(page, ~c.nulls & c.values.astype(bool))
        else:  # OutputNode
            page = Page(page.columns, page.num_rows, node.output_names)
    return page


def execute_spill_join(connector, plan: PlanNode,
                       memory_limit_bytes: int, session=None,
                       spill_dir: Optional[str] = None
                       ) -> Tuple[Page, dict]:
    """Execute a join-rooted plan under a memory budget by
    partitioning BOTH sides through the spiller and probing one
    partition at a time. Returns (page, stats) where stats records
    {"partitions", "spilled_bytes", "spill_files", "build_batches",
    "probe_batches"}. Raises SpillJoinUnsupported when the plan shape
    does not partition."""
    hit = _root_join(plan)
    if hit is None:
        raise SpillJoinUnsupported("plan root is not an inner join")
    above, join = hit
    if session is not None and not session["spill_enabled"]:
        raise SpillJoinUnsupported("spill_enabled is off")
    if getattr(join, "filter", None) is not None:
        raise SpillJoinUnsupported("join carries a residual filter")
    for f in join.build_keys:
        if join.build.output_types[f].is_string:
            # dictionary codes are not comparable across sides, so a
            # per-side hash partition would split matching keys apart
            raise SpillJoinUnsupported("string join keys")
    probe_table = _single_table(join.probe)
    build_table = _single_table(join.build)
    if probe_table is None or build_table is None \
            or probe_table == build_table:
        raise SpillJoinUnsupported("join sides are not single-table "
                                   "scan chains")

    ex = SplitExecutor(connector, session=session)
    # memory is bounded by OUR chunking, not by static admission — the
    # whole point of this path is running what admission refused
    ex.memory_limit_bytes = None
    build_nb = _batches_for(connector, build_table,
                            join.build.output_types, memory_limit_bytes)
    probe_nb = _batches_for(connector, probe_table,
                            join.probe.output_types, memory_limit_bytes)
    # one partition's build must fit the quarter-budget target too
    n_parts = _batches_for(connector, build_table,
                           join.build.output_types, memory_limit_bytes)
    n_parts = min(max(n_parts, 2), 64)

    build_parts: Dict[int, list] = {}
    probe_parts: Dict[int, list] = {}
    out_pages: List[Page] = []
    with FileSpiller(spill_dir) as spiller:
        _partition_and_spill(ex, join.build, build_table, build_nb,
                             join.build_keys, n_parts, spiller,
                             build_parts)
        _partition_and_spill(ex, join.probe, probe_table, probe_nb,
                             join.probe_keys, n_parts, spiller,
                             probe_parts)
        stats = {"partitions": n_parts,
                 "build_batches": build_nb, "probe_batches": probe_nb,
                 "spilled_bytes": spiller.total_spilled_bytes,
                 "spill_files": len(spiller.handles)}
        from presto_tpu.exec.lifespan import _concat_pages
        for p in range(n_parts):
            # an inner join emits nothing for a partition missing
            # either side
            if p not in build_parts or p not in probe_parts:
                continue
            build_page = _concat_pages(build_parts[p], spiller)
            probe_page = _concat_pages(probe_parts[p], spiller)
            joined = _join_partition(probe_page, build_page, join)
            if int(joined.num_rows):
                out_pages.append(joined)
        if not out_pages:
            # empty join result: still needs a correctly-typed page —
            # synthesize a zero-row page from the join schema
            from presto_tpu.data.column import Column
            cols = tuple(
                Column.from_strings([], capacity=256) if t.is_string
                else Column.from_numpy(np.zeros(0, dtype=t.dtype), t,
                                       capacity=256)
                for t in join.output_types)
            merged = Page(cols, jnp.asarray(0, dtype=jnp.int32),
                          join.output_names)
        else:
            merged = out_pages[0] if len(out_pages) == 1 \
                else _concat_pages(out_pages)
            merged = Page(merged.columns, merged.num_rows,
                          join.output_names)
    return _apply_rowwise(above, merged), stats
