"""Plan executor: lower a plan tree to ONE jit-compiled XLA program.

The reference executes a task as a pull-based chain of incremental operators
time-sliced on a thread pool (Driver.processFor,
presto-main-base/.../operator/Driver.java:310; TaskExecutor.java:87). That
model is wrong for XLA: here the *whole fragment* lowers to a single traced
function — scans arrive as device Pages, every operator is a pure
Page->Page transform, and XLA fuses across operator boundaries (the fusion
the reference gets piecemeal from PageProcessor codegen happens globally).

Dynamic cardinalities (join fan-out, group counts) use static capacity
buckets chosen from planner hints, with a host-side overflow-retry loop:
the compiled program also returns per-node "needed" counters; if any
exceeds its bucket, we re-lower at the next bucket and re-execute
(SURVEY.md §7.3 hard part #1 — the recompile is amortized across every
subsequent page/split batch at that bucket).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.data.column import Column, Page, bucket_capacity, compact
from presto_tpu.expr.compile import compile_expr
from presto_tpu.expr.nodes import (
    Call, InputRef, Literal, RowExpression, SpecialForm,
)
from presto_tpu.obs.metrics import (
    DEFAULT_ROWS_BUCKETS, DEFAULT_TIME_BUCKETS_S,
    histogram as _obs_histogram,
)
from presto_tpu.ops.aggregate import grouped_aggregate
from presto_tpu.ops.join import hash_join, merge_join
from presto_tpu.ops.sort import limit_page, sort_page, top_n
from presto_tpu.plan.nodes import (
    AggregationNode, AssignUniqueIdNode, ExchangeNode, FilterNode,
    GroupIdNode, JoinNode, JoinType, LimitNode, OutputNode, PlanNode,
    ProjectNode, RemoteSourceNode, SortNode, TableScanNode, TopNNode,
    MarkDistinctNode, TableWriterNode, UnionAllNode, UnnestNode,
    ValuesNode, WindowNode,
)

# per-operator execution histograms (OperatorStats role, scrapeable):
# wall seconds only exist on the profiled (collect_stats) island path —
# fused production dispatch deliberately has no per-operator sync —
# while output-row observations come from every converged program
_M_OP_WALL = _obs_histogram(
    "presto_tpu_operator_wall_seconds",
    "Per-operator island wall time (profiled executions)",
    ("operator",), buckets=DEFAULT_TIME_BUCKETS_S)
_M_OP_ROWS = _obs_histogram(
    "presto_tpu_operator_rows",
    "Per-operator output rows per execution", ("operator",),
    buckets=DEFAULT_ROWS_BUCKETS)


@dataclasses.dataclass
class ScanSpec:
    table: str
    columns: Tuple[str, ...]
    capacity: int


@dataclasses.dataclass
class RemoteSpec:
    """Input read from another fragment's result (the consumer side of a
    cut exchange; reference: RemoteSourceNode -> ExchangeOperator)."""
    fragment_id: int
    capacity: int


@dataclasses.dataclass(frozen=True)
class PageInputNode(PlanNode):
    """Placeholder leaf standing for an already-materialized child
    island's output page (island-split execution). Never appears in a
    coordinator plan — the executor synthesizes it when it cuts a plan
    into islands."""
    slot: int = 0


@dataclasses.dataclass
class PageInputSpec:
    """Scan-slot marker resolved from the executor's per-execution
    island inputs (no connector fetch)."""
    slot: int


class Overflow(Exception):
    def __init__(self, node_id: int, needed: int):
        self.node_id = node_id
        self.needed = needed


class QueryTimeoutError(RuntimeError):
    """query_max_execution_time exceeded (reference:
    QUERY_MAX_EXECUTION_TIME enforced by the QueryTracker). Checked at
    operator-island boundaries — a single compiled program is never
    interrupted mid-flight."""


class MemoryLimitExceeded(Exception):
    """Static plan footprint exceeds the executor's memory limit —
    the caller should batch (exec/lifespan.py) or reject the query.
    Reference role: MemoryPool reservation failure -> OOM kill
    (presto-main-base/.../memory/MemoryPool.java)."""

    def __init__(self, estimated: int, limit: int):
        super().__init__(
            f"plan needs ~{estimated // (1 << 20)} MiB device memory, "
            f"limit is {limit // (1 << 20)} MiB")
        self.estimated = estimated
        self.limit = limit


def _row_bytes(types) -> int:
    """Bytes per row of a page with these column types (values + null
    mask lane) — the static footprint unit of capacity accounting."""
    return sum(t.dtype.itemsize + 1 for t in types)


class Executor:
    """Executes a plan against a connector. Compiles once per (plan,
    capacity assignment); overflow retries bump capacities."""

    def __init__(self, connector, session=None):
        from presto_tpu.config import Session

        self.connector = connector
        self.session = session or Session()
        self._compiled: Dict = {}   # (plan, caps) -> (jitted, scans, watch)
        self._learned: Dict = {}    # plan -> learned capacity assignment
        # Static memory accounting (reference: memory/MemoryPool.java —
        # here capacities are static, so the whole footprint is known at
        # lower time). None = unlimited.
        self.memory_limit_bytes = self.session["query_max_memory_per_node"]
        self.last_memory_estimate = 0
        # Optional MemoryPool (exec/memory.py): static footprints
        # reserve against it at lower time (admission control BEFORE
        # execution — the TPU analog of MemoryPool.java's runtime
        # accounting); the engine frees per query.
        self.memory_pool = None
        self.pool_query_id: str = ""
        # EXPLAIN ANALYZE support (collect_stats session property):
        # per-node output row counts from the last execution.
        self.last_node_rows: Dict[int, int] = {}
        self._node_map: Dict[int, tuple] = {}   # nid -> (plan node, cap)
        self._stats_ids: List[int] = []

    def execute(self, plan: PlanNode) -> Page:
        import time
        budget = self.session["query_max_execution_time"]
        self._deadline = (time.time() + budget) if budget else None
        # stats maps are per query (islands accumulate into them)
        self.last_node_rows = {}
        self._node_map = {}
        plan = self._resolve_subqueries(plan)
        plan = self._prepare(plan)
        if isinstance(plan, TableWriterNode):
            return self._execute_writer(plan)
        return self._execute_prepared(plan)

    def _check_deadline(self):
        import time
        dl = getattr(self, "_deadline", None)
        if dl is not None and time.time() > dl:
            raise QueryTimeoutError(
                f"query exceeded query_max_execution_time "
                f"({self.session['query_max_execution_time']:.0f}s)")

    def _execute_writer(self, node: TableWriterNode) -> Page:
        """Writer root: run the source pipeline on device, then sink the
        rows host-side (ConnectorPageSink role) and emit the count row
        (TableWriterOperator's output contract). `column_names` maps the
        source outputs onto the target schema (missing columns
        NULL-fill), so a coordinator plan whose writer column order
        differs from the table layout still writes correctly."""
        page = self._execute_tree(node.source)
        rows = self._page_rows(page)
        schema = self.connector.schema(node.table)
        names = [c for c, _t in schema]
        cols = list(node.column_names) or list(page.names)
        if rows and len(rows[0]) != len(cols):
            raise ValueError(
                f"writer arity {len(rows[0])} != declared columns "
                f"{len(cols)}")
        if cols != names:
            unknown = [c for c in cols if c not in names]
            if unknown:
                raise ValueError(
                    f"writer columns not in table {node.table!r}: "
                    f"{unknown}")
            pos = {c: i for i, c in enumerate(cols)}
            rows = [tuple(r[pos[c]] if c in pos else None
                          for c in names) for r in rows]
        n = self.connector.append_rows(node.table, rows)
        out_col = Column.from_numpy(
            __import__("numpy").array([n], dtype="int64"),
            node.output_types[0])
        return Page.from_columns([out_col], 1, node.output_names)

    # ---- island-split execution ---------------------------------------
    # One XLA program per "fusion island" (a heavy operator plus the
    # row-wise Filter/Project chains feeding it) instead of one program
    # per plan: the remote TPU compile service OOMs on whole-plan
    # join-bearing programs, while every single-operator program
    # compiles. Device-resident Pages flow between islands — no host
    # round trip. This is the reference's own execution granularity
    # (operators connected by in-memory pages, Driver.java:310),
    # re-expressed as a handful of jit programs instead of ~38.
    _SPLIT_NODES = (JoinNode, AggregationNode, SortNode, TopNNode,
                    WindowNode, UnionAllNode, UnnestNode,
                    MarkDistinctNode, GroupIdNode)

    def _use_islands(self, plan: PlanNode) -> bool:
        mode = self.session["execution_mode"]
        if mode == "fused" or getattr(self, "_force_fused", False):
            return False
        found = [0]

        def walk(n):
            if isinstance(n, (JoinNode, WindowNode, UnionAllNode,
                              UnnestNode, MarkDistinctNode, GroupIdNode)):
                found[0] += 1
            elif isinstance(n, AggregationNode):
                found[0] += (2 if mode == "island" else 0)
            for c in n.children():
                if c is not None:
                    walk(c)
        walk(plan)
        # split only the shapes that blow up whole-plan compiles (in
        # "island" mode aggregations count too, via walk() above)
        return found[0] > 0

    def _island_of(self, plan: PlanNode):
        """(mini_plan, children): `plan`'s fusion island with descendant
        split-node subtrees replaced by PageInputNode slots. Cached by
        node identity (plans are reused across executions)."""
        cache = self.__dict__.setdefault("_island_cache", {})
        if len(cache) > 256:
            # bound the id-keyed memo (engines that re-plan per
            # execution would otherwise leak whole plan trees);
            # re-splitting is cheap and capacity ids are base-free
            cache.clear()
            self.__dict__.get("_island_alias", {}).clear()
        hit = cache.get(id(plan))
        if hit is not None:
            return hit[0], hit[1], hit[3]
        children: List[PlanNode] = []
        child_slots: Dict[int, int] = {}

        alias = self.__dict__.setdefault("_island_alias", {})

        def rec(n: PlanNode, is_root: bool) -> PlanNode:
            if n is None:
                return n
            if not is_root and isinstance(n, self._SPLIT_NODES):
                if id(n) in child_slots:
                    slot = child_slots[id(n)]
                else:
                    slot = len(children)
                    children.append(n)
                    child_slots[id(n)] = slot
                return PageInputNode(n.output_names, n.output_types,
                                     slot=slot)
            kids = n.children()
            if not kids:
                return n
            if isinstance(n, JoinNode):
                m = dataclasses.replace(
                    n, probe=rec(n.probe, False),
                    build=rec(n.build, False))
            elif isinstance(n, UnionAllNode):
                m = dataclasses.replace(
                    n, sources=tuple(rec(s, False) for s in n.sources))
            else:
                m = dataclasses.replace(n, source=rec(kids[0], False))
            # copy -> original identity, so EXPLAIN ANALYZE can project
            # per-island stats back onto the user-facing plan tree
            alias[id(m)] = id(n)
            return m

        mini = rec(plan, True)
        # stable per-island stats-id base: islands build in a
        # deterministic traversal order, so len(cache) is reproducible
        base = (len(cache) + 1) * 1_000_000
        cache[id(plan)] = (mini, children, plan, base)  # keep plan alive
        return mini, children, base

    def _execute_islands(self, plan: PlanNode) -> Page:
        """Optimistically dispatch the WHOLE island chain without
        syncing any island's counters, then resolve them all once: K
        islands cost one results-wait instead of K device round trips
        (on the remote-TPU tunnel each sync is a full network round
        trip — this is the per-island dispatch overhead the round-4
        profile flagged). If any island's capacities grew (first
        execution of a novel plan; learned caps persist), the chain
        re-runs with the grown capacities."""
        profile = self.session["collect_stats"]
        self.last_island_profile: List[dict] = []

        for _round in range(8):
            run_memo: Dict[int, Page] = {}
            pendings: List[dict] = []

            def run(node: PlanNode) -> Page:
                if id(node) in run_memo:
                    return run_memo[id(node)]
                self._check_deadline()
                mini, children, base = self._island_of(node)
                pages = [run(c) for c in children]
                self._island_inputs = pages
                self._stats_base = base
                if profile:
                    # EXPLAIN ANALYZE: block per island for true wall
                    # times — the per-operator profile fused execution
                    # cannot produce (profiling trades away the async
                    # overlap, production runs keep it)
                    import time as _t
                    t0 = _t.perf_counter()
                    out = self._execute_fused(mini)
                    jax.block_until_ready(out)   # Page is a pytree
                    entry = {
                        "root": type(node).__name__.replace("Node", ""),
                        "seconds": _t.perf_counter() - t0,
                        "rows": int(out.num_rows),
                        "memory_bytes": self.last_memory_estimate,
                    }
                    self.last_island_profile.append(entry)
                    _M_OP_WALL.observe(entry["seconds"],
                                       operator=entry["root"])
                    _M_OP_ROWS.observe(entry["rows"],
                                       operator=entry["root"])
                else:
                    out, pending = self._dispatch_fused(mini)
                    pendings.append(pending)
                run_memo[id(node)] = out
                return out

            try:
                result = run(plan)
            finally:
                self._stats_base = 0
            if profile:
                return result
            resolved = self._await_counters(pendings)
            # growth first across ALL islands: a truncated upstream
            # island feeds garbage downstream, so downstream's deferred
            # error lanes must not raise until a clean converged round
            grew = False
            for p, arr in zip(pendings, resolved):
                if self._grow_caps(p, arr):
                    grew = True
            if not grew:
                for p, arr in zip(pendings, resolved):
                    self._finish_counters(p, arr)
                return result
            if self.memory_pool is not None:
                # the failed round's buffers are unwound on re-run —
                # release its reservations so retries never double-count
                for p in pendings:
                    self.memory_pool.free(self.pool_query_id,
                                          p["pool_prev"])
        raise RuntimeError("island capacity retry did not converge")

    def _await_counters(self, pendings):
        """Deadline-aware single wait for the whole island chain's
        counters: the sync runs on a helper thread while the query's
        time budget stays enforced (the chain dispatches in
        milliseconds, so this wait is where the compute time actually
        passes)."""
        import numpy as _np
        if getattr(self, "_deadline", None) is None:
            return [_np.asarray(p["needed"]) for p in pendings]
        import threading
        box = {}
        done = threading.Event()

        def waiter():
            try:
                box["v"] = [_np.asarray(p["needed"]) for p in pendings]
            except BaseException as e:   # noqa: BLE001 — re-raised below
                box["e"] = e
            finally:
                done.set()

        from presto_tpu.utils.threads import spawn
        spawn("exec", "counter-waiter", waiter)
        while not done.wait(0.5):
            self._check_deadline()
        if "e" in box:
            raise box["e"]
        return box["v"]

    def _execute_tree(self, plan: PlanNode) -> Page:
        if self._use_islands(plan):
            return self._execute_islands(plan)
        return self._execute_fused(plan)

    # ---- learned-capacity persistence ---------------------------------
    # Overflow retries recompile the whole program; on the TPU a cold
    # compile through the remote service costs minutes. Persist the
    # converged capacity assignment per plan fingerprint so later
    # processes (bench children, worker restarts) lower at the right
    # capacities on the first attempt (the compiled-program analog of
    # the HBO row-count store).
    @staticmethod
    def _caps_store_path():
        import os
        p = os.environ.get("PRESTO_TPU_CAPS_CACHE")
        if p:
            return p
        return os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            ".caps_cache.json")

    def _plan_fingerprint(self, plan) -> str:
        import hashlib
        # salt with the connector identity/scale AND the scanned
        # tables' row counts: the same plan over SF0.01 and SF1 — or
        # over two different MemoryConnector datasets (sf=None) —
        # converges to different capacities
        sizes = []
        try:
            for t in sorted({n.table for n in self._walk_scans(plan)}):
                sizes.append((t, self.connector.table(t).num_rows))
        except Exception:   # noqa: BLE001 — salt is best-effort
            pass
        salt = (type(self.connector).__name__,
                getattr(self.connector, "sf", None), tuple(sizes))
        return hashlib.sha1(
            (repr(salt) + repr(plan)).encode()).hexdigest()[:24]

    @staticmethod
    def _walk_scans(plan):
        out = []

        def rec(n):
            if isinstance(n, TableScanNode):
                out.append(n)
            for c in n.children():
                if c is not None:
                    rec(c)
        rec(plan)
        return out

    def _plan_fingerprint_legacy(self, plan) -> str:
        import hashlib
        salt = (type(self.connector).__name__,
                getattr(self.connector, "sf", None))
        return hashlib.sha1(
            (repr(salt) + repr(plan)).encode()).hexdigest()[:24]

    def _load_caps(self, plan) -> Dict:
        import ast
        import json
        import os
        path = self._caps_store_path()
        if not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                data = json.load(f)
            raw = data.get(self._plan_fingerprint(plan))
            if raw is None:
                # migrate entries learned under the pre-row-count salt
                # (losing them would re-pay overflow-retry recompiles
                # through the remote TPU compile service)
                raw = data.get(self._plan_fingerprint_legacy(plan), {})
            out = {}
            for k, v in raw.items():
                try:
                    key = int(k)
                except ValueError:
                    # exchange capacities are keyed (node_id, "cap"/
                    # "chunk") — persisted via str(), recovered here
                    key = ast.literal_eval(k)
                    if not isinstance(key, tuple):
                        continue
                out[key] = int(v)
            return out
        except Exception:   # noqa: BLE001 — cache is best-effort
            return {}

    def _save_caps(self, plan, caps: Dict) -> None:
        import json
        import os
        if not caps:
            return
        key = self._plan_fingerprint(plan)
        entry = {str(k): int(v) for k, v in caps.items()}
        # in-memory dedup: streaming paths execute the same plan once
        # per lifespan/chunk — only the FIRST convergence (or a capacity
        # change) touches the file
        saved = self.__dict__.setdefault("_saved_caps", {})
        if saved.get(key) == entry:
            return
        saved[key] = entry
        path = self._caps_store_path()
        try:
            data = {}
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
            if data.get(key) == entry:
                return
            data[key] = entry
            if len(data) > 512:
                # bound the cache file: evict oldest-inserted entries
                # (insertion order == json order) — stale fingerprints
                # only cost a re-learn, never wrong results
                for k in list(data)[:len(data) - 512]:
                    data.pop(k, None)
            tmp = f"{path}.{os.getpid()}.tmp"
            # lint: disable=spill-chokepoint — caps cache, not a spill
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)           # atomic vs concurrent writers
        except Exception:   # noqa: BLE001 — cache is best-effort
            pass

    def _dispatch_fused(self, plan: PlanNode, pool_prev: int = 0):
        """Lower + dispatch ONE program without syncing its counters.
        Returns (out_page, pending) where `pending` resolves later via
        `_resolve_counters` — island execution defers every island's
        sync to the end of the chain, so K islands cost ONE wait for
        results instead of K tunnel round-trips."""
        caps: Dict = self._learned.setdefault(plan, None)
        if caps is None:
            caps = self._learned[plan] = self._load_caps(plan)
        # _lower is cheap (no tracing) and fills `caps` with its chosen
        # capacities, which completes the compilation cache key.
        fn, scans, watch = self._lower(plan, caps)
        if self.memory_pool is not None:
            # admission control: swap the PREVIOUS attempt's
            # reservation for this one (capacity-grow retries must
            # not double-count); islands of one query accumulate —
            # their pages stay device-resident
            self.memory_pool.free(self.pool_query_id, pool_prev)
            self.memory_pool.reserve(self.pool_query_id,
                                     self.last_memory_estimate)
            pool_prev = self.last_memory_estimate
        key = (plan, tuple(sorted(caps.items(), key=repr)),
               bool(self.session["collect_stats"]))
        entry = self._compiled.get(key)
        if entry is None:
            # stats_box is filled at this entry's first execution
            # (trace time fixes the node-id order for its lifetime).
            entry = (jax.jit(self._wrap(fn)), scans, watch, [])
            self._compiled[key] = entry
            self._note_compile(plan)
        fn, scans, watch, stats_box = entry
        pages = [self._fetch(s) for s in scans]
        self._stats_ids = []
        out, needed = fn(pages)
        if self._stats_ids and not stats_box:
            stats_box.extend(self._stats_ids)
        pending = {"plan": plan, "caps": caps, "watch": watch,
                   "needed": needed, "stats_box": stats_box,
                   "pool_prev": pool_prev}
        return out, pending

    def _grow_caps(self, pending, needed) -> bool:
        """Apply observed capacity needs; True = re-run required."""
        caps = pending["caps"]
        grew = False
        for nid, need in zip(pending["watch"], needed):
            need = int(need)
            if need > caps[nid]:
                caps[nid] = bucket_capacity(need)
                grew = True
        return grew

    def _anneal_caps(self, pending, needed) -> None:
        """Shrink learned capacities back toward the observed need.

        Growth is overflow-driven and monotone, so one oversized first
        guess (an exchange sized at twice its upstream capacity, a join
        fanout hint that never materializes) pins every later run to
        that bucket — and program cost scales with capacity, not rows.
        Each converged run updates a per-counter peak and re-buckets
        the cap at peak + 25% headroom; peaks are monotone, so the cap
        steps down to the true requirement and stays there instead of
        flip-flopping. An undershoot on later, larger data is always
        recoverable: every watched counter reports its unclamped need
        and rides the normal overflow-retry loop."""
        if not self.session["capacity_annealing_enabled"]:
            return
        caps = pending["caps"]
        peaks = self.__dict__.setdefault("_peak_needs", {}) \
            .setdefault(pending["plan"], {})
        for nid, need in zip(pending["watch"], needed):
            if isinstance(nid, int) and nid < 0:
                continue    # merge-join duplicate flags, not capacities
            peak = max(peaks.get(nid, 0), int(need))
            peaks[nid] = peak
            tgt = bucket_capacity(max(peak + (peak >> 2), 64))
            if tgt < caps[nid]:
                caps[nid] = tgt

    def _finish_counters(self, pending, needed) -> None:
        """Converged program: raise checked-arithmetic errors, record
        stats, persist the learned capacities."""
        from presto_tpu.expr import errors as _E
        watch = pending["watch"]
        _E.raise_for_mask(int(needed[len(watch)]))
        self._anneal_caps(pending, needed)
        stats_box = pending["stats_box"]
        if stats_box:
            stats = needed[len(watch) + 1:]
            node_map = getattr(self, "_node_map", {}) or {}
            for nid, r in zip(stats_box, stats):
                self.last_node_rows[nid] = int(r)
                entry = node_map.get(nid)
                op = (type(entry[0]).__name__.replace("Node", "")
                      if entry else "?")
                _M_OP_ROWS.observe(int(r), operator=op)
        self._save_caps(pending["plan"], pending["caps"])

    def _resolve_counters(self, pending) -> bool:
        """Sync + resolve one dispatched program (the single-program
        path): returns True when a re-run is required."""
        import numpy as _np
        needed = _np.asarray(pending["needed"])   # the sync point
        if self._grow_caps(pending, needed):
            return True
        self._finish_counters(pending, needed)
        return False

    def _execute_fused(self, plan: PlanNode) -> Page:
        # Learned capacities persist per plan: overflow retries and
        # merge-join duplicate fallbacks are paid once, not per execution.
        pool_prev = 0                 # this plan's live reservation
        for _attempt in range(8):
            out, pending = self._dispatch_fused(plan, pool_prev)
            pool_prev = pending["pool_prev"]
            if not self._resolve_counters(pending):
                return out
        raise RuntimeError("capacity retry loop did not converge")

    # ---- hooks overridden by the distributed executor ------------------
    def _prepare(self, plan: PlanNode) -> PlanNode:
        return plan

    def _execute_prepared(self, plan: PlanNode) -> Page:
        """Run an already-prepared plan (the distributed executor splits
        it into fragments here; EXPLAIN ANALYZE enters through this hook
        so it measures the real execution shape)."""
        return self._execute_tree(plan)

    def _note_compile(self, plan: PlanNode) -> None:
        """A new program was added to the compile cache (mesh executor
        counts fragment compiles here)."""

    def _wrap(self, fn: Callable) -> Callable:
        return fn

    def _page_rows(self, page: Page):
        return page.to_pylist()

    def _scan_rows(self, node) -> int:
        return self.connector.table(node.table).num_rows

    def _unique_ids(self, p: Page) -> jnp.ndarray:
        return jnp.arange(p.capacity, dtype=jnp.int64)

    def _finish_agg(self, node, out: Page) -> Page:
        return out

    def _finish_values(self, out: Page) -> Page:
        return out

    def _remote_input(self, node, scans):
        raise RuntimeError(
            "cut exchange in a single-process plan (fragments are only "
            "executed separately by the distributed executor)")

    def _remote_source(self, node, scans):
        raise RuntimeError(
            "RemoteSourceNode outside a protocol-driven task (the worker "
            "TaskManager binds remote splits before execution)")

    def _lower_exchange(self, node, nid, src, cap, caps, watch, _needed):
        """Single-process executor: an exchange is a no-op relabel (all
        rows already live in one page). The distributed executor overrides
        this with ICI collectives."""
        def out_fn(pages, node=node):
            p = src(pages)
            return Page(p.columns, p.num_rows, node.output_names)
        return out_fn, cap

    # ------------------------------------------------------------------
    def _fetch(self, s) -> Page:
        if isinstance(s, PageInputSpec):
            return self._island_inputs[s.slot]
        t = self.connector.table(s.table)
        return t.page(columns=list(s.columns), capacity=s.capacity)

    def _resolve_subqueries(self, plan: PlanNode) -> PlanNode:
        """Pre-execute scalar subqueries (uncorrelated), substituting
        literals (reference role: EnforceSingleRowOperator +
        coordinator-side subquery planning)."""
        from presto_tpu.sql.analyzer import Subquery

        def rewrite_expr(e: RowExpression) -> RowExpression:
            if isinstance(e, Subquery):
                page = self.execute(e.plan)
                rows = self._page_rows(page)
                if len(rows) != 1:
                    raise RuntimeError(
                        f"scalar subquery returned {len(rows)} rows")
                v = rows[0][0]
                if e.type.is_decimal and v is not None:
                    v = int(round(v * 10 ** e.type.scale))
                return Literal(v, e.type)
            if isinstance(e, Call):
                return dataclasses.replace(
                    e, args=tuple(rewrite_expr(a) for a in e.args))
            if isinstance(e, SpecialForm):
                return dataclasses.replace(
                    e, args=tuple(rewrite_expr(a) for a in e.args))
            return e

        def has_subquery(e) -> bool:
            if isinstance(e, Subquery):
                return True
            return any(has_subquery(c) for c in e.children())

        def rewrite(node: PlanNode) -> PlanNode:
            kids = tuple(rewrite(c) for c in node.children())
            repl = {}
            if isinstance(node, FilterNode):
                repl = {"source": kids[0]}
                if has_subquery(node.predicate):
                    repl["predicate"] = rewrite_expr(node.predicate)
            elif isinstance(node, ProjectNode):
                repl = {"source": kids[0]}
                if any(has_subquery(e) for e in node.expressions):
                    repl["expressions"] = tuple(
                        rewrite_expr(e) for e in node.expressions)
            elif isinstance(node, JoinNode):
                repl = {"probe": kids[0], "build": kids[1]}
                if node.filter is not None and has_subquery(node.filter):
                    repl["filter"] = rewrite_expr(node.filter)
            elif kids:
                names = [f.name for f in dataclasses.fields(node)]
                if "sources" in names:      # UnionAllNode and friends
                    repl = {"sources": kids}
                elif "source" in names:
                    repl = {"source": kids[0]}
            return dataclasses.replace(node, **repl) if repl else node

        return rewrite(plan)

    # ------------------------------------------------------------------
    def _lower(self, plan: PlanNode, caps: Dict[int, int]
               ) -> Tuple[Callable, List[ScanSpec], List[int]]:
        """Build (traced_fn(pages) -> (Page, needed[]), scan specs,
        watched node ids). Node ids are stable pre-order positions."""
        scans: List[ScanSpec] = []
        watch: List[int] = []
        counter = [0]
        # CAPACITY ids must be identical on every lowering of the same
        # (mini) plan — they key the persisted caps cache, and a base
        # offset would orphan learned TPU capacities across re-plans.
        # STATS ids additionally carry the island's base so row counts
        # from different islands of one query never collide.
        base = getattr(self, "_stats_base", 0)

        def node_id(_n) -> int:
            counter[0] += 1
            return counter[0]

        # Shared subtrees (mark joins reference the probe pipeline twice)
        # must lower and evaluate ONCE: memoize by node identity, and cache
        # each node's output per run so trace-time Python also runs once.
        memo: Dict[int, Tuple[Callable, int]] = {}
        run_cache: Dict[int, Page] = {}

        mem_bytes = [0]
        collect_stats = bool(self.session["collect_stats"])
        _node_rows: List = []
        if base == 0:
            self._node_map = {}
        # island mode (base > 0): maps ACCUMULATE across the query's
        # islands; execute() resets them per query

        def build(node: PlanNode):
            key = id(node)
            if key in memo:
                return memo[key]
            nid_stats = base + counter[0] + 1  # id build_inner assigns
            fn, cap = build_inner(node)
            mem_bytes[0] += cap * _row_bytes(node.output_types)
            self._node_map[nid_stats] = (node, cap)

            def cached(pages, fn=fn, key=key, nid=nid_stats):
                if key in run_cache:
                    return run_cache[key]
                out = fn(pages)
                if collect_stats:
                    _node_rows.append((nid, out.num_rows))
                run_cache[key] = out
                return out
            memo[key] = (cached, cap)
            return memo[key]

        def build_inner(node: PlanNode):
            nid = node_id(node)
            if isinstance(node, PageInputNode):
                idx = len(scans)
                scans.append(PageInputSpec(node.slot))
                cap = self._island_inputs[node.slot].capacity
                return (lambda pages: pages[idx]), cap
            if isinstance(node, TableScanNode):
                # Exact row count (generation is cached), not the planner
                # estimate — an under-estimated bucket would truncate rows.
                cap = caps.get(nid) or bucket_capacity(
                    self._scan_rows(node))
                idx = len(scans)
                scans.append(ScanSpec(node.table, node.columns, cap))
                return lambda pages: pages[idx], cap
            if isinstance(node, RemoteSourceNode):
                return self._remote_source(node, scans)
            if isinstance(node, ValuesNode):
                def values_fn(pages, node=node):
                    n = len(node.rows)
                    cols = tuple(
                        Column.from_numpy(
                            __import__("numpy").array(
                                [r[i] for r in node.rows]), t)
                        for i, t in enumerate(node.output_types))
                    return self._finish_values(
                        Page(cols, jnp.asarray(n, jnp.int32), ()))
                return values_fn, bucket_capacity(max(len(node.rows), 1))
            if isinstance(node, FilterNode):
                src, cap = build(node.source)
                pred = compile_expr(node.predicate)

                def filter_fn(pages):
                    p = src(pages)
                    c = pred(p)
                    return compact(p, ~c.nulls & c.values.astype(bool))
                return filter_fn, cap
            if isinstance(node, ProjectNode):
                src, cap = build(node.source)
                exprs = [compile_expr(e) for e in node.expressions]

                def project_fn(pages, node=node):
                    p = src(pages)
                    cols = tuple(ex(p) for ex in exprs)
                    return Page(cols, p.num_rows, node.output_names)
                return project_fn, cap
            if isinstance(node, AggregationNode):
                # Fuse the whole Filter/Project chain below the aggregation
                # into it: projections are row-wise column rewrites (row
                # count unchanged) and filters become a row mask consumed
                # by the aggregation — so the pipeline never compacts, and
                # never pays a sort. This is the reference's
                # ScanFilterAndProject -> HashAggregation pipeline fusion
                # (ScanFilterAndProjectOperator.java:67), taken further
                # because XLA fuses the mask into the reductions.
                steps = []            # bottom-up (kind, compiled payload)
                source = node.source
                while isinstance(source, (FilterNode, ProjectNode)):
                    if isinstance(source, FilterNode):
                        steps.append(("filter",
                                      compile_expr(source.predicate), None))
                    else:
                        steps.append(
                            ("project",
                             [compile_expr(e) for e in source.expressions],
                             source.output_names))
                    source = source.source
                steps.reverse()
                src, cap = build(source)
                hint = node.group_count_hint \
                    or self.session["group_count_hint"]
                out_cap = caps.get(nid) or min(
                    cap, bucket_capacity(hint))
                if not node.group_fields:
                    out_cap = 256
                caps[nid] = out_cap
                watch.append(nid)

                def agg_fn(pages, node=node, out_cap=out_cap, steps=steps):
                    p = src(pages)
                    mask = None
                    for kind, payload, names in steps:
                        if kind == "filter":
                            c = payload(p)
                            m = ~c.nulls & c.values.astype(bool)
                            mask = m if mask is None else (mask & m)
                        else:
                            cols = tuple(ex(p) for ex in payload)
                            p = Page(cols, p.num_rows, names)
                    out, true_groups = grouped_aggregate(
                        p, node.group_fields, node.aggs, out_cap,
                        row_mask=mask,
                        direct_max_bins=self.session[
                            "direct_agg_max_bins"])
                    _needed.append(true_groups)
                    return self._finish_agg(node, out)
                return agg_fn, out_cap
            if isinstance(node, JoinNode):
                psrc, pcap = build(node.probe)
                bsrc, bcap = build(node.build)
                if node.join_type in (JoinType.SEMI, JoinType.ANTI,
                                      JoinType.ANTI_EXISTS):
                    # Merge path: duplicates can't change a match flag,
                    # so no fallback is ever needed here.
                    def semi_fn(pages, node=node):
                        p = psrc(pages)
                        b = bsrc(pages)
                        out, _dup, _m = merge_join(
                            p, b, node.probe_keys, node.build_keys,
                            node.join_type.value)
                        if node.emit_flag:
                            # Protocol SemiJoinNode contract: keep every
                            # probe row, expose the flag column.
                            return Page(out.columns, out.num_rows,
                                        node.output_names)
                        flag = out.columns[-1]
                        filtered = compact(
                            Page(out.columns[:-1], out.num_rows,
                                 node.output_names),
                            flag.values.astype(bool))
                        return filtered
                    return semi_fn, pcap

                # Unique-build merge join first (two sorts + scans; the
                # TPU-fast path — TPC-H joins are FK joins). The dup
                # counter rides the generic overflow-retry loop under the
                # negated node id: any duplicate live build key re-lowers
                # onto the expansion hash_join below.
                use_merge = (bool(node.probe_keys)
                             and self.session["merge_join_enabled"]
                             and node.join_type in (JoinType.INNER,
                                                    JoinType.LEFT,
                                                    JoinType.FULL)
                             and caps.get(-nid, 0) == 0)
                if use_merge:
                    caps[-nid] = 0
                    watch.append(-nid)

                    def mjoin_fn(pages, node=node):
                        p = psrc(pages)
                        b = bsrc(pages)
                        residual = (compile_expr(node.filter)
                                    if node.filter is not None else None)
                        if (residual is not None
                                and node.join_type == JoinType.LEFT):
                            # Residual failure demotes a match to a
                            # null-extension (SQL outer-join ON clause):
                            # evaluate over the pre-filter join, then
                            # null out the build side where it fails.
                            out, dup, match = merge_join(
                                p, b, node.probe_keys, node.build_keys,
                                "left")
                            _needed.append(dup)
                            out = Page(out.columns, out.num_rows,
                                       node.output_names)
                            c = residual(out)
                            ok = match & ~c.nulls & c.values.astype(bool)
                            cols = list(out.columns[:len(p.columns)])
                            for bc in out.columns[len(p.columns):]:
                                sent = jnp.asarray(
                                    bc.type.null_sentinel(),
                                    dtype=bc.values.dtype)
                                cols.append(Column(
                                    jnp.where(ok, bc.values, sent),
                                    jnp.where(ok, bc.nulls, True),
                                    bc.type, bc.dictionary))
                            return Page(tuple(cols), out.num_rows,
                                        node.output_names)
                        out, dup, _match = merge_join(
                            p, b, node.probe_keys, node.build_keys,
                            node.join_type.value)
                        _needed.append(dup)
                        out = Page(out.columns, out.num_rows,
                                   node.output_names)
                        if node.filter is not None:
                            if node.join_type == JoinType.FULL:
                                raise NotImplementedError(
                                    "residual filter on full outer join")
                            c = compile_expr(node.filter)(out)
                            out = compact(out,
                                          ~c.nulls & c.values.astype(bool))
                        return out
                    # FULL appends the unmatched build rows: capacity grows
                    out_cap = pcap + (bcap if node.join_type
                                      == JoinType.FULL else 0)
                    return mjoin_fn, out_cap

                fan = max(node.fanout_hint, 1.0)
                out_cap = caps.get(nid) or bucket_capacity(
                    min(int(pcap * fan), 2**26))
                caps[nid] = out_cap
                watch.append(nid)

                if node.join_type == JoinType.FULL:
                    raise NotImplementedError(
                        "full outer join with duplicate build keys (the "
                        "expansion path has no full-outer form yet)")

                def join_fn(pages, node=node, out_cap=out_cap):
                    p = psrc(pages)
                    b = bsrc(pages)
                    out, total = hash_join(
                        p, b, node.probe_keys, node.build_keys, out_cap,
                        node.join_type.value)
                    _needed.append(total)
                    out = Page(out.columns, out.num_rows,
                               node.output_names)
                    if node.filter is not None:
                        c = compile_expr(node.filter)(out)
                        if node.join_type == JoinType.LEFT:
                            raise NotImplementedError(
                                "residual ON filter on a LEFT join whose "
                                "build side has duplicate keys (the "
                                "expansion fallback cannot null-extend "
                                "per probe row yet; build-side-only "
                                "conditions are pre-filtered by the "
                                "planner and never reach here)")
                        out = compact(out,
                                      ~c.nulls & c.values.astype(bool))
                    return out
                return join_fn, out_cap
            if isinstance(node, GroupIdNode):
                src, cap = build(node.source)
                nsets = len(node.grouping_sets)
                out_cap = nsets * cap
                # membership[s, c]: does column c survive in set s?
                # (non-key columns always do)
                member_np = __import__("numpy").ones(
                    (nsets, node.arity - 1), dtype=bool)
                for s, keep in enumerate(node.grouping_sets):
                    for c in node.key_fields:
                        member_np[s, c] = c in keep

                def gid_fn(pages, node=node, nsets=nsets,
                           member_np=member_np):
                    p = src(pages)
                    n = p.num_rows
                    ocap = nsets * p.capacity
                    r = jnp.arange(ocap, dtype=jnp.int32)
                    n1 = jnp.maximum(n, 1)
                    set_id = jnp.clip(r // n1, 0, nsets - 1)
                    srci = r - set_id * n1
                    valid = r < nsets * n
                    member = jnp.asarray(member_np)
                    cols = []
                    for ci, c in enumerate(p.columns):
                        keep = member[:, ci][set_id] & valid
                        vals = jnp.take(c.values, srci, mode="clip")
                        nulls = jnp.take(c.nulls, srci, mode="clip")
                        sent = jnp.asarray(c.type.null_sentinel(),
                                           dtype=vals.dtype)
                        cols.append(Column(
                            jnp.where(keep, vals, sent),
                            jnp.where(keep, nulls, True),
                            c.type, c.dictionary))
                    gsent = jnp.asarray(
                        node.output_types[-1].null_sentinel(), jnp.int64)
                    gid = Column(
                        jnp.where(valid, set_id.astype(jnp.int64), gsent),
                        ~valid, node.output_types[-1], None)
                    return Page(tuple(cols) + (gid,),
                                (nsets * n).astype(jnp.int32),
                                node.output_names)
                return gid_fn, out_cap
            if isinstance(node, AssignUniqueIdNode):
                src, cap = build(node.source)

                def rowid_fn(pages, node=node):
                    p = src(pages)
                    ids = self._unique_ids(p)
                    col = Column(ids, ~p.row_valid(),
                                 node.output_types[-1], None)
                    return Page(p.columns + (col,), p.num_rows,
                                node.output_names)
                return rowid_fn, cap
            if isinstance(node, TableWriterNode):
                src, cap = build(node.source)

                def writer_fn(pages, node=node):
                    # the jit pipeline produces the page; the sink write
                    # is a HOST side-effect (ConnectorPageSink role) —
                    # legal here because jit tracing happens once and the
                    # actual write runs per execution via io_callback-free
                    # host interpretation: the executor runs this whole
                    # closure eagerly when the plan root is a writer (see
                    # execute()); inside jit it is rejected below.
                    raise NotImplementedError(
                        "TableWriterNode inside a jit fragment — the "
                        "engine executes writer roots host-side")
                return writer_fn, cap
            if isinstance(node, MarkDistinctNode):
                src, cap = build(node.source)

                def mark_fn(pages, node=node):
                    from presto_tpu.ops.mark_distinct import mark_distinct
                    p = src(pages)
                    out = mark_distinct(p, node.key_fields,
                                        node.output_names[-1])
                    return Page(out.columns, out.num_rows,
                                node.output_names)
                return mark_fn, cap
            if isinstance(node, UnionAllNode):
                built = [build(s) for s in node.sources]
                out_cap = sum(c for _f, c in built)

                def union_fn(pages, node=node, built=built,
                             out_cap=out_cap):
                    from presto_tpu.data.column import merge_string_dicts
                    ps = [f(pages) for f, _c in built]
                    cols = []
                    for ci, t in enumerate(node.output_types):
                        branch = [p.columns[ci] for p in ps]
                        dicts = [c.dictionary for c in branch]
                        d0 = dicts[0]
                        if t.is_string and any(d is not d0
                                               for d in dicts):
                            # per-source dictionaries differ: merge at
                            # trace time (dicts are static aux), remap
                            # codes with constant tables
                            union_d, remaps = merge_string_dicts(dicts)
                            vals = jnp.concatenate([
                                (jnp.take(jnp.asarray(r), c.values,
                                          mode="clip") if len(r)
                                 else c.values)
                                for c, r in zip(branch, remaps)])
                            d0 = union_d
                        else:
                            vals = jnp.concatenate(
                                [c.values for c in branch])
                        nulls = jnp.concatenate(
                            [c.nulls for c in branch])
                        cols.append(Column(vals, nulls, t, d0))
                    # each source's valid rows sit at its own capacity
                    # offset; declare everything in-range, then compact
                    # squeezes the survivors dense and sets num_rows
                    keep = jnp.concatenate([p.row_valid() for p in ps])
                    out = Page(tuple(cols),
                               jnp.asarray(out_cap, jnp.int32),
                               node.output_names)
                    return compact(out, keep)
                return union_fn, out_cap
            if isinstance(node, UnnestNode):
                src, cap = build(node.source)
                fan = max(node.fanout_hint, 1.0)
                out_cap = caps.get(nid) or bucket_capacity(
                    min(int(cap * fan), 2**26))
                caps[nid] = out_cap
                watch.append(nid)

                def unnest_fn(pages, node=node, out_cap=out_cap):
                    from presto_tpu.ops.unnest import unnest_page
                    p = src(pages)
                    out, total = unnest_page(
                        p, node.replicate_fields, node.unnest_fields,
                        out_cap, node.with_ordinality, node.output_names)
                    _needed.append(total)
                    return out
                return unnest_fn, out_cap
            if isinstance(node, WindowNode):
                src, cap = build(node.source)

                def window_fn(pages, node=node):
                    from presto_tpu.ops.window import window_page
                    p = src(pages)
                    out = window_page(p, node.partition_fields,
                                      node.order_keys, node.specs)
                    return Page(out.columns, out.num_rows,
                                node.output_names)
                return window_fn, cap
            if isinstance(node, SortNode):
                src, cap = build(node.source)
                return (lambda pages: sort_page(src(pages), node.keys)), cap
            if isinstance(node, TopNNode):
                src, cap = build(node.source)
                return (lambda pages: top_n(src(pages), node.keys,
                                            node.count)), cap
            if isinstance(node, LimitNode):
                src, cap = build(node.source)
                return (lambda pages: limit_page(src(pages),
                                                 node.count)), cap
            if isinstance(node, ExchangeNode):
                if node.source is None:      # cut: reads another fragment
                    src, cap = self._remote_input(node, scans)
                else:
                    src, cap = build(node.source)
                return self._lower_exchange(node, nid, src, cap, caps,
                                            watch, _needed)
            if isinstance(node, OutputNode):
                src, cap = build(node.source)

                def out_fn(pages, node=node):
                    p = src(pages)
                    return Page(p.columns, p.num_rows, node.output_names)
                return out_fn, cap
            raise NotImplementedError(f"lowering {type(node).__name__}")

        _needed: List = []
        root, _cap = build(plan)
        self.last_memory_estimate = mem_bytes[0]
        if self.memory_limit_bytes is not None \
                and mem_bytes[0] > self.memory_limit_bytes:
            raise MemoryLimitExceeded(mem_bytes[0],
                                      self.memory_limit_bytes)

        def run(pages):
            from presto_tpu.expr import errors as E
            _needed.clear()
            run_cache.clear()
            _node_rows.clear()
            with E.collecting() as coll:
                out = root(pages)
                err = coll.combined()
            # The checked-arithmetic error lane rides right after the
            # capacity counters, then stats, in one stacked array (a
            # single host transfer); the stats node-id order is fixed at
            # trace time.
            self._stats_ids = [nid for nid, _ in _node_rows]
            extras = [r for _nid, r in _node_rows]
            all_counters = list(_needed) + [err] + extras
            counters = jnp.stack(
                [jnp.asarray(n, jnp.int64) for n in all_counters])
            return out, counters

        return run, scans, watch
