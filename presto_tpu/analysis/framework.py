"""Engine-aware static analysis framework: rule registry, package
walker, findings, suppressions.

The engine's architectural invariants (single RPC chokepoint,
exchange-only page consumption, spool-only task output, shuffle-only
collectives, thread/lock discipline) used to live in four ad-hoc regex
tests. This package expresses them as declarative *rules* over one
shared source index so they compose: `python -m presto_tpu.analysis`
runs the whole set from the command line (nonzero exit on findings),
and tests/test_analysis.py runs the same set as a tier-1 gate.

Core objects:

  SourceFile  one parsed file: text, line table, lazy AST
  Package     the walked file set (a real tree or in-memory sources —
              the honesty tests plant violations through the latter)
  Rule        `run(package) -> findings`; registered by name
  Finding     rule + file:line + message, renderable or JSON

Suppressions: a ``# lint: disable=<rule>[,<rule>...]`` comment at the
end of a line suppresses findings for those rules on that line; on a
line of its own it suppresses the following line. Every suppression
must actually suppress something — unused ones are reported as
`unused-suppression` findings, so stale exemptions fail the build the
same way violations do."""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: the presto_tpu package root this module ships inside
PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation anchored to a file:line."""

    rule: str
    path: str          # package-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One source file in the index; AST parsed lazily and cached."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[SyntaxError] = None

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree  # noqa: B018 — force the lazy parse
        return self._parse_error

    def line_at(self, offset: int) -> int:
        """1-based line number of a character offset (regex rules)."""
        return self.text.count("\n", 0, offset) + 1

    def lines(self) -> List[str]:
        return self.text.splitlines()


class Package:
    """The analyzed file set, keyed by package-relative posix path
    (e.g. ``presto_tpu/server/http.py``)."""

    def __init__(self, files: Dict[str, SourceFile]):
        self.files = files

    @classmethod
    def from_path(cls, root: Optional[pathlib.Path] = None) -> "Package":
        root = pathlib.Path(root) if root is not None else PKG_ROOT
        base = root.parent
        files: Dict[str, SourceFile] = {}
        paths = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for path in paths:
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(base).as_posix()
            files[rel] = SourceFile(rel, path.read_text())
        return cls(files)

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Package":
        """In-memory package — the honesty tests plant violation
        snippets here without touching the real tree."""
        return cls({rel: SourceFile(rel, text)
                    for rel, text in sources.items()})

    def get(self, relpath: str) -> Optional[SourceFile]:
        return self.files.get(relpath)

    def walk(self, prefix: str = "") -> Iterable[SourceFile]:
        for rel in sorted(self.files):
            if rel.startswith(prefix):
                yield self.files[rel]


class Rule:
    """Base rule: subclasses set `name`/`description` and implement
    `run`. Registration is by module-level `register()` call."""

    name: str = ""
    description: str = ""

    def run(self, pkg: Package) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, f: SourceFile, line: int, message: str) -> Finding:
        return Finding(self.name, f.relpath, line, message)


#: name -> rule instance (insertion-ordered: report order is stable)
_RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if not rule.name:
        raise ValueError(f"rule {rule!r} has no name")
    if rule.name in _RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _RULES[rule.name] = rule
    return rule


def all_rules() -> List[Rule]:
    # import for side effect: the engine rule set registers itself
    from presto_tpu.analysis import rules  # noqa: F401
    return list(_RULES.values())


def get_rule(name: str) -> Rule:
    all_rules()
    if name not in _RULES:
        raise KeyError(
            f"unknown rule {name!r}; known: {sorted(_RULES)}")
    return _RULES[name]


# ---------------------------------------------------------- suppressions
@dataclasses.dataclass
class Suppression:
    path: str
    line: int            # the line whose findings it suppresses
    comment_line: int    # where the comment itself sits (for reporting)
    rules: frozenset
    used: bool = False


def collect_suppressions(pkg: Package) -> List[Suppression]:
    out: List[Suppression] = []
    for f in pkg.walk():
        for i, line in enumerate(f.lines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            names = frozenset(
                s.strip() for s in m.group(1).split(",") if s.strip())
            # a comment-only line shields the NEXT line; a trailing
            # comment shields its own
            target = i + 1 if line.strip().startswith("#") else i
            out.append(Suppression(f.relpath, target, i, names))
    return out


# --------------------------------------------------------------- analyze
def analyze(pkg: Package,
            rules: Optional[Sequence[Rule]] = None
            ) -> List[Finding]:
    """Run rules over the package, apply suppressions, report unused
    suppressions and unparseable files. The returned list is the
    complete verdict: empty == clean."""
    rules = list(rules) if rules is not None else all_rules()
    raw: List[Finding] = []
    for f in pkg.walk():
        if f.parse_error is not None:
            raw.append(Finding(
                "parse-error", f.relpath,
                f.parse_error.lineno or 1,
                f"file does not parse: {f.parse_error.msg}"))
    for rule in rules:
        raw.extend(rule.run(pkg))

    sups = collect_suppressions(pkg)
    by_site: Dict[Tuple[str, int], List[Suppression]] = {}
    for s in sups:
        by_site.setdefault((s.path, s.line), []).append(s)

    kept: List[Finding] = []
    for fd in raw:
        suppressed = False
        for s in by_site.get((fd.path, fd.line), ()):
            if fd.rule in s.rules:
                s.used = True
                suppressed = True
        if not suppressed:
            kept.append(fd)
    for s in sups:
        if not s.used:
            kept.append(Finding(
                "unused-suppression", s.path, s.comment_line,
                f"suppression for {', '.join(sorted(s.rules))} never "
                f"matched a finding — remove it or fix the rule name"))
    return sorted(kept, key=lambda f: (f.path, f.line, f.rule))


# ------------------------------------------------------- shared helpers
def regex_findings(rule: Rule, pkg: Package,
                   patterns: Sequence[re.Pattern],
                   message: str,
                   allowed: Sequence[str] = (),
                   prefixes: Sequence[str] = ("presto_tpu/",)
                   ) -> List[Finding]:
    """Scan every file under `prefixes` (minus `allowed`) for any of
    `patterns`; one finding per match, message suffixed with the
    matched text."""
    out: List[Finding] = []
    allowed_set = set(allowed)
    for f in pkg.walk():
        if f.relpath in allowed_set:
            continue
        if not any(f.relpath.startswith(p) for p in prefixes):
            continue
        for pat in patterns:
            for m in pat.finditer(f.text):
                out.append(rule.finding(
                    f, f.line_at(m.start()),
                    f"{message} (matched {m.group(0)!r})"))
    return out


def honesty_finding(rule: Rule, pkg: Package, relpath: str,
                    patterns: Sequence[re.Pattern],
                    what: str) -> List[Finding]:
    """Allowlist-honesty check: the exempted file must itself still
    match the policed patterns, else the rule has gone vacuous (the
    implementation moved and the exemption is stale)."""
    f = pkg.get(relpath)
    if f is None:
        return [Finding(rule.name, relpath, 1,
                        f"allowlisted file is missing — {what} moved? "
                        f"update the rule's allowlist")]
    if not any(p.search(f.text) for p in patterns):
        return [Finding(rule.name, relpath, 1,
                        f"allowlist gone vacuous: this file no longer "
                        f"matches the patterns the rule polices — "
                        f"{what} moved? update the rule")]
    return []
