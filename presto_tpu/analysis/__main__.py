import sys

from presto_tpu.analysis import main

sys.exit(main())
