"""Engine-aware static analysis + dynamic sanitizers.

`python -m presto_tpu.analysis` runs the full rule set over the
package and exits nonzero on findings (including unused suppressions).
See framework.py for the rule/suppression machinery, rules.py for the
engine rule catalog, locksan.py for the lock-order sanitizer."""

from presto_tpu.analysis.framework import (
    Finding, Package, Rule, all_rules, analyze, get_rule, register,
)

__all__ = ["Finding", "Package", "Rule", "all_rules", "analyze",
           "get_rule", "register", "main"]


def main(argv=None) -> int:
    """CLI entry point (also invoked in-process by the tier-1 test)."""
    import argparse
    import json as _json
    import pathlib

    p = argparse.ArgumentParser(
        prog="python -m presto_tpu.analysis",
        description="Run the engine's static-analysis rule set.")
    p.add_argument("paths", nargs="*",
                   help="files or directories to analyze "
                        "(default: the installed presto_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.description}")
        return 0
    if args.rule:
        rules = [get_rule(name) for name in args.rule]

    if args.paths:
        files = {}
        for raw in args.paths:
            sub = Package.from_path(pathlib.Path(raw))
            files.update(sub.files)
        pkg = Package(files)
    else:
        pkg = Package.from_path()

    findings = analyze(pkg, rules)
    if args.as_json:
        print(_json.dumps({
            "rules": [r.name for r in rules],
            "files": len(pkg.files),
            "findings": [f.to_dict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s) across {len(pkg.files)} "
              f"file(s), {len(rules)} rule(s)")
    return 1 if findings else 0
