"""The engine rule set: every architectural invariant as one
declarative rule.

Four of these re-express the ad-hoc chokepoint guards that used to be
standalone regex tests (rpc/exchange/spool/mesh); the rest are the
concurrency-discipline rules the threaded engine grew to need. Each
rule carries its own allowlist-honesty check where applicable: if the
exempted implementation file stops matching the policed idiom, the
rule reports itself vacuous instead of silently passing forever.

Regex rules scan raw text (docstrings included — prose must not spell
the policed idiom with a literal call paren); AST rules skip strings
by construction."""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from presto_tpu.analysis.framework import (
    PKG_ROOT, Finding, Package, Rule, SourceFile, honesty_finding,
    regex_findings, register,
)

# =====================================================================
# 1. rpc-chokepoint — protocol/transport.py is the only place that
#    opens an outbound HTTP connection (urlopen OR http.client dials)
# =====================================================================

_URLOPEN_DIRECT = re.compile(r"urllib\s*\.\s*request\s*\.\s*urlopen")
_URLOPEN_IMPORT = re.compile(
    r"from\s+urllib\s*\.\s*request\s+import\s+[^\n]*\burlopen\b")
#: dialing http.client directly (the pooled transport's own idiom)
#: bypasses the pool, retry classification, breakers, fault injection
#: AND the header providers that sign internal requests
_HTTPCONN_DIRECT = re.compile(
    r"http\s*\.\s*client\s*\.\s*HTTPS?Connection\s*\(")
_HTTPCONN_IMPORT = re.compile(
    r"from\s+http\s*\.\s*client\s+import\s+[^\n]*"
    r"\bHTTPS?Connection\b")

_TRANSPORT = "presto_tpu/protocol/transport.py"


class RpcChokepointRule(Rule):
    name = "rpc-chokepoint"
    description = (
        "every HTTP request rides protocol/transport.HttpClient so "
        "retry policies, error classification, circuit breakers, "
        "keep-alive pooling, request signing and fault injection "
        "apply uniformly; a raw urlopen or http.client dial anywhere "
        "else opts that call site out of all of it")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg,
            (_URLOPEN_DIRECT, _URLOPEN_IMPORT,
             _HTTPCONN_DIRECT, _HTTPCONN_IMPORT),
            "raw HTTP dial outside protocol/transport.py — route this "
            "through transport.HttpClient",
            allowed=(_TRANSPORT,))
        # honesty: the allowlisted file must still contain the policed
        # dial idiom (today the pooled HTTPConnection transport; the
        # urlopen form also counts so the check spans both eras)
        out.extend(honesty_finding(
            self, pkg, _TRANSPORT,
            (_HTTPCONN_DIRECT, _URLOPEN_DIRECT),
            "the pooled-connection transport"))
        return out


register(RpcChokepointRule())

# =====================================================================
# 2. exchange-chokepoint — exchange.py/exchange_client.py are the only
#    consumers of /results/ page GETs
# =====================================================================

#: an f-string literal interpolating into a /results/ path = building a
#: results GET/DELETE url client-side (the server's route regexes use
#: groups, not interpolation, so they never match)
_RESULTS_URL = re.compile(r"""f["'][^"'\n]*/results/\{""")
_PAGESTREAM = re.compile(r"\bPageStream\s*\(")

_EXCHANGE_ALLOWED = ("presto_tpu/protocol/exchange.py",
                     "presto_tpu/protocol/exchange_client.py")


class ExchangeChokepointRule(Rule):
    name = "exchange-chokepoint"
    description = (
        "only protocol/exchange.py + exchange_client.py may consume "
        "/results/ page streams; any other consumer bypasses the "
        "bounded exchange buffer, truncation-before-ack validation "
        "and the spool fallback")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_RESULTS_URL, _PAGESTREAM),
            "page-protocol consumption outside protocol/exchange*.py — "
            "route through exchange.ExchangeClient/stream_pages",
            allowed=_EXCHANGE_ALLOWED)
        out.extend(honesty_finding(
            self, pkg, "presto_tpu/protocol/exchange_client.py",
            (_RESULTS_URL,), "results-url construction"))
        out.extend(honesty_finding(
            self, pkg, "presto_tpu/protocol/exchange.py",
            (_PAGESTREAM,), "PageStream construction"))
        return out


register(ExchangeChokepointRule())

# =====================================================================
# 3. spool-chokepoint — spool/ is the single task-output file writer
#    in the distributed-execution layers (server/, protocol/)
# =====================================================================

_WRITE_PATTERNS = (
    re.compile(r"""open\s*\([^)\n]*,\s*["'][wax]b?\+?["']"""),
    re.compile(r"tempfile\s*\.\s*(mkstemp|mkdtemp|NamedTemporaryFile|"
               r"TemporaryFile|TemporaryDirectory)"),
    re.compile(r"from\s+tempfile\s+import\b"),
    re.compile(r"os\s*\.\s*(open|mkstemp)\s*\("),
)


class SpoolChokepointRule(Rule):
    name = "spool-chokepoint"
    description = (
        "task output in server/ and protocol/ must go through "
        "presto_tpu/spool (TaskSpoolWriter/FrameFile) so atomic "
        "commit manifests, checksums and GC cover every byte; exec/ "
        "keeps its own node-local spill files and is out of scope")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, _WRITE_PATTERNS,
            "file-writing call site in a distributed-execution layer — "
            "task output must ride presto_tpu/spool",
            prefixes=("presto_tpu/server/", "presto_tpu/protocol/"))
        # honesty: the spool package must itself still match the write
        # idioms this rule polices
        spool = [f for f in pkg.walk("presto_tpu/spool/")]
        if spool and not any(
                p.search(f.text) for f in spool for p in _WRITE_PATTERNS):
            out.append(Finding(
                self.name, "presto_tpu/spool/files.py", 1,
                "presto_tpu/spool no longer matches the write patterns "
                "this rule scans for — update the rule's patterns"))
        return out


register(SpoolChokepointRule())

# =====================================================================
# 4. mesh-chokepoint — parallel/shuffle.py is the single ICI
#    collective call site
# =====================================================================

_COLLECTIVE_CALL = re.compile(
    r"\blax\s*\.\s*(all_to_all|all_gather)\s*\(")
_COLLECTIVE_IMPORT = re.compile(
    r"from\s+jax\s*\.\s*lax\s+import\s+[^\n]*\b(all_to_all|all_gather)\b")

_SHUFFLE = "presto_tpu/parallel/shuffle.py"


class MeshChokepointRule(Rule):
    name = "mesh-chokepoint"
    description = (
        "every cross-device exchange rides parallel/shuffle.py's "
        "page-level helpers (repartition_page/all_gather_page) — the "
        "packed same-dtype layout, overflow-retry counters and wire-"
        "byte metrics all live there")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_COLLECTIVE_CALL, _COLLECTIVE_IMPORT),
            "raw ICI collective outside parallel/shuffle.py — exchange "
            "pages via repartition_page/all_gather_page",
            allowed=(_SHUFFLE,))
        shuffle = pkg.get(_SHUFFLE)
        if shuffle is None:
            out.append(Finding(
                self.name, _SHUFFLE, 1,
                "allowlisted file is missing — the collective "
                "chokepoint moved? update the rule"))
        else:
            kinds = {m.group(1)
                     for m in _COLLECTIVE_CALL.finditer(shuffle.text)}
            if kinds != {"all_to_all", "all_gather"}:
                out.append(Finding(
                    self.name, _SHUFFLE, 1,
                    f"allowlist gone vacuous: shuffle.py calls "
                    f"{sorted(kinds) or 'no collectives'}, expected "
                    f"both all_to_all and all_gather — update the rule"))
        return out


register(MeshChokepointRule())

# =====================================================================
# 5. metric-name-grammar — every registered metric name is Prometheus-
#    valid and registered from exactly one call site
# =====================================================================

#: registration call with a literal first argument — matches the bare
#: helpers, aliased imports (_counter, _obs_gauge, ...) and registry
#: methods (REGISTRY.counter)
_METRIC_CALL = re.compile(
    r"\b[A-Za-z_.]*(?:counter|gauge|histogram)\s*\(\s*[\"']"
    r"([^\"']+)[\"']")

#: the registry module itself holds class definitions and docstring
#: examples, not registrations
_METRIC_EXCLUDED = ("presto_tpu/obs/metrics.py",)


class MetricNameRule(Rule):
    name = "metric-name-grammar"
    description = (
        "every metric name registered anywhere in the package must "
        "match the Prometheus grammar and appear at exactly one call "
        "site — an invalid name corrupts /v1/metrics at scrape time, "
        "a duplicate aliases two meanings onto one series")

    def run(self, pkg: Package) -> Iterable[Finding]:
        from presto_tpu.obs.metrics import METRIC_NAME_RE
        sites: Dict[str, List[Tuple[SourceFile, int]]] = {}
        for f in pkg.walk("presto_tpu/"):
            if f.relpath in _METRIC_EXCLUDED:
                continue
            for m in _METRIC_CALL.finditer(f.text):
                sites.setdefault(m.group(1), []).append(
                    (f, f.line_at(m.start())))
        out: List[Finding] = []
        for mname, where in sorted(sites.items()):
            if not METRIC_NAME_RE.match(mname):
                for f, line in where:
                    out.append(self.finding(
                        f, line,
                        f"invalid Prometheus metric name {mname!r}"))
            if len(where) > 1:
                locs = ", ".join(f"{f.relpath}:{ln}" for f, ln in where)
                f, line = where[1]
                out.append(self.finding(
                    f, line,
                    f"metric {mname!r} registered from {len(where)} "
                    f"call sites ({locs}) — move it to one module-"
                    f"level registration"))
        return out


register(MetricNameRule())

# =====================================================================
# 6. thread-discipline — every spawned thread is attributable
# =====================================================================

#: the one sanctioned spawn helper (names presto-tpu-<role>-<purpose>)
_THREADS_HELPER = "presto_tpu/utils/threads.py"


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread" \
            and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        return True
    return isinstance(fn, ast.Name) and fn.id == "Thread"


class ThreadDisciplineRule(Rule):
    name = "thread-discipline"
    description = (
        "every threading.Thread must be constructed with both name= "
        "and daemon= (or spawned via utils/threads.spawn, which names "
        "it presto-tpu-<role>-<purpose>) so stuck-thread dumps are "
        "attributable and shutdown behavior is uniform")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for f in pkg.walk("presto_tpu/"):
            if f.relpath == _THREADS_HELPER or f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call)
                        and _is_thread_ctor(node)):
                    continue
                kw = {k.arg for k in node.keywords}
                missing = [k for k in ("name", "daemon") if k not in kw]
                if missing:
                    out.append(self.finding(
                        f, node.lineno,
                        f"thread spawned without {'/'.join(missing)} — "
                        f"use presto_tpu.utils.threads.spawn (names it "
                        f"presto-tpu-<role>-<purpose>) or pass both"))
        return out


register(ThreadDisciplineRule())

# =====================================================================
# 7. no-blocking-under-lock — no sleeps / transport calls / thread
#    joins lexically inside a `with <lock>:` body
# =====================================================================

#: a with-item whose terminal name segment looks like a mutex or
#: condition variable
_LOCKISH = re.compile(
    r"(?i)(?:^|_)(?:lock|mutex|cond|condition)$|lock$|^state_change$")

#: method names that issue a network RPC (the transport chokepoint's
#: public surface + the announcer's one-shot)
_RPC_METHODS = {"request", "get_json", "post", "urlopen",
                "announce_once"}


def _terminal_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _lockish(expr: ast.AST) -> bool:
    n = _terminal_name(expr)
    return n is not None and bool(_LOCKISH.search(n))


def _is_thread_join(call: ast.Call) -> bool:
    """`x.join()` / `x.join(5)` / `x.join(timeout=...)` — a string
    join always takes a non-numeric positional iterable, so those
    shapes are thread (or process) joins."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "join"):
        return False
    if call.keywords:
        return all(k.arg == "timeout" for k in call.keywords) \
            and not call.args
    if not call.args:
        return True
    return len(call.args) == 1 \
        and isinstance(call.args[0], ast.Constant) \
        and isinstance(call.args[0].value, (int, float))


def _blocking_reason(call: ast.Call,
                     lock_expr: ast.AST) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            return "time.sleep under a lock"
        if fn.attr in _RPC_METHODS:
            return f".{fn.attr}() RPC under a lock"
        if fn.attr == "wait" \
                and ast.dump(fn.value) != ast.dump(lock_expr):
            return (".wait() on a different object than the held "
                    "lock (a condition wait only releases its own "
                    "lock)")
    if _is_thread_join(call):
        return ".join() under a lock"
    return None


class _UnderLockVisitor(ast.NodeVisitor):
    """Walk a with-body without descending into nested function or
    lambda bodies — those run later, not under the lock."""

    def __init__(self, rule: Rule, f: SourceFile, lock_expr: ast.AST,
                 out: List[Finding]):
        self.rule, self.f = rule, f
        self.lock_expr, self.out = lock_expr, out

    def visit_FunctionDef(self, node):   # noqa: N802 — ast API
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node):          # noqa: N802 — ast API
        reason = _blocking_reason(node, self.lock_expr)
        if reason is not None:
            self.out.append(self.rule.finding(
                self.f, node.lineno,
                f"{reason} — hoist it out of the `with "
                f"{_terminal_name(self.lock_expr)}:` body"))
        self.generic_visit(node)


class NoBlockingUnderLockRule(Rule):
    name = "no-blocking-under-lock"
    description = (
        "no time.sleep, transport RPC, thread join, or foreign .wait "
        "lexically inside a `with <lock>:` body — a blocked holder "
        "stalls every other thread contending the lock (the exchange "
        "fetchers and breaker paths are exactly where this bites)")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for f in pkg.walk("presto_tpu/"):
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                for item in node.items:
                    if not _lockish(item.context_expr):
                        continue
                    v = _UnderLockVisitor(self, f, item.context_expr,
                                          out)
                    for stmt in node.body:
                        v.visit(stmt)
        return out


register(NoBlockingUnderLockRule())

# =====================================================================
# 8. lock-leak — bare .acquire() without with/try-finally
# =====================================================================

#: receivers the leak rule covers: locks, conditions, semaphores
_ACQUIRABLE = re.compile(
    r"(?i)(?:^|_)(?:lock|mutex|cond|condition|sem|semaphore|permits?)s?$"
    r"|lock$")


def _release_targets(try_node: ast.Try) -> List[str]:
    out = []
    for stmt in try_node.finalbody:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                out.append(ast.dump(node.func.value))
    return out


def _trailing_acquires(stmt: ast.stmt) -> List[ast.Call]:
    """Acquire calls a following try/finally can cover: a bare
    acquire expression statement, or — the guarded-acquire idiom —
    an acquire as the LAST statement of an if/else branch whose
    matching release in the try's finally carries the same guard."""
    if isinstance(stmt, ast.Expr) \
            and isinstance(stmt.value, ast.Call) \
            and isinstance(stmt.value.func, ast.Attribute) \
            and stmt.value.func.attr == "acquire":
        return [stmt.value]
    if isinstance(stmt, ast.If):
        out = []
        for branch in (stmt.body, stmt.orelse):
            if branch:
                out.extend(_trailing_acquires(branch[-1]))
        return out
    return []


class LockLeakRule(Rule):
    name = "lock-leak"
    description = (
        "a bare lock/semaphore .acquire() must be immediately followed "
        "by try/finally that releases the same object (or use `with`) "
        "— any exception between acquire and release leaks the lock "
        "and wedges every future contender")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for f in pkg.walk("presto_tpu/"):
            if f.tree is None:
                continue
            safe: set = set()
            # pass 1: expression-statement acquire immediately followed
            # by a try whose finally releases the same receiver
            for node in ast.walk(f.tree):
                for body in (getattr(node, "body", None),
                             getattr(node, "orelse", None),
                             getattr(node, "finalbody", None)):
                    if not isinstance(body, list):
                        continue
                    for i, stmt in enumerate(body):
                        for call in _trailing_acquires(stmt):
                            if i + 1 < len(body) \
                                    and isinstance(body[i + 1], ast.Try) \
                                    and ast.dump(call.func.value) in \
                                    _release_targets(body[i + 1]):
                                safe.add(id(call))
            # pass 2: flag every uncovered acquire on a lock-like
            # receiver
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "acquire"):
                    continue
                rname = _terminal_name(node.func.value)
                if rname is None or not _ACQUIRABLE.search(rname):
                    continue
                if id(node) not in safe:
                    out.append(self.finding(
                        f, node.lineno,
                        f"bare {rname}.acquire() without an immediate "
                        f"try/finally release — use `with {rname}:` or "
                        f"follow with try/finally"))
        return out


register(LockLeakRule())

# =====================================================================
# 9. no-jax-in-control-plane — server/, protocol/, spool/, obs/ stay
#    importable and fast on device-less nodes
# =====================================================================

_CONTROL_PLANE = ("presto_tpu/server/", "presto_tpu/protocol/",
                  "presto_tpu/spool/", "presto_tpu/obs/",
                  "presto_tpu/net/")


def _module_level_stmts(tree: ast.Module) -> Iterable[ast.stmt]:
    """Top-level statements, descending into module-level if/try
    blocks (conditional imports) but never into defs/classes."""
    stack = list(tree.body)
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, (ast.If, ast.Try)):
            for body in (stmt.body, stmt.orelse,
                         getattr(stmt, "finalbody", []),
                         *[h.body for h in
                           getattr(stmt, "handlers", [])]):
                stack.extend(body)


class NoJaxInControlPlaneRule(Rule):
    name = "no-jax-in-control-plane"
    description = (
        "server/, protocol/, spool/ and obs/ must not import jax at "
        "module level — the coordinator and the wire protocol must "
        "import fast on device-less nodes; the device path may "
        "lazy-import inside the function that needs it")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for prefix in _CONTROL_PLANE:
            for f in pkg.walk(prefix):
                if f.tree is None:
                    continue
                for stmt in _module_level_stmts(f.tree):
                    mods: List[str] = []
                    if isinstance(stmt, ast.Import):
                        mods = [a.name for a in stmt.names]
                    elif isinstance(stmt, ast.ImportFrom):
                        mods = [stmt.module or ""]
                    for mod in mods:
                        if mod == "jax" or mod.startswith("jax."):
                            out.append(self.finding(
                                f, stmt.lineno,
                                f"module-level `import {mod}` in the "
                                f"control plane — lazy-import inside "
                                f"the device-path function instead"))
        return out


register(NoJaxInControlPlaneRule())

# =====================================================================
# 10. no-spawn-in-request-handler — HTTP handler bodies never spawn
#     execution threads; all statement execution goes through the
#     admission dispatcher's bounded pool
# =====================================================================

#: `handle` is the App-contract router (net/aio_server.py shells); the
#: do_* names are the http.server handler surface the threaded shell
#: and test doubles still use
_HANDLER_METHODS = ("do_GET", "do_POST", "do_DELETE", "do_PUT",
                    "do_HEAD", "handle")


def _is_spawn_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "spawn":
        return True
    return isinstance(fn, ast.Attribute) and fn.attr == "spawn"


class _HandlerBodyVisitor(ast.NodeVisitor):
    """Collect spawn()/Thread() calls in the LEXICAL body of a handler
    method — nested function definitions are someone else's body (a
    closure handed to the dispatcher is exactly the sanctioned
    pattern)."""

    def __init__(self):
        self.calls: List[ast.Call] = []

    def visit_FunctionDef(self, node):      # noqa: N802 — ast API
        pass                                # do not descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):             # noqa: N802 — ast API
        if _is_spawn_call(node) or _is_thread_ctor(node):
            self.calls.append(node)
        self.generic_visit(node)


class NoSpawnInRequestHandlerRule(Rule):
    name = "no-spawn-in-request-handler"
    description = (
        "HTTP request handlers (do_GET/do_POST/do_DELETE/...) must "
        "not call threads.spawn or construct Thread objects — "
        "per-request thread creation is unbounded under load; route "
        "execution through the admission dispatcher's bounded pool")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for f in pkg.walk("presto_tpu/"):
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.FunctionDef)
                        and node.name in _HANDLER_METHODS):
                    continue
                v = _HandlerBodyVisitor()
                for stmt in node.body:
                    v.visit(stmt)
                for call in v.calls:
                    out.append(self.finding(
                        f, call.lineno,
                        f"thread spawned inside {node.name} — accept "
                        f"cheaply and hand execution to the admission "
                        f"dispatcher pool instead"))
        return out


register(NoSpawnInRequestHandlerRule())

# =====================================================================
# 10b. no-blocking-in-event-loop — async def bodies never block the
#      loop thread (sleep via asyncio, blocking work via run_blocking)
# =====================================================================


def _loop_blocking_reason(call: ast.Call) -> Optional[str]:
    """Why `call` would stall the event loop, or None. One blocked
    coroutine freezes EVERY parked long-poll on the server."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            return "time.sleep on the event loop — await " \
                   "asyncio.sleep instead"
        if fn.attr in _RPC_METHODS:
            return (f".{fn.attr}() blocking RPC on the event loop — "
                    f"dispatch it through server.run_blocking")
    if isinstance(fn, ast.Name) and fn.id == "urlopen":
        return "urlopen on the event loop — dispatch it through " \
               "server.run_blocking"
    if _is_thread_join(call):
        return ".join() on the event loop — a thread join parks the " \
               "loop and every coroutine on it"
    return None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk an async def's LEXICAL body without descending into nested
    defs/lambdas — an inline sync helper handed to run_blocking runs on
    the executor, not the loop."""

    def __init__(self, rule: Rule, f: SourceFile, out: List[Finding]):
        self.rule, self.f, self.out = rule, f, out

    def visit_FunctionDef(self, node):   # noqa: N802 — ast API
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node):          # noqa: N802 — ast API
        reason = _loop_blocking_reason(node)
        if reason is not None:
            self.out.append(self.rule.finding(self.f, node.lineno,
                                              reason))
        self.generic_visit(node)


class NoBlockingInEventLoopRule(Rule):
    name = "no-blocking-in-event-loop"
    description = (
        "async def bodies must not call time.sleep, a blocking "
        "transport RPC/urlopen, or a thread join — the event loop "
        "serves every connection on one thread, so one blocking call "
        "stalls all of them; sleep with asyncio.sleep and push "
        "blocking work through the server's bounded executor")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for f in pkg.walk("presto_tpu/"):
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.AsyncFunctionDef):
                    continue
                v = _AsyncBodyVisitor(self, f, out)
                for stmt in node.body:
                    v.visit(stmt)
        return out


register(NoBlockingInEventLoopRule())

# =====================================================================
# 11. no-planner-in-data-plane — ops/ and parallel/ never consult the
#     planner's estimator or rule engine
# =====================================================================

_DATA_PLANE = ("presto_tpu/ops/", "presto_tpu/parallel/")

#: planner modules the data plane must not reach (cost/history
#: estimation and the iterative rule engine); plan.nodes stays legal —
#: kernels legitimately pattern-match on plan node types
_PLANNER_MODULES = ("presto_tpu.plan.stats", "presto_tpu.plan.iterative")


class NoPlannerInDataPlaneRule(Rule):
    name = "no-planner-in-data-plane"
    description = (
        "ops/ and parallel/ (the per-batch device hot paths) must not "
        "import plan.stats or plan.iterative at ANY level — cardinality "
        "estimation and rule rewriting are planning-time work; an "
        "estimator call inside a kernel re-prices the plan once per "
        "batch and drags HBO state into traced code")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out: List[Finding] = []
        for prefix in _DATA_PLANE:
            for f in pkg.walk(prefix):
                if f.tree is None:
                    continue
                for node in ast.walk(f.tree):
                    mods: List[str] = []
                    if isinstance(node, ast.Import):
                        mods = [a.name for a in node.names]
                    elif isinstance(node, ast.ImportFrom):
                        mod = node.module or ""
                        mods = [mod]
                        # `from presto_tpu.plan import stats` names the
                        # module in the alias list, not in `module`
                        if mod == "presto_tpu.plan":
                            mods += [f"{mod}.{a.name}"
                                     for a in node.names]
                    for mod in mods:
                        if any(mod == p or mod.startswith(p + ".")
                               for p in _PLANNER_MODULES):
                            out.append(self.finding(
                                f, node.lineno,
                                f"planner import `{mod}` in the data "
                                f"plane — estimate at planning time and "
                                f"pass the decision in as plain data"))
        return out


register(NoPlannerInDataPlaneRule())

# =====================================================================
# 12. membership-chokepoint — cluster.py's _membership() is the only
#     mutator of the dead/drained sets
# =====================================================================

#: a direct mutation of the coordinator's dead/drained membership sets;
#: every such write must sit inside TpuCluster._membership() under
#: _membership_lock (the chokepoint lines there carry suppressions) so
#: a failure-detector sweep can never interleave with a scheduler's
#: placement snapshot and observe half-applied membership
_MEMBERSHIP_MUTATION = re.compile(
    r"\.\s*(?:dead|drained)\s*\.\s*"
    r"(?:add|discard|remove|clear|update|pop)\s*\(")

_CLUSTER = "presto_tpu/server/cluster.py"


class MembershipChokepointRule(Rule):
    name = "membership-chokepoint"
    description = (
        "every mutation of the coordinator's dead/drained worker sets "
        "flows through TpuCluster._membership() under _membership_lock "
        "— a bare .dead.add / .drained.discard elsewhere races the "
        "failure detector against placement snapshots (the "
        "check_workers membership-mutation race)")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_MEMBERSHIP_MUTATION,),
            "dead/drained set mutated outside the _membership() "
            "chokepoint — pass dead_add/dead_remove/drained_add/"
            "drained_remove to _membership() instead",
            prefixes=("presto_tpu/server/",))
        # honesty: the chokepoint itself must still mutate the sets via
        # the idiom this rule polices (its lines carry suppressions)
        out.extend(honesty_finding(
            self, pkg, _CLUSTER, (_MEMBERSHIP_MUTATION,),
            "the membership chokepoint"))
        return out


register(MembershipChokepointRule())

# =====================================================================
# 12b. journal-chokepoint — QueryJournal is the only coordinator
#      query-state persistence path
# =====================================================================

#: a bare JSONL-style append (json.dumps into .write, or a manual
#: line + "\n" write) — coordinator query state persisted outside the
#: QueryJournal would be invisible to crash recovery AND to peer
#: coordinators adopting queries from the shared journal
_JOURNAL_JSONL = re.compile(
    r"\.write\s*\(\s*(?:json\s*\.\s*dumps|[\w.]+\s*\+\s*[\"']\\n[\"'])")

_JOURNAL = "presto_tpu/server/journal.py"


class JournalChokepointRule(Rule):
    name = "journal-chokepoint"
    description = (
        "all coordinator query-state persistence in presto_tpu/server/ "
        "flows through QueryJournal — a bare JSONL write elsewhere "
        "creates a second durability log that crash recovery and "
        "multi-coordinator adoption never read (the HA split-brain "
        "hazard)")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_JOURNAL_JSONL,),
            "JSONL-style write outside QueryJournal — append through "
            "the journal (server/journal.py) so recovery and peer "
            "adoption see it",
            allowed=(_JOURNAL,),
            prefixes=("presto_tpu/server/",))
        # honesty: the journal itself must still persist via the idiom
        # this rule polices — an allowlist pointing at a file that no
        # longer writes JSONL is a stale exemption
        out.extend(honesty_finding(
            self, pkg, _JOURNAL, (_JOURNAL_JSONL,),
            "the query-journal chokepoint"))
        return out


register(JournalChokepointRule())

# =====================================================================
# 13. metric-docs-sync — the README metric catalog and the registered
#     metric set agree in both directions
# =====================================================================

#: the catalog section opener in README.md; entries follow as a bullet
#: list (blank lines allowed) until the first non-bullet paragraph
_CATALOG_HEADER = re.compile(r"^Metric catalog \(prefix `presto_tpu_`")

_BACKTICK_TOKEN = re.compile(r"`([^`\n]+)`")

#: a {a,b,c} alternation inside a catalog token (never token-final —
#: token-final braces are label annotations and are stripped first)
_ALTERNATION = re.compile(r"\{([A-Za-z0-9_]*(?:,[A-Za-z0-9_]*)+)\}")

_README = "README.md"


def _catalog_entries(text: str) -> Tuple[Optional[int],
                                         List[Tuple[str, int]]]:
    """Parse the README metric catalog: returns (header line or None,
    [(metric name, line)]). Token grammar: backticked, optional
    trailing ``{label,...}`` annotation (stripped), inner ``{a,b}``
    alternations expanded, ``presto_tpu_`` prefix implied."""
    lines = text.splitlines()
    header_at: Optional[int] = None
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(lines, start=1):
        if header_at is None:
            if _CATALOG_HEADER.match(line.strip()):
                header_at = i
            continue
        stripped = line.strip()
        if stripped and not stripped.startswith(("-", "`")) \
                and not line.startswith(" "):
            break                          # first paragraph after list
        for m in _BACKTICK_TOKEN.finditer(line):
            tok = m.group(1)
            if " " in tok or "/" in tok or "." in tok:
                continue                   # prose in backticks, not a name
            # token-final braces are a label annotation UNLESS the name
            # is incomplete without them (`result_cache_{bytes,entries}`
            # — the char before `{` is `_`, so it's an alternation)
            tok = re.sub(r"(?<=[A-Za-z0-9])\{[A-Za-z0-9_,=]*\}$", "",
                         tok)
            variants = [tok]
            while any("{" in v for v in variants):
                nxt: List[str] = []
                for v in variants:
                    am = _ALTERNATION.search(v)
                    if am is None:
                        if "{" in v:       # unbalanced/unknown braces
                            break
                        nxt.append(v)
                        continue
                    for opt in am.group(1).split(","):
                        nxt.append(v[:am.start()] + opt + v[am.end():])
                variants = nxt
            for v in variants:
                if not v:
                    continue
                if not v.startswith("presto_tpu_"):
                    v = "presto_tpu_" + v
                out.append((v, i))
    return header_at, out


class MetricDocsSyncRule(Rule):
    name = "metric-docs-sync"
    description = (
        "every metric name registered in code must appear in the "
        "README metric catalog and every catalog entry must still be "
        "registered — an undocumented series is invisible to the ops "
        "runbook, a stale entry sends an operator hunting for a "
        "series that no longer exists")

    def _readme_text(self, pkg: Package) -> Optional[str]:
        f = pkg.get(_README)
        if f is not None:
            return f.text
        path = PKG_ROOT.parent / _README
        try:
            return path.read_text()
        except OSError:
            return None

    def run(self, pkg: Package) -> Iterable[Finding]:
        registered: Dict[str, Tuple[SourceFile, int]] = {}
        for f in pkg.walk("presto_tpu/"):
            if f.relpath in _METRIC_EXCLUDED:
                continue
            for m in _METRIC_CALL.finditer(f.text):
                registered.setdefault(
                    m.group(1), (f, f.line_at(m.start())))
        text = self._readme_text(pkg)
        if text is None:
            return [Finding(self.name, _README, 1,
                            "README.md is missing — the metric catalog "
                            "has nowhere to live")]
        header_at, entries = _catalog_entries(text)
        if header_at is None:
            return [Finding(
                self.name, _README, 1,
                "README.md has no 'Metric catalog (prefix "
                "`presto_tpu_`)' section — restore it (or update this "
                "rule's header pattern)")]
        documented: Dict[str, int] = {}
        for mname, line in entries:
            documented.setdefault(mname, line)
        out: List[Finding] = []
        for mname in sorted(set(registered) - set(documented)):
            f, line = registered[mname]
            out.append(self.finding(
                f, line,
                f"metric {mname!r} is registered here but absent from "
                f"the README metric catalog — document it"))
        for mname in sorted(set(documented) - set(registered)):
            out.append(Finding(
                self.name, _README, documented[mname],
                f"README catalog documents {mname!r} but nothing "
                f"registers it — stale docs entry, delete or fix it"))
        return out


register(MetricDocsSyncRule())

# =====================================================================
# 14. mv-cache-chokepoint — mv/manager.py is the only caller of the
#     fragment cache's pin/unpin API
# =====================================================================

#: a pin/unpin call site — pinning exempts an entry from LRU
#: eviction, so a stray pin anywhere else is a silent budget leak and a
#: stray unpin can evict live materialized-view state from under a read
_CACHE_PIN = re.compile(r"\.\s*pin\s*\(")
_CACHE_UNPIN = re.compile(r"\.\s*unpin\s*\(")

_MV_MANAGER = "presto_tpu/mv/manager.py"


class MvCacheChokepointRule(Rule):
    name = "mv-cache-chokepoint"
    description = (
        "only presto_tpu/mv/ may pin/unpin fragment-cache entries — "
        "materialized-view state is the sole legitimate pinned "
        "resident, and routing every pin through the mv manager keeps "
        "the pinned-bytes accounting, journalled lifecycle and "
        "refresh-then-release ordering in one place; a pin elsewhere "
        "leaks budget past eviction forever, an unpin elsewhere can "
        "drop live view state mid-read")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_CACHE_PIN, _CACHE_UNPIN),
            "fragment-cache pin/unpin outside presto_tpu/mv/ — route "
            "materialized state through mv.MaterializedViewManager",
            allowed=(_MV_MANAGER,))
        out.extend(honesty_finding(
            self, pkg, _MV_MANAGER, (_CACHE_PIN, _CACHE_UNPIN),
            "mv state pinning"))
        return out


register(MvCacheChokepointRule())

# =====================================================================
# 15. spill-chokepoint — exec/spill.py is the only spill-file writer
#     in the execution layers (exec/, ops/)
# =====================================================================

_SPILL = "presto_tpu/exec/spill.py"


class SpillChokepointRule(Rule):
    name = "spill-chokepoint"
    description = (
        "exec/ and ops/ open spill files for write only through "
        "exec/spill.FileSpiller — one spill write path means one "
        "partial-file cleanup story under ENOSPC, one SpillError "
        "classification, one stray-dir GC prefix and one "
        "spilled-bytes metric; a bare file write inside an operator "
        "would leak torn run files past every one of them")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, _WRITE_PATTERNS,
            "file-writing call site in the execution layer — spill "
            "through exec/spill.FileSpiller",
            allowed=(_SPILL,),
            prefixes=("presto_tpu/exec/", "presto_tpu/ops/"))
        # honesty: the spiller itself must still match the write
        # idioms this rule polices
        out.extend(honesty_finding(
            self, pkg, _SPILL, _WRITE_PATTERNS,
            "the spill-file writer"))
        return out


register(SpillChokepointRule())

# =====================================================================
# 16. alert-rule-metric-exists — every metric a declarative alert rule
#     references is a registered metric, and obs/tsdb.py is the only
#     telemetry-history writer
# =====================================================================

#: a `metric="..."` literal inside an AlertRule construction — the
#: name an alert evaluates against the telemetry history
_ALERT_METRIC_REF = re.compile(r"\bmetric\s*=\s*[\"']([^\"']+)[\"']")
#: the TimeSeriesStore write chokepoint: the scraper is the ONLY
#: legitimate history writer — a second writer could plant points the
#: alert engine fires on without any scrape having observed them
_TSDB_WRITE = re.compile(r"\.\s*write_points\s*\(")

_ALERTS_FILE = "presto_tpu/obs/alerts.py"
_TSDB_FILE = "presto_tpu/obs/tsdb.py"


class AlertRuleMetricExistsRule(Rule):
    name = "alert-rule-metric-exists"
    description = (
        "every metric name referenced by an alert rule in "
        "obs/alerts.py must be registered somewhere in the package — "
        "a rule over a metric nobody registers silently never fires, "
        "which is worse than no rule at all; and obs/tsdb.py is the "
        "only caller of the TSDB write chokepoint, so alert "
        "evaluations can only ever see history the scraper wrote")

    def run(self, pkg: Package) -> Iterable[Finding]:
        registered = set()
        for f in pkg.walk("presto_tpu/"):
            if f.relpath in _METRIC_EXCLUDED:
                continue
            for m in _METRIC_CALL.finditer(f.text):
                registered.add(m.group(1))
        out: List[Finding] = []
        alerts = pkg.get(_ALERTS_FILE)
        if alerts is None:
            out.append(Finding(
                self.name, _ALERTS_FILE, 1,
                "the alert-rule module is missing — the catalog "
                "moved? update the rule"))
        else:
            refs = list(_ALERT_METRIC_REF.finditer(alerts.text))
            for m in refs:
                if m.group(1) not in registered:
                    out.append(Finding(
                        self.name, _ALERTS_FILE,
                        alerts.line_at(m.start()),
                        f"alert rule references metric "
                        f"{m.group(1)!r}, which no call site "
                        f"registers — the rule can never fire"))
            # honesty: the catalog must still spell rule metrics with
            # the metric="..." idiom this rule scans for
            if not refs:
                out.append(Finding(
                    self.name, _ALERTS_FILE, 1,
                    "no metric=\"...\" references found in the alert "
                    "catalog — the rule idiom changed? update the "
                    "rule's pattern"))
        out.extend(regex_findings(
            self, pkg, (_TSDB_WRITE,),
            "telemetry-history write outside obs/tsdb.py — all "
            "history enters through the scraper's write chokepoint",
            allowed=(_TSDB_FILE,)))
        out.extend(honesty_finding(
            self, pkg, _TSDB_FILE, (_TSDB_WRITE,),
            "the telemetry-history write chokepoint"))
        return out


register(AlertRuleMetricExistsRule())

# =====================================================================
# 19. ici-exchange-chokepoint — server/mesh_tier.py is the only place
#     that decides ICI-vs-HTTP exchange routing
# =====================================================================

#: the ICI exchange descriptor rides the task session properties under
#: this key; reading or writing it anywhere else in the control plane
#: is a routing decision made outside the sanctioned policy
_ICI_DESCRIPTOR = re.compile(r"[\"']x_ici_exchange[\"']")

_MESH_TIER = "presto_tpu/server/mesh_tier.py"


class IciExchangeChokepointRule(Rule):
    name = "ici-exchange-chokepoint"
    description = (
        "only server/mesh_tier.py may decide whether an exchange "
        "rides ICI collectives or HTTP page pulls — a bare mesh-"
        "descriptor check elsewhere in server/ or protocol/ forks the "
        "routing policy, and a fork that disagrees with the "
        "chokepoint silently double-accounts or drops the fallback "
        "contract (non-co-located/degraded stages must keep HTTP "
        "byte-for-byte)")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_ICI_DESCRIPTOR,),
            "bare ICI exchange-descriptor access outside "
            "server/mesh_tier.py — route the decision through "
            "stamp_ici_descriptor/ici_descriptor",
            allowed=(_MESH_TIER,),
            prefixes=("presto_tpu/server/", "presto_tpu/protocol/"))
        out.extend(honesty_finding(
            self, pkg, _MESH_TIER, (_ICI_DESCRIPTOR,),
            "the ICI exchange routing chokepoint"))
        return out


register(IciExchangeChokepointRule())

# =====================================================================
# 20. no-page-copy-in-data-plane — page bytes cross protocol/ and
#     spool/ as views; copies live only at serde.py's sanctioned sites
# =====================================================================

#: flattening an array lane into an owned bytes object — the idiom the
#: PageBuffer scatter-gather writer exists to remove
_TOBYTES = re.compile(r"\.tobytes\(")
#: materializing a decoded lane that frombuffer already aliased
_FROMBUFFER_COPY = re.compile(r"frombuffer\([^)]*\)\s*\.copy\(")

_SERDE = "presto_tpu/protocol/serde.py"


class NoPageCopyInDataPlaneRule(Rule):
    name = "no-page-copy-in-data-plane"
    description = (
        "the columnar data plane (protocol/, spool/) moves page bytes "
        "as buffer views: encode scatter-gathers lanes into one "
        "pre-sized frame, decode returns read-only frombuffer aliases, "
        "spool reads slice one contiguous read — a stray .tobytes() "
        "or frombuffer(...).copy() reintroduces a per-lane copy that "
        "the zero-copy contract (and its GB/s bench lane) exists to "
        "keep out; sanctioned copies live in protocol/serde.py only, "
        "counted by page_copy_fallback_total")

    def run(self, pkg: Package) -> Iterable[Finding]:
        out = regex_findings(
            self, pkg, (_TOBYTES, _FROMBUFFER_COPY),
            "page-lane copy in the data plane — emit through the "
            "PageBuffer writer / return a frombuffer view (sanctioned "
            "copy sites live in protocol/serde.py and count "
            "page_copy_fallback_total)",
            allowed=(_SERDE,),
            prefixes=("presto_tpu/protocol/", "presto_tpu/spool/"))
        # honesty: serde.py must still contain a policed idiom (the
        # small-piece coalesce in _PageWriter.put_array); if the last
        # sanctioned copy disappears, the allowlist is vacuous
        out.extend(honesty_finding(
            self, pkg, _SERDE, (_TOBYTES,),
            "the sanctioned data-plane copy sites"))
        return out


register(NoPageCopyInDataPlaneRule())
