"""Dynamic lock-order sanitizer: TSan-style deadlock-potential
detection for the whole engine.

The engine is genuinely concurrent — exchange fetcher threads, per-task
worker threads, heartbeat/announcer loops, breaker state machines — and
a deadlock needs only two locks acquired in opposite orders by two
threads that never actually collide in a test run. This module catches
the *potential*: instrumented Lock/RLock/Condition wrappers record, per
thread, which locks are held when another is acquired, accumulate those
observations into one global lock-ORDER graph keyed by allocation site,
and report any cycle in that graph — the classic ABBA pattern — even
though no run ever deadlocked.

Two ways to use it:

  - `LockSanitizer()` + `san.lock()/rlock()/condition()` builds
    instrumented primitives against a private graph (the honesty tests
    drive a deliberate ABBA fixture through this).
  - `install()` monkeypatches `threading.Lock/RLock/Condition` so every
    lock subsequently allocated *from repo code* is instrumented
    against the process-global sanitizer; tests/conftest.py does this
    for the whole tier-1 suite and fails the session on any cycle.
    Locks allocated by stdlib/third-party code pass through raw — the
    graph stays ours.

While active, every tracked release observes the hold duration into the
`presto_tpu_lock_hold_seconds` histogram (labeled by lock name) in the
process metrics registry, so contended locks surface in /v1/metrics
next to everything else."""

from __future__ import annotations

import _thread
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

#: bind the real factories at import time — installation rebinds the
#: threading module attributes, never these
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: hold-duration buckets: spin-length holds up to pathological seconds
_HOLD_BUCKETS = (0.000_01, 0.000_1, 0.001, 0.01, 0.1, 0.5, 2.0, 10.0)

#: allocation sites never instrumented even inside the repo: the
#: metrics registry's own locks guard the histogram this module
#: observes into (instrumenting them would recurse), and this module's
#: internals must not watch themselves
_SITE_BLOCKLIST = (os.path.join("obs", "metrics.py"),
                   os.path.join("analysis", "locksan.py"))


class LockOrderError(RuntimeError):
    """Raised by assert_no_cycles when the order graph has a cycle."""


class LockSanitizer:
    """The order graph + per-thread held-lock accounting."""

    def __init__(self):
        # raw mutex: the sanitizer must never route through wrappers
        self._mutex = _thread.allocate_lock()
        self._tls = threading.local()
        #: (held_site, acquired_site) -> one example stack pair
        self._edges: Dict[Tuple[str, str], str] = {}
        #: sites observed nesting with a *different instance* of the
        #: same site (diagnostic only: a length-1 site cycle needs two
        #: threads nesting opposite instances to deadlock)
        self.same_site_nesting: set = set()
        self.tracked_locks = 0
        self._hold_hist = None

    # -------------------------------------------------- wrapper factories
    def lock(self, name: Optional[str] = None) -> "_SanLock":
        with self._mutex:
            self.tracked_locks += 1
        return _SanLock(self, _REAL_LOCK(), name or _caller_site())

    def rlock(self, name: Optional[str] = None) -> "_SanRLock":
        with self._mutex:
            self.tracked_locks += 1
        return _SanRLock(self, _REAL_RLOCK(), name or _caller_site())

    def condition(self, name: Optional[str] = None,
                  lock=None) -> threading.Condition:
        """A real Condition over an instrumented RLock: wait/notify
        semantics are stdlib's, every acquire/release is accounted."""
        return _REAL_CONDITION(
            lock if lock is not None
            else self.rlock(name or _caller_site()))

    # ------------------------------------------------------- accounting
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def _in_hook(self) -> bool:
        return getattr(self._tls, "in_hook", False)

    def before_acquire(self, lock: "_SanLock") -> None:
        if self._in_hook():
            return
        held = self._held()
        for h in held:
            if h is lock:
                return               # reentrant — not an ordering fact
        for h in held:
            if h.name == lock.name:
                with self._mutex:
                    self.same_site_nesting.add(lock.name)
                continue
            edge = (h.name, lock.name)
            if edge not in self._edges:       # racy pre-check is fine
                example = "".join(traceback.format_stack(
                    sys._getframe(2), limit=6))
                with self._mutex:
                    self._edges.setdefault(edge, example)

    def after_acquire(self, lock: "_SanLock") -> None:
        if not self._in_hook():
            self._held().append(lock)

    def after_release(self, lock: "_SanLock",
                      t0: Optional[float]) -> None:
        if self._in_hook():
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break
        if t0 is not None:
            self._observe_hold(lock.name, time.perf_counter() - t0)

    def _observe_hold(self, name: str, dt: float) -> None:
        self._tls.in_hook = True
        try:
            hist = self._hold_hist
            if hist is None:
                from presto_tpu.obs.metrics import histogram
                hist = self._hold_hist = histogram(
                    "presto_tpu_lock_hold_seconds",
                    "Lock hold duration by lock allocation site "
                    "(present while the lock sanitizer is active)",
                    ("lock",), buckets=_HOLD_BUCKETS)
            hist.observe(dt, lock=name)
        except Exception:   # noqa: BLE001 — interpreter teardown etc.
            pass
        finally:
            self._tls.in_hook = False

    # ---------------------------------------------------------- verdicts
    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mutex:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the site-order graph (Tarjan SCCs;
        within each nontrivial SCC one representative cycle is walked
        out). Empty list == no deadlock potential observed."""
        edges = self.edges()
        graph: Dict[str, set] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        sccs = _tarjan(graph)
        out: List[List[str]] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp_set = set(comp)
            # walk one cycle inside the component for the report
            start = comp[0]
            path, seen = [start], {start}
            node = start
            while True:
                nxt = next(n for n in sorted(graph[node])
                           if n in comp_set)
                if nxt in seen:
                    out.append(path[path.index(nxt):])
                    break
                path.append(nxt)
                seen.add(nxt)
                node = nxt
        return out

    def report(self) -> str:
        edges = self.edges()
        cycles = self.cycles()
        lines = [f"lock-order sanitizer: {self.tracked_locks} tracked "
                 f"locks, {len(edges)} order edges, "
                 f"{len(cycles)} cycle(s)"]
        for cyc in cycles:
            ring = " -> ".join(cyc + [cyc[0]])
            lines.append(f"  CYCLE: {ring}")
            for a, b in zip(cyc, cyc[1:] + [cyc[0]]):
                ex = edges.get((a, b), "")
                lines.append(f"    edge {a} -> {b} first seen at:")
                lines.extend("      " + ln
                             for ln in ex.rstrip().splitlines())
        if self.same_site_nesting:
            lines.append(
                "  note: same-site instance nesting (deadlocks only "
                "if two threads nest opposite instances): "
                + ", ".join(sorted(self.same_site_nesting)))
        return "\n".join(lines)

    def assert_no_cycles(self) -> None:
        if self.cycles():
            raise LockOrderError(self.report())


def _tarjan(graph: Dict[str, set]) -> List[List[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: set = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for child in it:
                if child not in index:
                    index[child] = low[child] = counter[0]
                    counter[0] += 1
                    stack.append(child)
                    on_stack.add(child)
                    work.append((child, iter(sorted(graph[child]))))
                    advanced = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
    return sccs


# ------------------------------------------------------------- wrappers
class _SanLock:
    """Instrumented non-reentrant lock: full Lock protocol, every
    transition accounted against the owning sanitizer."""

    def __init__(self, san: LockSanitizer, inner, name: str):
        self._san = san
        self._inner = inner
        self.name = name
        self._t0: Optional[float] = None

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._san.before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._t0 = time.perf_counter()
            self._san.after_acquire(self)
        return got

    def release(self) -> None:
        t0, self._t0 = self._t0, None
        self._inner.release()
        self._san.after_release(self, t0)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return (f"<{type(self).__name__} {self.name} "
                f"wrapping {self._inner!r}>")


class _SanRLock(_SanLock):
    """Instrumented reentrant lock, including the Condition protocol
    (_release_save/_acquire_restore/_is_owned) so a Condition built
    over it keeps the accounting exact across wait()."""

    def __init__(self, san: LockSanitizer, inner, name: str):
        super().__init__(san, inner, name)
        self._count = 0          # owner-only mutation: no race

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._inner._is_owned():
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._count += 1
            return got
        self._san.before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._count = 1
            self._t0 = time.perf_counter()
            self._san.after_acquire(self)
        return got

    def release(self) -> None:
        if self._count > 1:
            self._inner.release()
            self._count -= 1
            return
        t0, self._t0 = self._t0, None
        self._count = 0
        self._inner.release()
        self._san.after_release(self, t0)

    # Condition protocol --------------------------------------------------
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        t0, self._t0 = self._t0, None
        count, self._count = self._count, 0
        state = self._inner._release_save()
        self._san.after_release(self, t0)
        return (state, count)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        self._san.before_acquire(self)
        self._inner._acquire_restore(state)
        self._count = count
        self._t0 = time.perf_counter()
        self._san.after_acquire(self)


# ---------------------------------------------------- global installation
#: repo root: locks allocated from files under here are instrumented
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

_active: Optional[LockSanitizer] = None


def _caller_site() -> str:
    """repo-relative file:line of the nearest frame outside this
    module and threading.py — the lock's allocation site, which is the
    graph node (all instances from one site share ordering facts)."""
    f = sys._getframe(1)
    here = os.path.abspath(__file__)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != here and "threading" not in \
                os.path.basename(fn):
            rel = os.path.relpath(fn, _REPO_ROOT)
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


def _track_site() -> Optional[str]:
    """The allocation site if it should be instrumented (repo code,
    not blocklisted), else None for raw pass-through."""
    f = sys._getframe(2)
    here = os.path.abspath(__file__)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if fn != here and os.path.basename(
                f.f_code.co_filename) != "threading.py":
            if not fn.startswith(_REPO_ROOT + os.sep):
                return None
            for blocked in _SITE_BLOCKLIST:
                if fn.endswith(blocked):
                    return None
            return f"{os.path.relpath(fn, _REPO_ROOT)}:{f.f_lineno}"
        f = f.f_back
    return None


def _patched_lock():
    site = _track_site()
    if _active is None or site is None:
        return _REAL_LOCK()
    return _active.lock(site)


def _patched_rlock():
    site = _track_site()
    if _active is None or site is None:
        return _REAL_RLOCK()
    return _active.rlock(site)


def _patched_condition(lock=None):
    if lock is not None:
        return _REAL_CONDITION(lock)
    site = _track_site()
    if _active is None or site is None:
        return _REAL_CONDITION()
    return _active.condition(site)


def install(san: Optional[LockSanitizer] = None) -> LockSanitizer:
    """Activate the global sanitizer: every threading.Lock/RLock/
    Condition subsequently allocated from repo code is instrumented.
    Idempotent; returns the active sanitizer."""
    global _active
    if _active is not None:
        return _active
    _active = san or LockSanitizer()
    threading.Lock = _patched_lock
    threading.RLock = _patched_rlock
    threading.Condition = _patched_condition
    return _active


def uninstall() -> None:
    """Restore the real factories. Locks already created stay
    instrumented (they hold their own sanitizer reference)."""
    global _active
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _active = None


def active() -> Optional[LockSanitizer]:
    return _active
