"""Client statement protocol: POST /v1/statement + nextUri polling.

Reference: QueuedStatementResource / ExecutingStatementResource
(presto-main/.../server/protocol/QueuedStatementResource.java:213,
ExecutingStatementResource.java) and the client contract in
presto-client/.../StatementClientV1.java:365 — a client POSTs SQL,
receives a QueryResults JSON with a `nextUri`, and polls it until
`nextUri` disappears; `columns` + `data` batches carry the rows, and
`stats.state` tracks QUEUED -> RUNNING -> FINISHED/FAILED.

This is the L0 surface over TpuCluster: accepted statements go through
the admission front door (`presto_tpu/admission/`) — shed check,
resource-group queueing, then a bounded dispatch pool executes them —
results buffer per query, and each GET serves one data batch.  The
HTTP handler never spawns execution threads itself."""

from __future__ import annotations

import asyncio
import json
import re
import threading
import time as _time
import uuid
from typing import Callable, Dict, List, Optional

import presto_tpu.exec.dist_executor  # noqa: F401 — registers mesh metrics
from presto_tpu.admission import (DispatchManager, OverloadedError,
                                  QueryQueueFull, ResourceGroupManager)
from presto_tpu.admission import dispatcher as _dispatch
from presto_tpu.config import DEFAULT_ADMISSION, DEFAULT_ELASTIC
from presto_tpu.net.aio_server import AioHttpServer, Request, Response
from presto_tpu.server.journal import QueryJournal
from presto_tpu.obs.metrics import counter as _counter, gauge as _gauge
from presto_tpu.utils.threads import spawn
from presto_tpu.utils.tracing import TRACER

_EXECUTING = re.compile(r"^/v1/statement/executing/([^/]+)/(\d+)$")
_QUEUED = re.compile(r"^/v1/statement/queued/([^/]+)/(\d+)$")
_CANCEL = re.compile(r"^/v1/statement/executing/([^/]+)$")
_TRACE = re.compile(r"^/v1/trace/([^/]+)$")
_INGEST = re.compile(r"^/v1/ingest/([^/]+)/([^/]+)/([^/]+)$")

_M_QUERIES = _counter("presto_tpu_coordinator_queries_total",
                      "Queries submitted to the coordinator, by outcome",
                      ("state",))
_M_COORD_UPTIME = _gauge(
    "presto_tpu_coordinator_uptime_seconds",
    "Seconds since this coordinator process started serving")
_M_ADOPTIONS = _counter(
    "presto_tpu_coordinator_ha_adoptions_total",
    "Journaled queries adopted from a dead peer coordinator under "
    "their original query id")

_COORD_START = _time.time()

_BATCH_ROWS = 4096


def _type_name(t) -> str:
    return str(t)


class _DoneEvent(threading.Event):
    """threading.Event plus completion callbacks: the async nextUri
    long-poll registers a loop-threadsafe waker here so a parked poll
    wakes the instant the query finishes instead of sleeping out its
    poll window. Callbacks fire exactly once, from whichever thread
    calls set(); one registered after set() fires immediately."""

    def __init__(self):
        super().__init__()
        self._cb_lock = threading.Lock()
        self._cbs: List[Callable[[], None]] = []

    def add_callback(self, cb: Callable[[], None]) -> None:
        with self._cb_lock:
            if not self.is_set():
                self._cbs.append(cb)
                return
        cb()

    def remove_callback(self, cb: Callable[[], None]) -> None:
        with self._cb_lock:
            try:
                self._cbs.remove(cb)
            except ValueError:
                pass

    def set(self) -> None:
        super().set()
        with self._cb_lock:
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:   # noqa: BLE001 — a dead loop's waker
                pass            # must not break query completion


class _Query:
    def __init__(self, qid: str, sql: str, user: str = ""):
        self.qid = qid
        self.sql = sql
        self.user = user
        self.state = "QUEUED"
        self.dispatch_state: Optional[str] = None
        self.error: Optional[str] = None
        self.error_name = "GENERIC_INTERNAL_ERROR"
        self.error_type = "INTERNAL_ERROR"
        self.columns: Optional[List[dict]] = None
        self.rows: List[tuple] = []
        self.done = _DoneEvent()
        self.cancelled = False
        # final-batch cache: clients auto-retry nextUri GETs, so the
        # last data batch must survive serving it once — a replayed GET
        # of the same token re-serves the same rows instead of silently
        # returning FINISHED with no data
        self._final_token: Optional[int] = None
        self._final_batch: List = []
        # set once a terminal payload (final batch or error) has been
        # rendered to a client — only then is FIFO eviction safe; an
        # undelivered finished query evicted early 404s its owner's
        # next poll
        self.delivered = False

    def run(self, engine):
        self.state = "RUNNING"
        try:
            rows = engine.execute_sql(self.sql)
            names = ()
            types = ()
            try:
                plan = engine.plan_sql(self.sql)
                names, types = plan.output_names, plan.output_types
            except Exception:   # noqa: BLE001 — DDL has no plan
                pass
            if not names:
                names = tuple(f"_col{i}"
                              for i in range(len(rows[0]) if rows else 1))
                types = ()
            self.columns = [
                {"name": n,
                 "type": _type_name(types[i]) if i < len(types)
                 else "unknown"}
                for i, n in enumerate(names)]
            # decimals travel as exact strings (the reference client
            # protocol's decimal encoding, presto-client QueryResults).
            # Keyed on the DECLARED column type, not the python value
            # shape, so scale-0 decimals (which materialize as ints)
            # encode identically to scaled ones.
            dec_cols = {i for i, t in enumerate(types)
                        if getattr(t, "is_decimal", False)}
            self.rows = [
                [None if v is None else
                 (str(v) if i in dec_cols
                  or type(v).__name__ == "Decimal" else v)
                 for i, v in enumerate(r)] for r in rows]
            self.state = "FINISHED"
        except Exception as e:  # noqa: BLE001 — rendered to the client
            self.error = f"{type(e).__name__}: {e}"[:500]
            if isinstance(e, QueryQueueFull):
                self.error_name = "QUERY_QUEUE_FULL"
                self.error_type = "INSUFFICIENT_RESOURCES"
            self.state = "FAILED"
        finally:
            if self.cancelled:
                # the engine call itself is not interruptible; report
                # the cancellation honestly instead of a silent FINISH
                self.state = "FAILED"
                self.error = "Query was canceled by the user"
                self.rows = []
            _M_QUERIES.inc(state=self.state)
            self.done.set()

    def results_json(self, base: str, token: int) -> dict:
        out = {
            "id": self.qid,
            "infoUri": f"{base}/v1/query/{self.qid}",
            "stats": {"state": self.state, "queued": self.state == "QUEUED",
                      "scheduled": self.state != "QUEUED"},
        }
        if self.state == "FAILED":
            out["error"] = {"message": self.error,
                            "errorName": self.error_name,
                            "errorType": self.error_type}
            self.delivered = True
            return out
        if self.state != "FINISHED":
            out["nextUri"] = \
                f"{base}/v1/statement/executing/{self.qid}/{token}"
            return out
        # FINISHED: serve data batches; nextUri until drained
        if self.columns is not None:
            out["columns"] = self.columns
        if self._final_token is not None:
            # already drained: the bulk buffer is released, but the
            # final batch stays cached so a client RETRY of the last
            # GET (response lost after the server built it) re-serves
            # the same rows — same-token GETs must be idempotent
            if token == self._final_token and self._final_batch:
                out["data"] = self._final_batch
            return out
        lo = token * _BATCH_ROWS
        hi = lo + _BATCH_ROWS
        batch = self.rows[lo:hi]
        if batch:
            out["data"] = batch
        if hi < len(self.rows):
            out["nextUri"] = \
                f"{base}/v1/statement/executing/{self.qid}/{token + 1}"
        else:
            # final batch served: release the buffered result (queries
            # stay listed for /v1/query info, rows do not accumulate)
            # but keep this batch for idempotent replay
            self._final_token = token
            self._final_batch = batch
            self.rows = []
            self.delivered = True
        return out


def _query_info(q) -> dict:
    """ONE query-info shape for the list and detail endpoints."""
    return {"queryId": q.qid, "state": q.state, "query": q.sql,
            "user": getattr(q, "user", ""),
            "dispatchState": getattr(q, "dispatch_state", None),
            "error": q.error}


class StatementApp:
    """The coordinator's request router, served by AioHttpServer. The
    two client hot paths — POST /v1/statement and the nextUri GET
    long-poll — run natively async (a parked poll is a coroutine
    waiting on the query's done event); every other route rides the
    loop's bounded executor via `handle`."""

    def __init__(self, coordinator: "StatementServer"):
        self.coordinator = coordinator

    @property
    def base(self) -> str:
        return self.coordinator.base

    def _dead(self, server) -> bool:
        """Crash-simulation check (StatementServer.kill): a killed
        coordinator's in-flight handlers must NOT answer — a dying
        process tears its connections, it does not serve one last
        response. A None response makes the server close the socket
        with no status line, which the client transport classifies as
        a connection error and fails over."""
        return bool(getattr(server, "dead", False))

    @staticmethod
    def _json(code: int, obj) -> Response:
        return Response(code, json.dumps(obj).encode())

    # -------------------------------------------------- async hot paths
    def dispatch_async(self, req: Request, server: AioHttpServer):
        if req.method == "POST" and req.path == "/v1/statement":
            return self._submit_async(server, req)
        if req.method == "GET":
            m = _EXECUTING.match(req.path) or _QUEUED.match(req.path)
            if m:
                return self._poll_async(server, req, m.group(1),
                                        int(m.group(2)))
            if req.path in ("/v1/metrics", "/v1/status", "/v1/alerts"):
                return self._snapshot_async(server, req)
        return None

    async def _snapshot_async(self, server: AioHttpServer,
                              req: Request):
        """Scrape-time computation (registry render, process gauges,
        admission/journal/alert snapshots) runs on the executor —
        never on the loop, where one slow scrape would stall every
        parked long-poll (tests/test_aio_server.py asserts this)."""
        if self._dead(server):
            return None
        return await server.run_blocking(self._get, req)

    async def _submit_async(self, server: AioHttpServer, req: Request):
        if self._dead(server):
            return None
        sql = req.body.decode()
        try:
            # admission + journal append touch locks and disk — run
            # them on the executor, never on the loop
            q = await server.run_blocking(
                self._do_submit, sql, req.headers.get(
                    "X-Presto-User", "") or "",
                req.headers.get("X-Presto-Source", "") or "",
                req.headers.get("X-Presto-Idempotency-Key"))
        except OverloadedError as e:
            return self._overloaded(e)
        return self._json(200, q.results_json(self.base, 0))

    def _do_submit(self, sql, user, source, idem) -> "_Query":
        return self.coordinator.submit(sql, user=user, source=source,
                                       idempotency_key=idem)

    def _overloaded(self, e: OverloadedError) -> Response:
        """Load shed: refuse at the door with the advised back-off; the
        transport layer treats 503 + Retry-After as its own retry class
        and sleeps exactly this interval."""
        body = json.dumps({"error": {
            "message": str(e),
            "errorName": "SERVER_OVERLOADED",
            "errorType": "INSUFFICIENT_RESOURCES",
            "retryAfterSeconds": e.retry_after_s}}).encode()
        return Response(503, body,
                        headers={"Retry-After": f"{e.retry_after_s:g}"})

    async def _poll_async(self, server: AioHttpServer, req: Request,
                          qid: str, token: int):
        if self._dead(server):
            return None
        co = self.coordinator
        q = co.queries.get(qid)
        if q is None:
            # multi-coordinator failover: a client re-resolving a dead
            # peer's nextUri here may be asking about a query this
            # coordinator never saw — adopt it from the shared journal
            # (disk I/O -> executor) under its ORIGINAL qid
            q = await server.run_blocking(co.adopt, qid)
        if q is None:
            return self._json(404, {"error": "no query"})
        # long-poll briefly while the query runs: park on the done
        # event's callback, zero threads held
        if not q.done.is_set():
            evt, wake = server.waiter()
            q.done.add_callback(wake)
            try:
                await asyncio.wait_for(evt.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
            finally:
                q.done.remove_callback(wake)
        if self._dead(server):   # killed mid-poll: die silently
            return None
        return self._json(200, q.results_json(self.base, token))

    # ------------------------------------------------------ sync router
    def handle(self, req: Request) -> Optional[Response]:
        server = self.coordinator.httpd
        if self._dead(server):
            return None
        if req.method == "POST":
            return self._post(req)
        if req.method == "GET":
            resp = self._get(req)
            if resp is None and self._dead(server):
                return None
            return resp
        if req.method == "DELETE":
            return self._delete(req)
        return self._json(404, {"error": "no route"})

    def _post(self, req: Request) -> Response:
        path = req.path
        m = _INGEST.match(path)
        if m:
            return self._do_ingest(req, *m.groups())
        if path != "/v1/statement":
            return self._json(404, {"error": "no route"})
        sql = req.body.decode()
        try:
            q = self.coordinator.submit(
                sql,
                user=req.headers.get("X-Presto-User", "") or "",
                source=req.headers.get("X-Presto-Source", "") or "",
                idempotency_key=req.headers.get(
                    "X-Presto-Idempotency-Key"))
        except OverloadedError as e:
            return self._overloaded(e)
        return self._json(200, q.results_json(self.base, 0))

    def _do_ingest(self, req: Request, catalog: str, schema: str,
                   table: str) -> Response:
        """Streaming-append batch: JSON ``{"rows": [[...], ...]}`` in,
        commit receipt (rows, post-append version, cumulative row
        count) out. The append itself is admitted through the ingest
        resource-group tenant inside IngestManager — the HTTP handler
        neither executes nor schedules anything itself."""
        from presto_tpu.stream.ingest import IngestError

        try:
            body = json.loads(req.body.decode() or "{}")
            rows = body["rows"]
            if not isinstance(rows, list):
                raise IngestError("'rows' must be a list of rows")
        except (ValueError, KeyError) as e:
            return self._json(400, {"error": f"bad ingest body: {e}"})
        try:
            receipt = self.coordinator.ingest(
                catalog, schema, table, rows)
        except IngestError as e:
            return self._json(400, {"error": str(e)})
        except QueryQueueFull as e:
            return self._json(429, {"error": str(e)})
        return self._json(200, receipt)

    def _get(self, req: Request) -> Optional[Response]:
        path = req.path
        m = _EXECUTING.match(path) or _QUEUED.match(path)
        if m:
            # threaded fallback for the nextUri poll (normally served
            # async): same adopt + bounded wait semantics
            q = self.coordinator.queries.get(m.group(1))
            if q is None:
                q = self.coordinator.adopt(m.group(1))
            if q is None:
                return self._json(404, {"error": "no query"})
            q.done.wait(timeout=1.0)
            if self._dead(self.coordinator.httpd):
                return None     # killed mid-poll: die silently
            return self._json(200, q.results_json(self.base,
                                                  int(m.group(2))))
        if path == "/v1/query":
            # the query list (QueryResource.getAllQueryInfo role —
            # the UI's landing data)
            co = self.coordinator
            return self._json(200, [_query_info(q)
                                    for q in list(co.queries.values())])
        if path.startswith("/v1/query/"):
            q = self.coordinator.queries.get(path.rsplit("/", 1)[-1])
            if q is None:
                return self._json(404, {"error": "no query"})
            return self._json(200, _query_info(q))
        if path == "/v1/metrics":
            # same process-global registry the workers render — on the
            # coordinator a scrape additionally shows transport/breaker
            # counters for every worker host it talks to; process
            # gauges + scrape histogram via the shared scrape path
            from presto_tpu.obs.process import render_metrics_payload
            _M_COORD_UPTIME.set(_time.time() - _COORD_START)
            return Response(200, render_metrics_payload().encode(),
                            content_type="text/plain; version=0.0.4")
        if path == "/v1/alerts":
            # the alert engine's full state: every rule with its
            # current state machine position, plus the transition
            # history ring (matches system.runtime.alerts rows)
            eng = getattr(self.coordinator.engine, "alerts", None)
            if eng is None:
                return self._json(200, {"alerts": [],
                                        "transitions": []})
            return self._json(200, {"alerts": eng.snapshot(),
                                    "transitions": eng.transitions()})
        if path == "/v1/profile":
            # coordinator-side collapsed stacks (the profiler is
            # process-global, so in-process workers show here too)
            from presto_tpu.obs.profiler import PROFILER
            return Response(200, (PROFILER.collapsed() + "\n").encode(),
                            content_type="text/plain; charset=utf-8")
        if path == "/v1/ha/admission":
            # the peer-gossip surface: this coordinator's stride-WFQ
            # admission totals, polled by every peer's AdmissionGossip
            # so shedding/quotas act on cluster totals
            co = self.coordinator
            rgs = co.resource_groups
            return self._json(200, {
                "coordinatorId": co.coordinator_id,
                "queued": rgs.total_queued(),
                "running": rgs.total_running(),
                "draining": co.draining,
                "ts": _time.time()})
        if path == "/v1/status":
            # coordinator NodeStatus: uptime, role, query counts, and
            # the engine memory pool as the heap proxy
            co = self.coordinator
            qs = list(co.queries.values())
            eng = co.engine
            pool = getattr(eng, "memory_pool", None)
            rgs = co.resource_groups
            return self._json(200, {
                "nodeId": co.coordinator_id, "role": "coordinator",
                "environment": "tpu",
                "uptime": f"{_time.time() - _COORD_START:.2f}s",
                "uptimeSeconds": _time.time() - _COORD_START,
                "queryCount": len(qs),
                "runningQueries": sum(
                    1 for q in qs if not q.done.is_set()),
                "taskCount": 0,
                "heapUsed": pool.reserved if pool is not None else 0,
                "heapAvailable": 16 << 30, "nonHeapUsed": 0,
                # serving-tier snapshot: event-loop connection counts,
                # async vs executor route split, loop lag ticks
                "net": co.httpd.stats(),
                # per-group admission stats (reference:
                # ResourceGroupInfo on the cluster resource): live
                # queue depth / running plus lifetime counters per row
                "resourceGroups": (
                    {name: stats for name, stats in rgs.info()}
                    if rgs is not None else {}),
                # front-door snapshot: pool occupancy, queue-wait
                # percentiles, shed counters and thresholds
                "admission": co.dispatcher.snapshot(),
                # write-ahead journal state (None when crash recovery
                # is not configured) + the engine's membership view
                "journal": (co.journal.stats()
                            if co.journal is not None else None),
                "membership": (eng.membership_snapshot()
                               if hasattr(eng, "membership_snapshot")
                               else None),
                # multi-coordinator HA view: peers, drain state,
                # adoption count, and the gossip round snapshot
                "ha": {"coordinatorId": co.coordinator_id,
                       "peers": list(co.peers),
                       "draining": co.draining,
                       "adoptions": co.adoptions,
                       "gossip": (co.gossip.snapshot()
                                  if co.gossip is not None else None)},
                # alert-engine summary (full detail at /v1/alerts):
                # which rules are firing and every rule's state
                "alerts": self._alerts_block()})
        m = _TRACE.match(path)
        if m:
            # stitched cross-node span dump for one query id (worker
            # spans appear here after the cluster scraped them)
            return self._json(200, TRACER.to_json(m.group(1)))
        if path == "/v1/cluster":
            # ClusterStatsResource role: the cluster-overview numbers
            # the reference UI polls (running/queued/finished counts,
            # worker membership, memory reservation)
            co = self.coordinator
            qs = list(co.queries.values())
            queued = sum(1 for q in qs if q.state == "QUEUED")
            running = sum(1 for q in qs
                          if not q.done.is_set()
                          and q.state != "QUEUED")
            failed = sum(1 for q in qs
                         if q.done.is_set() and q.error is not None)
            finished = sum(1 for q in qs
                           if q.done.is_set() and q.error is None)
            eng = co.engine
            workers = list(getattr(eng, "worker_uris", []) or [])
            mem = 0
            pool = getattr(eng, "memory_pool", None)
            if pool is not None:
                mem = pool.reserved
            return self._json(200, {
                "runningQueries": running,
                "queuedQueries": queued,
                "finishedQueries": finished,
                "failedQueries": failed,
                "trackedQueries": len(qs),
                "activeWorkers": len(workers),
                "workers": workers,
                "reservedMemoryBytes": mem,
            })
        return self._json(404, {"error": f"no route {path}"})

    def _alerts_block(self) -> Optional[dict]:
        eng = getattr(self.coordinator.engine, "alerts", None)
        if eng is None:
            return None
        return {"firing": eng.firing(),
                "states": {a["rule"]: a["state"]
                           for a in eng.snapshot()}}

    def _delete(self, req: Request) -> Response:
        m = _CANCEL.match(req.path)
        if m:
            co = self.coordinator
            q = co.queries.get(m.group(1))
            if q is not None:
                q.cancelled = True
                co.cancel(q)
            return Response(204)         # no body with 204
        return self._json(404, {"error": "no route"})


class StatementServer:
    """The coordinator's client-facing HTTP surface over any engine with
    execute_sql/plan_sql (TpuCluster or LocalEngine).

    Multi-coordinator HA: N StatementServers run as symmetric peers
    over one shared ``QueryJournal`` file (pass the same
    ``elastic.journal_path`` and distinct ``coordinator_id``s, then
    wire the peer sets with :meth:`set_peers`). Every accepted
    statement is journaled with its owner; a peer that receives a
    nextUri poll for a query it never saw adopts it from the journal
    under the ORIGINAL qid (:meth:`adopt`), and peers gossip their
    stride-WFQ admission totals so shedding acts on cluster totals."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 admission=None, resource_groups=None, elastic=None,
                 coordinator_id: str = "tpu-coordinator", peers=()):
        self.engine = engine
        self.coordinator_id = coordinator_id
        self.peers: List[str] = []
        self.draining = False
        self.adoptions = 0
        self.gossip = None
        self._started = False
        # coordinator crash recovery: with a journal path configured
        # (ElasticConfig.journal_path) every accepted statement is
        # write-ahead journaled and re-queued by recover() on restart
        self.elastic = elastic if elastic is not None else DEFAULT_ELASTIC
        self.journal = (QueryJournal(
            self.elastic.journal_path,
            compact_threshold=self.elastic.journal_compact_threshold)
            if self.elastic.journal_path else None)
        # share the engine's resource groups when it has them so the
        # front door and the engine agree on admission state (the
        # engine's own acquire becomes a no-op under the dispatcher)
        self.resource_groups = (resource_groups
                                or getattr(engine, "resource_groups",
                                           None)
                                or ResourceGroupManager())
        self.admission_config = admission or DEFAULT_ADMISSION
        self.dispatcher = DispatchManager(
            self.resource_groups, self.admission_config,
            memory_pool=getattr(engine, "memory_pool", None))
        # observability time-dimension wiring (engines without a
        # telemetry plane — LocalEngine — skip both): the shedder
        # reads the cluster-wide windowed queue-wait p99 from the
        # telemetry history instead of its private sliding window,
        # and the journal append-age gauge refreshes on every scrape
        # so the JournalAppendStalled alert evaluates a live value
        telemetry = getattr(engine, "telemetry", None)
        if telemetry is not None:
            self.dispatcher.shedder.attach_history(
                lambda: telemetry.windowed_quantile(
                    "presto_tpu_admission_queue_wait_seconds"))
            if self.journal is not None:
                telemetry.add_refresher(
                    lambda: self.journal.stats())
        self.queries: Dict[str, _Query] = {}
        # client idempotency key -> qid: POST /v1/statement is
        # auto-retried by the transport, and a retry after a LOST
        # response must attach to the already-running query instead of
        # re-executing the SQL (an INSERT/CTAS replay would silently
        # duplicate rows)
        self._idempotency: Dict[str, str] = {}
        self._submit_lock = threading.Lock()
        # the front door: asyncio event loop + bounded executor (see
        # presto_tpu/net/aio_server.py) — POST /v1/statement and the
        # nextUri long-poll are async-native, everything else dispatches
        # through the executor. Port is bound in the ctor.
        self.app = StatementApp(self)
        self.httpd = AioHttpServer(self.app, host, port,
                                   role="coordinator")
        self.httpd.coordinator = self
        self.port = self.httpd.port
        self.base = f"http://{host}:{self.port}"
        self.httpd.base = self.base
        self._thread = spawn("coordinator", "statement-http",
                             self.httpd.serve_forever, start=False)
        # introspection plane: the system connector unions this front
        # door's live dispatcher states into system.runtime.queries via
        # this back-reference; the wide-event sink and profiler start
        # here too so a statement-only deployment still gets both.
        # With multiple peer coordinators over one engine every
        # instance also registers in statement_frontends, so
        # system.runtime.nodes can list coordinator rows per peer.
        setattr(engine, "statement_frontend", self)
        fronts = getattr(engine, "statement_frontends", None)
        if fronts is None:
            fronts = []
            setattr(engine, "statement_frontends", fronts)
        fronts.append(self)
        if peers:
            self.set_peers(peers)
        from presto_tpu.obs.profiler import PROFILER
        from presto_tpu.obs.wide_events import install_event_log_sink
        install_event_log_sink()
        PROFILER.ensure_started()

    def set_peers(self, peers) -> None:
        """Declare the peer coordinator set (base URIs; this server's
        own base is filtered out, so the full fleet list can be passed
        symmetrically to every member). Rewires the admission gossip
        and points the LoadShedder's queue-depth signal at cluster
        totals."""
        from presto_tpu.server.ha import AdmissionGossip
        self.peers = [p.rstrip("/") for p in peers
                      if p.rstrip("/") != self.base]
        if self.gossip is not None:
            self.gossip.stop()
            self.gossip = None
        if self.peers:
            self.gossip = AdmissionGossip(
                self.coordinator_id, self.resource_groups, self.peers)
            self.dispatcher.shedder.cluster_queued = \
                self.gossip.cluster_queued
            if self._started:
                self.gossip.start()
        else:
            self.dispatcher.shedder.cluster_queued = None

    #: completed queries kept for /v1/query info (QueryTracker role)
    MAX_TRACKED = 200

    def submit(self, sql: str, user: str = "", source: str = "",
               idempotency_key: Optional[str] = None) -> _Query:
        with self._submit_lock:
            if idempotency_key is not None:
                known = self._idempotency.get(idempotency_key)
                dup = self.queries.get(known) if known else None
                if dup is not None:
                    return dup          # retried POST: do NOT re-execute
            if self.draining:
                # graceful shutdown: refuse new work with the standard
                # 503 + Retry-After so the client's failover loop moves
                # to a peer coordinator instead of erroring out
                raise OverloadedError(
                    "coordinator draining",
                    self.admission_config.retry_after_s)
            # shed BEFORE registering: a refused statement must leave
            # no trace (the client retries with the same idempotency
            # key and must get a fresh admission decision)
            self.dispatcher.shedder.check()
            qid = f"{uuid.uuid4().hex[:16]}"
            q = _Query(qid, sql, user=user)
            self.queries[qid] = q
            if idempotency_key is not None:
                self._idempotency[idempotency_key] = qid
            if len(self.queries) > self.MAX_TRACKED:
                # FIFO-evict finished queries (dict preserves insertion
                # order), and drop idempotency entries with them.
                # Delivered queries go first: evicting a finished query
                # whose owner hasn't fetched the final batch yet 404s
                # its next poll — under a 1000-client storm that's a
                # dropped query. Undelivered ones are only reclaimed
                # past a 10x hard cap (memory bound beats the SLO only
                # when the registry is genuinely blowing up).
                for old_id in list(self.queries):
                    if len(self.queries) <= self.MAX_TRACKED:
                        break
                    old = self.queries[old_id]
                    if old.done.is_set() and old.delivered:
                        del self.queries[old_id]
                hard_cap = self.MAX_TRACKED * 10
                if len(self.queries) > hard_cap:
                    for old_id in list(self.queries):
                        if len(self.queries) <= hard_cap:
                            break
                        if self.queries[old_id].done.is_set():
                            del self.queries[old_id]
                self._idempotency = {
                    k: v for k, v in self._idempotency.items()
                    if v in self.queries}
        # write-ahead: journal the statement BEFORE dispatch so a
        # coordinator crash between admission and completion leaves a
        # recoverable record (group path is advisory — selection is
        # deterministic on (user, source), so recovery re-selects it)
        if self.journal is not None:
            self.journal.append(qid, sql=sql, user=user, source=source,
                                group=self._group_path(user, source),
                                state="QUEUED",
                                owner=self.coordinator_id)
        try:
            self._dispatch(q, user=user, source=source)
        except OverloadedError:
            with self._submit_lock:
                self.queries.pop(qid, None)
                if idempotency_key is not None:
                    self._idempotency.pop(idempotency_key, None)
            raise
        return q

    def ingest(self, catalog: str, schema: str, table: str,
               rows) -> dict:
        """POST /v1/ingest/{catalog}/{schema}/{table} backend: one
        shared IngestManager per engine (lazy; tenant group + counters
        live there)."""
        from presto_tpu.stream.ingest import ingest_manager
        return ingest_manager(self.engine).append(
            catalog, schema, table, rows)

    def _group_path(self, user: str, source: str) -> Optional[str]:
        try:
            return self.resource_groups.select(
                user=user, source=source).path
        except Exception:   # noqa: BLE001 — the path is advisory
            return None

    def _dispatch(self, q: _Query, user: str, source: str) -> None:
        """Route one registered _Query through the admission
        dispatcher, with journal appends on every lifecycle transition.
        Raises OverloadedError (shed); queue-full failures close the
        query cleanly instead."""

        def _on_state(state: str, error) -> None:
            q.dispatch_state = state
            if state == _dispatch.FAILED and error is not None \
                    and not q.done.is_set():
                # rejected before execution (queue full, queue-timeout
                # eviction, cancelled while queued): q.run never ran,
                # so close the protocol query here
                q.error = f"{type(error).__name__}: {error}"[:500]
                if isinstance(error, QueryQueueFull):
                    q.error_name = "QUERY_QUEUE_FULL"
                    q.error_type = "INSUFFICIENT_RESOURCES"
                q.state = "FAILED"
                _M_QUERIES.inc(state="FAILED")
                q.done.set()
                if self.journal is not None:
                    self.journal.append(q.qid, state="FAILED")

        def _run() -> None:
            if self.journal is not None:
                self.journal.append(q.qid, state="RUNNING")
            q.run(self.engine)
            if self.journal is not None:
                self.journal.append(q.qid, state=q.state)

        try:
            q._handle = self.dispatcher.submit(
                _run, user=user, source=source,
                query_id=q.qid, listener=_on_state)
        except OverloadedError:
            raise
        except QueryQueueFull as e:
            _on_state(_dispatch.FAILED, e)      # clean rejection

    def recover(self) -> int:
        """Coordinator crash recovery: re-queue every journaled
        non-terminal query from a previous coordinator process through
        the admission front door, under the ORIGINAL query ids so
        clients polling pre-crash nextUris re-attach. QUEUED queries
        re-dispatch exactly like fresh submissions; RUNNING ones re-run
        — under ``retry_policy=TASK`` the re-execution absorbs any
        spools the previous run committed instead of redoing that work.
        Returns the number of queries re-queued."""
        if self.journal is None:
            return 0
        grace = float(getattr(self.elastic, "recover_grace_s", 0) or 0)
        if grace > 0:
            _time.sleep(grace)
        n = 0
        for rec in self.journal.pending():
            qid, sql = rec.get("qid"), rec.get("sql")
            if not qid or not sql or qid in self.queries:
                continue
            # a shared journal holds every peer's records: a restart
            # only re-queues its OWN (ownerless legacy records too);
            # a live peer's in-flight queries are not ours to re-run
            if rec.get("owner") not in (None, self.coordinator_id):
                continue
            user = rec.get("user", "") or ""
            requeues = int(rec.get("recoveries", 0) or 0)
            cap = int(getattr(self.elastic, "recover_max_requeues", 3))
            if requeues >= cap:
                # repeated crashes keep orphaning this query; abandon
                # it with a terminal record instead of letting an
                # unbounded recovery storm clog the admission queue
                q = _Query(qid, sql, user=user)
                q.error = (f"abandoned after {requeues} crash-recovery "
                           f"re-queues")
                q.state = "FAILED"
                q.done.set()
                with self._submit_lock:
                    self.queries[qid] = q
                self.journal.append(qid, state="FAILED",
                                    owner=self.coordinator_id)
                continue
            q = _Query(qid, sql, user=user)
            with self._submit_lock:
                self.queries[qid] = q
            self.journal.append(qid, state="QUEUED",
                                owner=self.coordinator_id,
                                recoveries=requeues + 1)
            try:
                self._dispatch(q, user=user,
                               source=rec.get("source", "") or "")
            except OverloadedError as e:
                # recovery never sheds silently: close the query with
                # the rejection so the journal reaches a terminal state
                q.error = f"{type(e).__name__}: {e}"[:500]
                q.state = "FAILED"
                q.done.set()
                self.journal.append(qid, state="FAILED")
                continue
            self.journal.mark_recovered()
            n += 1
        return n

    def adopt(self, qid: str) -> Optional[_Query]:
        """Multi-coordinator failover: take over a dead peer's
        journaled query under its ORIGINAL qid. Called when a client's
        nextUri poll lands here for a query this coordinator never
        registered — refresh the shared journal from disk (the peer's
        appends were never in our memory view), and if the record is
        live, re-queue it through our own admission front door.

        Terminal records are adoptable too: results live only in the
        owner's memory, so a query that FINISHED just before its owner
        died — with the client's poll still in flight — must be re-run
        here or the client can never fetch it. That re-execution is
        safe because adoption only triggers from an unanswered poll
        (the results were never delivered) and this statement surface
        is read-only analytics; a journaled FAILED query deterministic-
        ally re-delivers its error. Returns None when there is nothing
        adoptable (no journal, unknown qid, no recorded sql, or we are
        draining)."""
        if self.journal is None or self.draining:
            return None
        self.journal.refresh()
        rec = self.journal.get(qid)
        if rec is None or not rec.get("sql"):
            return None
        user = rec.get("user", "") or ""
        with self._submit_lock:
            dup = self.queries.get(qid)
            if dup is not None:
                return dup      # raced with another poll: one adoption
            q = _Query(qid, rec["sql"], user=user)
            self.queries[qid] = q
        # adoption is never capped (a live client is polling this qid)
        # but still counts toward the crash-recovery re-queue budget an
        # UNATTENDED restart honors in recover()
        self.journal.append(qid, state="QUEUED",
                            owner=self.coordinator_id,
                            recoveries=int(rec.get("recoveries", 0)
                                           or 0) + 1)
        try:
            self._dispatch(q, user=user,
                           source=rec.get("source", "") or "")
        except OverloadedError as e:
            # adoption never sheds silently — the client is already
            # polling this qid, so close it with the rejection
            q.error = f"{type(e).__name__}: {e}"[:500]
            q.state = "FAILED"
            q.done.set()
            self.journal.append(qid, state="FAILED")
            return q
        self.adoptions += 1
        _M_ADOPTIONS.inc()
        self.journal.mark_recovered()
        return q

    def cancel(self, q: _Query) -> bool:
        """Withdraw a statement still waiting for admission; running
        queries are only flagged (the engine call is uninterruptible,
        `_Query.run` reports the cancellation when it returns)."""
        h = getattr(q, "_handle", None)
        return h is not None and self.dispatcher.cancel(h)

    def start(self) -> "StatementServer":
        self._thread.start()
        self._started = True
        # crash recovery before the first client request lands: any
        # journaled non-terminal queries from a previous process are
        # back in the admission queue by the time start() returns
        if self.journal is not None:
            self.recover()
        if self.gossip is not None:
            self.gossip.start()
        return self

    def stop(self, drain_timeout_s: Optional[float] = None):
        """Graceful coordinator shutdown: stop accepting (draining
        submits shed with Retry-After so clients fail over), then
        bounded-wait for in-flight dispatch-pool queries to finish —
        the same drain discipline as the PR 10 worker drain — so a
        deliberately stopped coordinator journals/finishes what it can
        instead of abandoning in-flight queries."""
        self.draining = True
        timeout = (drain_timeout_s if drain_timeout_s is not None
                   else float(getattr(self.elastic, "drain_timeout_s",
                                      0) or 0))
        poll = float(getattr(self.elastic, "drain_poll_s", 0.05)
                     or 0.05)
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            with self._submit_lock:
                inflight = [q for q in self.queries.values()
                            if not q.done.is_set()]
            if not inflight:
                break
            _time.sleep(poll)
        if self.gossip is not None:
            self.gossip.stop()
        if self._thread.is_alive():     # shutdown() blocks forever
            self.httpd.shutdown()       # unless serve_forever runs
        self.httpd.server_close()
        self.dispatcher.stop()
        # deliberate decommission leaves the fleet registry; a KILLED
        # coordinator stays registered so system.runtime.nodes shows
        # the DEAD row
        fronts = getattr(self.engine, "statement_frontends", None)
        if fronts is not None:
            try:
                fronts.remove(self)
            except ValueError:
                pass

    def kill(self):
        """Crash simulation for chaos tests: no drain, no terminal
        journal appends. The journal handle is dropped FIRST so any
        still-running dispatch threads of this \"dead\" process cannot
        journal their outcomes — exactly the window a real crash
        leaves, which a surviving peer must repair by adoption."""
        self.draining = True
        self.journal = None
        # in-flight handler threads check this and tear their
        # connections instead of serving one last response
        self.httpd.dead = True
        if self.gossip is not None:
            self.gossip.stop()
        if self._thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()
        self.dispatcher.stop(timeout_s=0.0)


def run_statement(base_uri: str, sql: str, timeout_s: float = 600,
                  user: str = ""):
    """Client side of the protocol (StatementClientV1.advance loop):
    POST, then follow nextUri until it disappears; returns
    (columns, rows). Raises on FAILED."""
    import time

    from presto_tpu.protocol.transport import get_client

    client = get_client()
    # per-execute idempotency key: the transport auto-retries the POST,
    # and the server dedupes on the key so a retry after a lost
    # response attaches to the in-flight query instead of re-running
    # the SQL (which would duplicate INSERT/CTAS writes)
    headers = {"Content-Type": "text/plain",
               "X-Presto-Idempotency-Key": uuid.uuid4().hex}
    if user:
        headers["X-Presto-User"] = user
    payload = client.post(f"{base_uri}/v1/statement", sql.encode(),
                          headers=headers,
                          request_class="statement").json()
    columns, rows = None, []
    deadline = time.time() + timeout_s
    while True:
        if "error" in payload:
            raise RuntimeError(payload["error"]["message"])
        if payload.get("columns"):
            columns = payload["columns"]
        rows.extend(payload.get("data", []))
        nxt = payload.get("nextUri")
        if not nxt:
            return columns, rows
        if time.time() > deadline:
            raise TimeoutError(f"query {payload.get('id')} timed out")
        payload = client.get_json(nxt, request_class="statement")
