"""Cluster mesh execution tier: mesh-lowered worker tasks + ICI-backed
repartition exchange.

This module is the SINGLE sanctioned chokepoint for the ICI-vs-HTTP
exchange decision (analysis rule `ici-exchange-chokepoint`): only here
may code read or write the ICI exchange descriptor that rides the task
session properties, and only `plan_cluster_mesh` may decide that a
query's inter-stage bytes move over the mesh instead of HTTP.

Three pieces (SNIPPETS.md north-star: a TPU worker lowering operators
under a device mesh "with the repartition exchange implemented as an
all_to_all over the TPU ICI mesh"; PAPER.md's L6a TaskExecutor + L7
exchange layers are the reference analogue — swap the execution tier,
keep the coordinator protocol fixed):

  1. `MeshTaskRunner` — worker side. Owns this worker's mesh slice,
     advertises it (announcement properties + GET /v1/mesh), and
     executes eligible task fragments (join/agg-bearing, mesh-
     lowerable) on PR 6's `DistSplitExecutor` under shard_map with the
     packed per-dtype collectives and capacity annealing. ANY lowering
     failure falls back to the generic executor path byte-for-byte.

  2. ICI exchange descriptor — coordinator side the scheduler fuses a
     co-locatable multi-stage plan into ONE single-task fragment
     posted to a mesh worker; the worker's `DistSplitExecutor` re-runs
     exchange placement locally, so every cut that would have been an
     HTTP page pull lowers to a genuine `all_to_all`/`all_gather` over
     the mesh (parallel/shuffle.py). The descriptor stamped into the
     task's session properties is what marks those bytes as ICI-moved;
     tasks without it account nothing.

  3. `plan_cluster_mesh` — the placement policy: for an eligible query
     (session `cluster_mesh_enabled`, join/agg-bearing, 2..N fragments,
     no writers) probe live workers' mesh advertisements fresh (a
     draining worker retracts and is never chosen) and pick the widest
     slice. Non-co-located or degraded queries keep the HTTP path
     unchanged, so every chaos/recovery contract (spool fallback,
     retry_policy=TASK, churn) holds as-is.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Dict, Optional, Tuple

from presto_tpu.config import DEFAULT_MESH_TIER, MeshTierConfig
from presto_tpu.obs.metrics import counter, gauge
from presto_tpu.plan.nodes import (
    AggregationNode, JoinNode, PlanNode, TableWriterNode,
)

log = logging.getLogger("presto_tpu.mesh_tier")

_M_CLUSTER_TASKS = counter(
    "presto_tpu_mesh_cluster_tasks_total",
    "Cluster task fragments executed on the worker device-mesh tier")
_M_ICI_BYTES = counter(
    "presto_tpu_mesh_ici_exchange_bytes_total",
    "Exchange bytes moved over ICI mesh collectives in lieu of HTTP "
    "page pulls (descriptor-stamped co-located stages only)")
_M_FALLBACKS = counter(
    "presto_tpu_mesh_exchange_fallback_total",
    "Cluster-mesh decisions that degraded to the generic/HTTP path",
    ("reason",))
_M_COLOCATED = gauge(
    "presto_tpu_mesh_colocated_stages",
    "Producer/consumer stages the last cluster-mesh query co-located "
    "onto one mesh (0 when the query rode the HTTP path)")

#: the ONE place the descriptor property name is spelled — it rides the
#: task session properties like the dynamic-filter side channel and is
#: filtered out of worker Session construction by the known-property
#: filter in task_manager
_ICI_PROP = "x_ici_exchange"


# ---------------------------------------------------------------------------
# descriptor chokepoint
# ---------------------------------------------------------------------------
def stamp_ici_descriptor(props: Dict[str, str], desc: dict
                         ) -> Dict[str, str]:
    """Coordinator side: mark a stage's task properties as ICI-routed.
    The descriptor records the chosen mesh (group, ndev) and how many
    HTTP-path exchanges the fusion replaced."""
    props[_ICI_PROP] = json.dumps(desc, sort_keys=True)
    return props


def ici_descriptor(props: Optional[Dict[str, str]]) -> Optional[dict]:
    """Worker side: the stamped descriptor, or None for plain tasks.
    Garbage never raises — an unreadable descriptor means HTTP."""
    raw = (props or {}).get(_ICI_PROP)
    if not raw:
        return None
    try:
        desc = json.loads(raw)
    except (TypeError, ValueError):
        return None
    return desc if isinstance(desc, dict) else None


def _truthy(v: Any) -> bool:
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def _mesh_lowerable(plan: PlanNode) -> bool:
    """Join/agg-bearing and writer-free — the fragment shapes PR 6's
    dist executor lowers profitably; everything else stays generic."""
    bearing = False
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, TableWriterNode):
            return False
        if isinstance(n, (JoinNode, AggregationNode)):
            bearing = True
        stack.extend(n.children())
    return bearing


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------
class MeshTaskRunner:
    """Per-worker mesh slice owner: advertisement + mesh-lowered task
    execution with generic fallback."""

    def __init__(self, config: Optional[MeshTierConfig] = None):
        self.config = config if config is not None else DEFAULT_MESH_TIER
        self._lock = threading.Lock()
        #: flips False on drain (PR 10 sequence): a SHUTTING_DOWN
        #: worker must stop advertising so new stages never co-locate
        #: onto a draining mesh
        self._advertising = bool(self.config.enabled)
        self._mesh = None
        self._ndev: Optional[int] = None
        # internal tallies mirrored into GET /v1/status (ints, not
        # registry scrapes — the registry is process-global and shared
        # across in-process workers)
        self.cluster_tasks = 0
        self.ici_bytes = 0
        self.fallbacks: Dict[str, int] = {}
        self.last_error: Optional[str] = None

    # -- advertisement ----------------------------------------------------
    def ndev(self) -> int:
        """Devices in this worker's slice (0 when jax is unavailable).
        Lazy: the control plane must not import jax at module load."""
        if self._ndev is None:
            n = int(self.config.ndev)
            if n <= 0:
                try:
                    import jax
                    n = len(jax.devices())
                except Exception:   # noqa: BLE001 — no devices = no mesh
                    n = 0
            self._ndev = n
        return self._ndev

    def advertising(self) -> bool:
        with self._lock:
            return self._advertising and self.ndev() >= 1

    def retract(self) -> None:
        """Drain hook: stop advertising the slice immediately. Running
        mesh tasks finish; no new stage may co-locate here."""
        with self._lock:
            self._advertising = False

    def advertisement(self) -> dict:
        """The GET /v1/mesh body — probed FRESH by the coordinator per
        mesh-eligible query so a draining worker is never chosen."""
        adv = self.advertising()
        return {"meshGroup": self.config.mesh_group,
                "meshDevices": self.ndev() if adv else 0,
                "advertising": adv}

    def announce_properties(self) -> Dict[str, str]:
        """Extra announcement properties (server/announcer.py payload):
        the slice rides the same discovery surface as the http URI."""
        if not self.advertising():
            return {}
        return {"meshGroup": self.config.mesh_group,
                "meshDevices": str(self.ndev())}

    def status_block(self) -> dict:
        """The `clusterMesh` block of the worker's GET /v1/status."""
        with self._lock:
            return {"advertising": self._advertising,
                    "meshGroup": self.config.mesh_group,
                    "meshDevices": self._ndev,
                    "clusterTasks": self.cluster_tasks,
                    "iciExchangeBytes": self.ici_bytes,
                    "fallbacks": dict(self.fallbacks),
                    "lastError": self.last_error}

    def note_fallback(self, reason: str) -> None:
        _M_FALLBACKS.inc(reason=reason)
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    # -- execution --------------------------------------------------------
    def _ensure_mesh(self):
        with self._lock:
            if self._mesh is None:
                from presto_tpu.parallel.mesh import device_mesh
                self._mesh = device_mesh(self.ndev())
            return self._mesh

    def try_run(self, tm, task, plan: PlanNode,
                props: Dict[str, str]) -> Optional[Tuple[Any, Any]]:
        """Attempt mesh-lowered execution of a task fragment. Returns
        (host page, executor) on success, None to fall back to the
        generic path — the caller's ladder then runs unchanged, so a
        mesh failure can degrade service but never the answer."""
        desc = ici_descriptor(task.session_properties)
        if desc is None and not _truthy(props.get(
                "cluster_mesh_enabled", "false")):
            return None
        if not self.config.enabled:
            if desc is not None:
                self.note_fallback("disabled")
            return None
        if not self.advertising():
            self.note_fallback("draining")
            return None
        if getattr(task, "remote_splits", None):
            # fragments with remote inputs pull producer pages over
            # HTTP — the generic path owns that protocol
            if desc is not None:
                self.note_fallback("remote_inputs")
            return None
        if not _mesh_lowerable(plan):
            if desc is not None:
                self.note_fallback("not_lowerable")
            return None
        try:
            mesh = self._ensure_mesh()
        except Exception as e:      # noqa: BLE001 — no mesh, no tier
            self.last_error = f"mesh: {e}"
            self.note_fallback("no_mesh")
            return None
        try:
            from presto_tpu.config import PROPERTIES, Session
            from presto_tpu.exec.dist_executor import DistSplitExecutor
            known = {p.name for p in PROPERTIES}
            sprops = {k: v for k, v in props.items() if k in known}
            ex = DistSplitExecutor(tm.connector, mesh,
                                   session=Session(sprops))
            if getattr(tm, "memory_pool", None) is not None:
                ex.memory_pool = tm.memory_pool
                ex.pool_query_id = task.task_id
            ex.set_splits(task.splits)
            out = ex.execute(plan)
            page = self._to_host_page(out, ex.ndev)
        except Exception as e:      # noqa: BLE001 — degrade, never fail
            self.last_error = f"{type(e).__name__}: {e}"
            self.note_fallback("lowering_error")
            log.debug("mesh lowering failed for %s; generic fallback",
                      getattr(task, "task_id", "?"), exc_info=True)
            return None
        _M_CLUSTER_TASKS.inc()
        with self._lock:
            self.cluster_tasks += 1
        if desc is not None:
            # these bytes moved over ICI collectives INSTEAD of the
            # HTTP exchange the unfused plan would have run
            wire = int((ex.last_mesh_stats or {}).get("wire_bytes", 0))
            if wire > 0:
                _M_ICI_BYTES.inc(wire)
                with self._lock:
                    self.ici_bytes += wire
        return page, ex

    @staticmethod
    def _to_host_page(out, ndev: int):
        """Collapse a stacked (device-leading) page to one host page;
        ndev==1 executes unstacked already."""
        if ndev == 1:
            return out
        from presto_tpu.data.column import concat_pages_host
        from presto_tpu.parallel.mesh import unstack_page
        return concat_pages_host(unstack_page(out))


# ---------------------------------------------------------------------------
# coordinator side
# ---------------------------------------------------------------------------
def ici_bytes_total() -> float:
    """Process-total ICI exchange bytes — the coordinator brackets this
    around a query for the per-query delta (same process-global-registry
    assumption the wide-event mesh block already makes)."""
    return _M_ICI_BYTES.value()


def fallbacks_total() -> float:
    """Label-summed process total of mesh exchange fallbacks."""
    return sum(v for _n, _ln, _lv, v in _M_FALLBACKS.samples())


def set_colocation_gauge(n: int) -> None:
    _M_COLOCATED.set(float(n))


def note_plan_fallback(reason: str) -> None:
    """Coordinator-side fallback accounting (no runner instance)."""
    _M_FALLBACKS.inc(reason=reason)


def plan_cluster_mesh(cluster, plan: PlanNode, n_fragments: int
                      ) -> Optional[dict]:
    """THE ICI-vs-HTTP decision. For an eligible query, pick a mesh
    worker and return the mesh plan::

        {"worker": uri, "group": g, "ndev": n, "descriptor": {...}}

    The caller (cluster.py) fuses the stage plan into one single-task
    fragment on that worker and stamps the descriptor; returning None
    keeps the HTTP path byte-for-byte."""
    props = cluster.session_properties
    if not _truthy(props.get("cluster_mesh_enabled", "false")):
        return None
    cfg = getattr(cluster, "mesh_config", None) or DEFAULT_MESH_TIER
    if not cfg.colocate:
        note_plan_fallback("colocate_disabled")
        return None
    if n_fragments < 2:
        # nothing to co-locate — single-fragment plans still mesh-lower
        # worker-side, they just have no exchange to re-route
        return None
    if n_fragments > cfg.max_colocate_fragments:
        note_plan_fallback("too_wide")
        return None
    if _truthy(props.get("exchange_materialization_enabled", "false")):
        note_plan_fallback("batch_mode")
        return None
    if not _mesh_lowerable(plan):
        note_plan_fallback("not_lowerable")
        return None
    best: Optional[Tuple[str, dict]] = None
    for uri in cluster.worker_uris:
        try:
            adv = cluster.http.request(f"{uri}/v1/mesh",
                                       request_class="probe").json()
        except Exception:   # noqa: BLE001 — unreachable = not a candidate
            continue
        if not adv.get("advertising") or int(
                adv.get("meshDevices") or 0) < 1:
            continue
        if best is None or (int(adv["meshDevices"])
                            > int(best[1]["meshDevices"])):
            best = (uri, adv)
    if best is None:
        note_plan_fallback("no_mesh")
        return None
    uri, adv = best
    ndev = int(adv["meshDevices"])
    desc = {"group": adv.get("meshGroup", cfg.mesh_group),
            "ndev": ndev,
            "colocated_stages": n_fragments - 1}
    return {"worker": uri, "group": desc["group"], "ndev": ndev,
            "descriptor": desc}
