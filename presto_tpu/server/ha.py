"""Multi-coordinator HA: the peer admission gossip (resource-manager
view).

Reference: the disaggregated coordinator of Presto's L1 split
(QueuedStatementResource dispatchers in front of a ResourceManager
holding cluster-wide admission state) — here collapsed to symmetric
peers: every coordinator serves ``GET /v1/ha/admission`` with its own
stride-WFQ totals (admission/groups.py already exposes per-group
running/queued), and every coordinator polls its peers on the
heartbeat/announce path. The folded view makes the LoadShedder's
queue-depth signal act on CLUSTER totals instead of this
coordinator's slice.

Failure handling is purely freshness-based, the same passive discipline
as announcement expiry in discovery.py: an unreachable peer simply ages
out of the view; coordinator death needs no extra failure detector.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Sequence

from presto_tpu.obs.metrics import counter as _counter, gauge as _gauge
from presto_tpu.utils.threads import spawn

log = logging.getLogger("presto_tpu.ha")

_M_GOSSIP_ROUNDS = _counter(
    "presto_tpu_coordinator_ha_gossip_rounds_total",
    "Completed admission-gossip polling rounds against peer "
    "coordinators")
_M_PEER_QUEUED = _gauge(
    "presto_tpu_coordinator_ha_peer_queued",
    "Queued statements reported by fresh peer coordinators (summed; "
    "the remote half of the cluster-wide shed signal)")


class AdmissionGossip:
    """Background exchange of per-coordinator admission totals.

    One instance per ``StatementServer`` with peers configured; the
    loop pulls every peer's ``/v1/ha/admission`` on ``interval_s`` and
    keeps a freshness-bounded view.  ``cluster_queued()`` is wired into
    the LoadShedder so shedding/quotas see the cluster-wide backlog.
    """

    def __init__(self, coordinator_id: str, groups,
                 peers: Sequence[str], interval_s: float = 0.5,
                 freshness_s: float = 5.0, client=None):
        from presto_tpu.protocol.transport import get_client
        self.coordinator_id = coordinator_id
        self.groups = groups
        self.peers = [p.rstrip("/") for p in peers]
        self.interval_s = interval_s
        self.freshness_s = freshness_s
        self.client = client or get_client()
        self.rounds = 0
        self._view: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self._thread = spawn("coordinator", "ha-gossip", self._loop,
                             start=False)

    # ------------------------------------------------------------ rounds
    def poll_once(self) -> int:
        """One gossip round; returns how many peers answered. Errors
        are absorbed — a dead peer's entry just goes stale."""
        ok = 0
        for peer in self.peers:
            try:
                doc = self.client.get_json(f"{peer}/v1/ha/admission",
                                           request_class="announce",
                                           timeout=2.0)
            except Exception:   # noqa: BLE001 — dead peers age out
                continue
            cid = doc.get("coordinatorId") or peer
            with self._lock:
                self._view[cid] = {
                    "uri": peer,
                    "queued": int(doc.get("queued") or 0),
                    "running": int(doc.get("running") or 0),
                    "draining": bool(doc.get("draining")),
                    "ts": time.time()}
            ok += 1
        self.rounds += 1
        _M_GOSSIP_ROUNDS.inc()
        _M_PEER_QUEUED.set(self.peer_queued())
        return ok

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:   # noqa: BLE001 — the loop must survive
                log.warning("gossip round failed; continuing",
                            exc_info=True)

    # -------------------------------------------------------------- view
    def _fresh(self) -> Dict[str, dict]:
        now = time.time()
        with self._lock:
            return {cid: dict(v) for cid, v in self._view.items()
                    if now - v["ts"] <= self.freshness_s
                    and cid != self.coordinator_id}

    def peer_queued(self) -> int:
        return sum(v["queued"] for v in self._fresh().values())

    def peer_running(self) -> int:
        return sum(v["running"] for v in self._fresh().values())

    def cluster_queued(self) -> int:
        """The REMOTE queued total; the LoadShedder adds its own local
        count, making the queue-depth shed signal cluster-wide."""
        return self.peer_queued()

    def snapshot(self) -> dict:
        return {"rounds": self.rounds, "peers": self._fresh()}

    # --------------------------------------------------------- lifecycle
    def start(self) -> "AdmissionGossip":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
