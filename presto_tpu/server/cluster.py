"""Multi-worker cluster: a coordinator-side scheduler driving N TPU
workers over the real task protocol.

Reference roles folded into TpuCluster:
  - SqlQueryScheduler / SectionExecutionFactory
    (execution/scheduler/SqlQueryScheduler.java:115,356): walk the
    fragment tree leaf-first, decide task counts and placement.
  - HttpRemoteTask (server/remotetask/HttpRemoteTaskWithEventLoop.java:981):
    build TaskUpdateRequests (fragment bytes, splits, output buffer ids)
    and POST them to /v1/task/{taskId}.
  - StageLinkage: wire producer task locations into consumer tasks as
    remote splits (RemoteSplit.location -> the producer's results URI).
  - the coordinator's root-stage ExchangeClient: pull the root fragment's
    buffers and decode rows for the client.

Every byte between coordinator and workers rides HTTP exactly as the
Java/C++ pairing does; inside each worker the fragment still executes as
one jit program (and on a real multi-chip worker, over the ICI mesh via
the DistExecutor — HTTP across hosts, collectives within a host,
SURVEY.md §5.8)."""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from presto_tpu.config import DEFAULT_OBS, TransportConfig
from presto_tpu.obs.metrics import counter as _obs_counter, \
    gauge as _obs_gauge
from presto_tpu.plan.fragment import add_exchanges, create_fragments
from presto_tpu.plan.iterative import reorder_joins
from presto_tpu.plan.stats import (
    HistoryStore, canonical_key, default_history_path, estimate_rows,
)
from presto_tpu.utils.threads import spawn
from presto_tpu.utils.tracing import TRACER, trace_scope
from presto_tpu.plan.nodes import ExchangeNode, Partitioning, PlanNode
from presto_tpu.protocol import structs as S
from presto_tpu.protocol.exchange import (
    ExchangeClient, exchange_counters, stream_pages,
)
from presto_tpu.protocol.to_protocol import FragmentSpec, \
    constrain_split_payload, fragment_to_protocol, remote_split_payload
from presto_tpu.protocol.transport import (FatalResponseError,
                                           HttpClient, TransportError)
from presto_tpu.server.http import TpuWorkerServer

log = logging.getLogger("presto_tpu.cluster")

_M_MERGE_HIGH = _obs_gauge(
    "presto_tpu_merge_inflight_high_water",
    "Max in-flight row batches during bounded k-way root merges")

# elastic-membership counters (Presto@Meta VLDB'23 §3 fluid worker
# membership): admissions into, and departures from, the schedulable set
_M_MEMBER_JOINS = _obs_counter(
    "presto_tpu_membership_joins_total",
    "Workers admitted to the schedulable set (first announcement or "
    "re-admission after death/drain)")
_M_MEMBER_DEPARTURES = _obs_counter(
    "presto_tpu_membership_departures_total",
    "Workers removed from the schedulable set by the failure detector")
_M_MEMBER_DRAINS = _obs_counter(
    "presto_tpu_membership_drains_total",
    "Workers that left the schedulable set via graceful decommission "
    "(SHUTTING_DOWN)")


def _unshare(plan: PlanNode) -> PlanNode:
    """Duplicate shared subtrees (mark joins reference the probe pipeline
    twice) so the fragmenter emits independent producer fragments per
    consumer. The in-worker ICI path evaluates shared subtrees once; the
    HTTP path re-executes them — the reference does the same unless CTE
    materialization is enabled (optimizations/PhysicalCteOptimizer.java)."""
    import copy

    seen = set()

    def visit(n: PlanNode) -> PlanNode:
        if id(n) in seen:
            n = copy.deepcopy(n)
        seen.add(id(n))
        kids = n.children()
        if not kids:
            return n
        repl = {}
        names = [f.name for f in dataclasses.fields(n)]
        if "probe" in names:
            repl["probe"] = visit(n.probe)
            repl["build"] = visit(n.build)
        elif "source" in names and n.source is not None:
            repl["source"] = visit(n.source)
        return dataclasses.replace(n, **repl)

    return visit(plan)


def _derange(plan: PlanNode):
    """Distributed ORDER BY in the HTTP cluster: the ROOT sort's RANGE
    exchange is dropped entirely — each task sorts its own shard and the
    COORDINATOR k-way merges the sorted page streams (the ordered merge
    exchange, operator/MergeOperator.java + MergeHashSort.java). Peak
    per-worker memory stays O(shard); the coordinator holds one page per
    stream. Returns (plan', merge_keys or None). Any OTHER RANGE
    exchange (nested sorts) still degrades to a SINGLE gather: range
    splitters need a sampling pass the streaming protocol doesn't carry;
    the in-worker ICI path (DistExecutor) keeps true range exchanges."""
    from presto_tpu.plan.nodes import OutputNode, SortNode

    merge_keys = None
    if isinstance(plan, OutputNode) \
            and isinstance(plan.source, SortNode) \
            and isinstance(plan.source.source, ExchangeNode) \
            and plan.source.source.partitioning == Partitioning.RANGE:
        sort = plan.source
        local_sort = dataclasses.replace(sort, source=sort.source.source)
        plan = dataclasses.replace(plan, source=local_sort)
        merge_keys = tuple(sort.keys)

    def visit(n: PlanNode) -> PlanNode:
        kids = n.children()
        if not kids:
            return n
        repl = {}
        names = [f.name for f in dataclasses.fields(n)]
        if "probe" in names:
            repl["probe"] = visit(n.probe)
            repl["build"] = visit(n.build)
        elif "source" in names and n.source is not None:
            repl["source"] = visit(n.source)
        n = dataclasses.replace(n, **repl)
        if isinstance(n, ExchangeNode) \
                and n.partitioning == Partitioning.RANGE:
            n = dataclasses.replace(n, partitioning=Partitioning.SINGLE,
                                    keys=(), sort_keys=())
        return n
    return visit(plan), merge_keys


def bounded_merge(batch_sources, key, queue_pages=4):
    """K-way merge of pre-sorted row-batch streams under a COORDINATOR
    memory bound (reference: MergeOperator + ExchangeClient's
    maxBufferedBytes back-pressure). One producer thread per stream
    decodes batches into a `queue.Queue(maxsize=queue_pages)`; a full
    queue blocks its producer (and, through the page protocol, stops
    acknowledging frames), so at most ``k * (queue_pages + 2)`` row
    batches exist coordinator-side at once instead of every run fully
    materialized before the merge. The consumer side feeds
    ``heapq.merge`` — streams stay sorted, output is the total order.

    ``batch_sources`` is a list of zero-arg callables each returning an
    iterator of row batches (lists of tuples). Returns
    ``(rows, in_flight_high_water)``. The first real producer failure is
    re-raised after all producers stop; sibling streams abort instead of
    draining to completion."""
    import heapq
    import queue as _queue

    n = len(batch_sources)
    if n == 0:
        return [], 0
    queues = [_queue.Queue(maxsize=queue_pages) for _ in range(n)]
    done = [False] * n
    failed = threading.Event()
    cause: List[BaseException] = []
    lock = threading.Lock()
    in_flight = [0]
    high_water = [0]

    def produce(i):
        try:
            for batch in batch_sources[i]():
                if not batch:
                    continue
                with lock:
                    in_flight[0] += 1
                    if in_flight[0] > high_water[0]:
                        high_water[0] = in_flight[0]
                while True:
                    if failed.is_set():
                        return
                    try:
                        queues[i].put(batch, timeout=0.05)
                        break
                    except _queue.Full:
                        continue
        except BaseException as e:   # noqa: BLE001 — propagated below
            if not failed.is_set():
                cause.append(e)      # the REAL failure, not a sibling's
            failed.set()             # abort placeholder
        finally:
            done[i] = True

    def stream(i):
        while True:
            try:
                batch = queues[i].get(timeout=0.05)
            except _queue.Empty:
                if failed.is_set():
                    raise ClusterQueryError(
                        "merge input stream failed; aborting merge")
                if done[i] and queues[i].empty():
                    return
                continue
            with lock:
                in_flight[0] -= 1
            for row in batch:
                yield row

    threads = [spawn("coordinator", f"merge-produce-{i}", produce,
                     args=(i,), start=False)
               for i in range(n)]
    for t in threads:
        t.start()
    try:
        rows = list(heapq.merge(*(stream(i) for i in range(n)), key=key))
    except BaseException:
        failed.set()                 # release blocked producers
        for t in threads:
            t.join(timeout=5)
        if cause:
            raise cause[0]
        raise
    for t in threads:
        t.join(timeout=5)
    if cause:
        raise cause[0]
    return rows, high_water[0]


@dataclasses.dataclass
class _Stage:
    spec: FragmentSpec
    n_tasks: int
    n_buffers: int
    # consumer fragment id -> first buffer index it owns (shared
    # SINGLE/BROADCAST producers give each consumer a disjoint range)
    buffer_offset: Dict[int, int] = dataclasses.field(default_factory=dict)
    task_ids: List[str] = dataclasses.field(default_factory=list)
    task_uris: List[str] = dataclasses.field(default_factory=list)
    # scan-node id -> (connector id, per-task split payloads); kept so
    # task-level recovery re-posts the SAME lifespans elsewhere
    scan_splits: Dict = dataclasses.field(default_factory=dict)
    recovered_tasks: int = 0
    # retry_policy=TASK bookkeeping: task indices whose COMMITTED spool
    # absorbed a dead worker (never re-executed, never re-polled), and
    # the committed attempt's task id consumers should read
    spool_done: set = dataclasses.field(default_factory=set)
    spool_task_ids: Dict[int, str] = dataclasses.field(
        default_factory=dict)
    # cross-exchange dynamic filtering (reference: DynamicFilterService):
    # a build stage publishes its join-key domain on this output channel;
    # a probe stage carries the spec of the filter it should wait for,
    # and — once merged — the constraint injected into its scan splits.
    # The constraint lives HERE so recovery re-posts reproduce it.
    df_publish_channel: Optional[int] = None
    df_spec: Optional[dict] = None
    df_constraint: Optional[dict] = None
    # cluster mesh tier (server/mesh_tier.py): the mesh worker this
    # fused stage should land on, and the ICI exchange descriptor its
    # task properties carry. Kept on the stage so recovery re-posts
    # re-stamp the SAME descriptor (a survivor re-runs mesh-lowered or
    # falls back generic — either way oracle-exact).
    mesh_worker: Optional[str] = None
    mesh_descriptor: Optional[dict] = None


class ClusterQueryError(RuntimeError):
    pass


class ClusterMemoryKillError(ClusterQueryError):
    """EXCEEDED_MEMORY_LIMIT class: the cluster low-memory killer chose
    this query (ClusterMemoryManager.maybe_kill). Terminal — recovery
    paths must NEVER retry or re-execute a killed query, even under
    retry_policy=TASK."""


class _ClusterSubqueryExec:
    """Adapter exposing Executor._resolve_subqueries over the cluster:
    `execute` routes nested plans through the cluster and returns rows."""

    def __init__(self, cluster: "TpuCluster"):
        self.cluster = cluster

    def execute(self, plan):
        return self.cluster._execute_plan(plan)

    def _page_rows(self, rows):
        return rows

    def _resolve_subqueries(self, plan):
        from presto_tpu.exec.executor import Executor
        return Executor._resolve_subqueries(self, plan)


class TpuCluster:
    """N in-process workers + the scheduler. `workers` may also be
    attached to externally-started servers via `worker_uris`."""

    def __init__(self, connector, n_workers: int = 2,
                 session_properties: Optional[Dict[str, str]] = None,
                 resource_groups=None, history=None, discovery=None,
                 shared_secret: Optional[str] = None,
                 transport_config: Optional[TransportConfig] = None,
                 cache_config=None, spool_config=None,
                 exchange_config=None, mv_config=None,
                 mv_journal_path: Optional[str] = None,
                 memory_config=None, obs_config=None,
                 mesh_config=None):
        import dataclasses as _dc

        from presto_tpu.cache import AffinityRouter
        from presto_tpu.config import DEFAULT_EXCHANGE, DEFAULT_SPOOL
        from presto_tpu.server.resource_groups import ResourceGroupManager
        from presto_tpu.sql.analyzer import Planner

        # internal-communication JWT (InternalCommunicationConfig
        # sharedSecret + internalJwtEnabled): the coordinator signs its
        # requests; workers enforce
        self.shared_secret = shared_secret
        if shared_secret:
            from presto_tpu.server.auth import configure
            configure(shared_secret, "tpu-coordinator")

        # introspection facade: `system.*` tables answer from this
        # cluster's live state, everything else delegates to the real
        # connector. Wrapped FIRST so the planner and every in-process
        # worker (which share the object) see one catalog; the cluster
        # reference is attached at the end of construction.
        from presto_tpu.connectors.system_runtime import \
            SystemTablesConnector
        if not isinstance(connector, SystemTablesConnector):
            connector = SystemTablesConnector(connector)

        self.connector = connector
        self.planner = Planner(connector)
        # HBO store (plan/stats.HistoryStore) consulted by AddExchanges'
        # broadcast-vs-repartition costing AND fed back from the workers'
        # observed cardinalities at query end (cluster-fed HBO; reference:
        # HistoryBasedPlanStatisticsCalculator.java:58 paired with the
        # tracker that records actuals). A default in-memory store makes
        # the second run of a repeated query history-informed even
        # without explicit wiring; PRESTO_TPU_HBO_CACHE persists it.
        self.history = (history if history is not None
                        else HistoryStore(default_history_path()))
        self.last_hbo = {"hits": 0, "misses": 0}
        self.last_join_reorders = 0
        self.session_properties = dict(session_properties or {})
        # admission control (reference: InternalResourceGroupManager
        # gating DispatchManager.createQueryInternal)
        self.resource_groups = resource_groups or ResourceGroupManager()
        # discovery-driven membership (reference: DiscoveryNodeManager):
        # workers that announce to `discovery` join the schedulable set
        # alongside the statically started ones.
        self.discovery = discovery
        self.cache_config = cache_config
        # concurrent-exchange knobs: the coordinator's own root collect
        # AND every worker's upstream pulls share one config
        self.exchange_config = (exchange_config
                                if exchange_config is not None
                                else DEFAULT_EXCHANGE)
        # spooled exchange (retry_policy=TASK): the coordinator opens
        # the shared spool base FIRST (sweeping orphans when attaching
        # to an existing base), then hands every worker a config
        # pointing at the SAME directory — the local-FS stand-in for
        # disaggregated storage (Presto@Meta VLDB'23 §3)
        scfg = spool_config if spool_config is not None else DEFAULT_SPOOL
        task_retry = str(self.session_properties.get(
            "retry_policy", "")).strip().upper() == "TASK"
        self.spool = None
        self.spool_config = scfg
        if scfg.enabled or task_retry:
            from presto_tpu.spool.store import SpoolStore
            self.spool = SpoolStore(_dc.replace(scfg, enabled=True))
            self.spool_config = _dc.replace(
                scfg, enabled=True, base_dir=self.spool.base_dir,
                sweep_on_start=False)
        # worker memory arbitration (exec/memory.py): every in-process
        # worker gets a real MemoryPool sized from MemoryConfig; the
        # coordinator holds the cluster view over those pools for the
        # low-memory killer, and gossips per-query reservations to
        # admission on the heartbeat path
        from presto_tpu.config import DEFAULT_MEMORY
        mcfg = memory_config if memory_config is not None \
            else DEFAULT_MEMORY
        self.memory_config = mcfg
        # cluster mesh tier (server/mesh_tier.py): one config governs
        # the coordinator's co-location policy AND every in-process
        # worker's slice advertisement
        from presto_tpu.config import DEFAULT_MESH_TIER
        self.mesh_config = (mesh_config if mesh_config is not None
                            else DEFAULT_MESH_TIER)
        self.last_cluster_mesh = None
        self.workers: List[TpuWorkerServer] = [
            TpuWorkerServer(connector, node_id=f"tpu-worker-{i}",
                            shared_secret=shared_secret,
                            cache_config=cache_config,
                            spool_config=self.spool_config,
                            exchange_config=exchange_config,
                            memory_config=memory_config,
                            mesh_config=self.mesh_config).start()
            for i in range(n_workers)]
        self.cluster_memory = None
        if mcfg.pool_bytes:
            from presto_tpu.exec.memory import ClusterMemoryManager
            pools = [w.task_manager.memory_pool for w in self.workers
                     if w.task_manager.memory_pool is not None]
            if pools:
                self.cluster_memory = ClusterMemoryManager(
                    pools,
                    budget_bytes=mcfg.cluster_budget(len(self.workers)))
        # heartbeat-gossiped cluster reservations ({qid: bytes} summed
        # over worker pools) — consumed by resource-group memory quotas
        self.cluster_reservations: Dict[str, int] = {}
        attach = getattr(self.resource_groups,
                         "attach_cluster_reservations", None)
        if attach is not None:
            attach(lambda: dict(self.cluster_reservations))
        # cache-affinity placement memory (reference: the coordinator's
        # fragment-result-cache-aware NetworkLocationCache / soft
        # affinity SplitPlacementPolicy): remembers which worker holds a
        # fragment fingerprint so repeat queries land on the warm cache
        self.affinity = AffinityRouter()
        self.all_worker_uris = [f"http://127.0.0.1:{w.port}"
                                for w in self.workers]
        self.dead: set = set()
        # graceful-decommission set: workers that reported SHUTTING_DOWN
        # (or answered a task POST with the draining 410). They leave
        # the schedulable set WITHOUT a breaker penalty; their running
        # tasks finish and their committed spools stay readable.
        self.drained: set = set()
        # THE membership lock: every read of the schedulable set and
        # every dead/drained mutation flows through _membership() under
        # this lock (membership-chokepoint rule) so a failure-detector
        # sweep can never interleave with a scheduler's placement
        # snapshot and observe half-applied state
        self._membership_lock = threading.Lock()
        self._members_seen: set = set(self.all_worker_uris)
        self.membership_stats = {"joins": 0, "departures": 0,
                                 "drains": 0}
        # this cluster's fault-tolerant RPC chokepoint: per-worker
        # circuit breakers + per-request-class retry policies; chaos
        # tests install a FaultInjector on it
        self.http = HttpClient(config=transport_config)
        self._query_counter = 0
        self._lock = threading.Lock()
        self._plans: Dict[str, PlanNode] = {}
        # materialized views (presto_tpu/mv/): manager is lazy — built
        # on the first MV statement — so query-only clusters pay
        # nothing; a journal path makes definitions restart-durable
        self.mv_config = mv_config
        self.mv_journal_path = mv_journal_path
        self._mv_manager = None
        # introspection plane: system tables can now see this cluster;
        # the wide-event JSONL sink registers (a no-op without a
        # configured path) and the sampling profiler starts
        connector.attach_cluster(self)
        from presto_tpu.obs.profiler import PROFILER
        from presto_tpu.obs.wide_events import install_event_log_sink
        install_event_log_sink()
        PROFILER.ensure_started()
        # telemetry history + alerting (obs/tsdb.py, obs/alerts.py):
        # the scraper rides check_workers' heartbeat cadence — every
        # sweep snapshots the coordinator registry plus each live
        # worker's /v1/metrics into the TSDB, then the alert engine
        # evaluates its catalog against the history just written
        from presto_tpu.config import DEFAULT_OBS
        from presto_tpu.obs.alerts import AlertEngine
        from presto_tpu.obs.tsdb import Telemetry
        self.obs_config = (obs_config if obs_config is not None
                           else DEFAULT_OBS)
        self.telemetry = Telemetry(self.obs_config)
        self.alerts = AlertEngine(self.telemetry.store,
                                  config=self.obs_config)
        # first history point at t=0 via one real probe round: the
        # probes dial the client pool, so the coordinator's transport
        # series exist BEFORE the first query and its bracket pair can
        # show the query's delta (a bare local sweep here would miss
        # every counter that is born on first use)
        self.check_workers()

    @property
    def worker_uris(self) -> List[str]:
        return self._membership()

    def _membership(self, dead_add=(), dead_remove=(), drained_add=(),
                    drained_remove=()) -> List[str]:
        """THE membership chokepoint (membership-chokepoint rule):
        every read of the schedulable worker set and every mutation of
        the dead/drained sets happens inside this one lock. Callers
        collect probe verdicts FIRST (RPCs never run under the lock)
        and apply them here in one shot, so scheduling snapshots always
        see a consistent membership state. Returns the live URI list:
        static workers plus fresh discovery announcements, minus dead
        and draining nodes."""
        with self._membership_lock:
            for u in dead_add:
                if u not in self.dead:
                    # lint: disable=membership-chokepoint
                    self.dead.add(u)
                    self.membership_stats["departures"] += 1
                    _M_MEMBER_DEPARTURES.inc()
            for u in dead_remove:
                if u in self.dead:
                    # lint: disable=membership-chokepoint
                    self.dead.discard(u)
                    self.membership_stats["joins"] += 1
                    _M_MEMBER_JOINS.inc()
            for u in drained_add:
                if u not in self.drained:
                    # lint: disable=membership-chokepoint
                    self.drained.add(u)
                    self.membership_stats["drains"] += 1
                    _M_MEMBER_DRAINS.inc()
            for u in drained_remove:
                if u in self.drained:
                    # lint: disable=membership-chokepoint
                    self.drained.discard(u)
                    self.membership_stats["joins"] += 1
                    _M_MEMBER_JOINS.inc()
            uris = list(self.all_worker_uris)
            if self.discovery is not None:
                uris += [u for u in self.discovery.active_workers()
                         if u not in uris]
            # forget dead/drained entries that are neither static nor
            # announced: they cannot re-enter placement without a fresh
            # announcement, which re-evaluates them anyway — without
            # this, continuous churn grows the sets without bound
            known = set(uris)
            for u in [u for u in self.dead if u not in known]:
                # lint: disable=membership-chokepoint
                self.dead.discard(u)
            for u in [u for u in self.drained if u not in known]:
                # lint: disable=membership-chokepoint
                self.drained.discard(u)
            live = [u for u in uris if u not in self.dead
                    and u not in self.drained]
            for u in live:
                if u not in self._members_seen:
                    self._members_seen.add(u)
                    self.membership_stats["joins"] += 1
                    _M_MEMBER_JOINS.inc()
            return live

    def _probe_candidates(self) -> List[str]:
        """Every URI the failure detector should probe: static workers,
        fresh discovery announcements, and currently dead/drained nodes
        (the re-admission path needs to see them answer again). Built
        under the membership lock; the probes themselves run outside."""
        with self._membership_lock:
            uris = list(self.all_worker_uris)
            if self.discovery is not None:
                uris += [u for u in self.discovery.active_workers()
                         if u not in uris]
            uris += [u for u in sorted(self.dead) if u not in uris]
            uris += [u for u in sorted(self.drained) if u not in uris]
            return uris

    def membership_snapshot(self) -> dict:
        """Locked point-in-time membership view (EXPLAIN ANALYZE's
        "Membership:" line and status surfaces)."""
        live = self._membership()
        with self._membership_lock:
            return {"live": len(live), "dead": len(self.dead),
                    "drained": len(self.drained),
                    **self.membership_stats}

    # ---------------------------------------------------- failure detector
    def check_workers(self) -> List[str]:
        """Active liveness probe (reference:
        failureDetector/HeartbeatFailureDetector.java:76 + the
        discovery-announcement timeout in DiscoveryNodeManager): probe
        /v1/info/state so one sweep yields both verdicts — unreachable
        workers are marked dead so the scheduler stops placing tasks on
        them (and RE-ADMITTED when they answer again), and workers
        reporting SHUTTING_DOWN move to the drained set while their
        running tasks finish and their spools stay readable. Dead
        workers keep being probed through the circuit breaker: while
        its breaker is OPEN the probe fast-fails without touching the
        network; once the cooldown elapses the half-open state lets
        exactly one real probe through, and a restarted worker rejoins
        the schedulable set instead of staying banned forever. All
        verdicts are applied through the single locked membership
        chokepoint; the probe RPCs run outside it. Returns the live
        URI list."""
        dead_add: List[str] = []
        dead_remove: List[str] = []
        drained_add: List[str] = []
        drained_remove: List[str] = []
        for uri in self._probe_candidates():
            try:
                state = self.http.get_json(f"{uri}/v1/info/state",
                                           request_class="probe")
            except Exception:     # noqa: BLE001 — any failure = dead node
                dead_add.append(uri)
                continue
            if str(state).upper() == "SHUTTING_DOWN":
                drained_add.append(uri)
                dead_remove.append(uri)
            else:
                if uri in self.dead:
                    log.info("worker %s recovered; re-admitting", uri)
                dead_remove.append(uri)
                drained_remove.append(uri)
        live = self._membership(
            dead_add=dead_add, dead_remove=dead_remove,
            drained_add=drained_add, drained_remove=drained_remove)
        if self.memory_config.pool_bytes:
            self._scrape_memory(live)
        self._scrape_telemetry(live)
        return live

    def _scrape_memory(self, live: List[str]) -> None:
        """Heartbeat-path memory gossip: pull every live worker's
        /v1/memory pool snapshot and aggregate per-query reservations
        into the cluster view that admission quotas consult. A failed
        scrape keeps the previous view — stale beats empty (an empty
        view would wave oversized queries through)."""
        agg: Dict[str, int] = {}
        ok = False
        for uri in live:
            try:
                mem = self.http.get_json(f"{uri}/v1/memory",
                                         request_class="probe")
            except Exception:   # noqa: BLE001 — dead node, next sweep
                continue
            ok = True
            by_query = (mem.get("memoryPool") or {}).get(
                "queryReservations") or {}
            for qid, b in by_query.items():
                agg[qid] = agg.get(qid, 0) + int(b)
        if ok or not live:
            self.cluster_reservations = agg

    def _scrape_telemetry(self, live: List[str],
                          force: bool = False) -> None:
        """Heartbeat-path telemetry sweep: coordinator registry plus
        every live worker's /v1/metrics into the history store, then
        one alert-evaluation round over what was just written. The
        scraper self-throttles (sweep spacing + overhead budget) and
        never raises — history is advisory, probing is not. `force`
        (the query brackets) bypasses the spacing throttle; bracket
        callers pass no workers, so a forced sweep never adds
        per-query worker HTTP fetches."""
        try:
            swept = self.telemetry.scrape(
                workers=live,
                fetch=lambda uri: self.http.request(
                    f"{uri}/v1/metrics",
                    request_class="probe").body.decode(
                        "utf-8", "replace"),
                force=force)
            if swept:
                self.alerts.evaluate()
        except Exception:   # noqa: BLE001 — advisory plane only
            log.exception("telemetry sweep failed; continuing")

    def decommission(self, worker_uri: str,
                     timeout_s: Optional[float] = None) -> dict:
        """Gracefully drain one worker: PUT /v1/info/state
        "SHUTTING_DOWN" (the native worker's node-state shutdown
        protocol) and mark it drained through the membership
        chokepoint. The PUT blocks until the worker's running tasks
        finished and committed their spools (or its drain timeout
        elapsed), so on return the node holds no live work and new
        queries schedule around it. Returns the worker's drain
        report."""
        import json as _json
        from presto_tpu.config import DEFAULT_ELASTIC
        wait_s = (DEFAULT_ELASTIC.drain_timeout_s
                  if timeout_s is None else timeout_s)
        resp = self.http.request(
            f"{worker_uri}/v1/info/state", method="PUT",
            body=_json.dumps("SHUTTING_DOWN").encode(),
            headers={"Content-Type": "application/json"},
            request_class="control", timeout=wait_s + 10.0,
            attempts=1)
        self._membership(drained_add=[worker_uri])
        return resp.json()

    def start_heartbeat(self, interval_s: float = 5.0) -> "TpuCluster":
        """Periodic background liveness prober (reference:
        failureDetector/HeartbeatFailureDetector.java:76 — continuous
        monitoring, not only the on-failure probe): dead workers leave
        the schedulable set BEFORE the next query fails on them."""
        self._hb_stop = threading.Event()

        def loop():
            while not self._hb_stop.wait(interval_s):
                try:
                    self.check_workers()
                except Exception:   # noqa: BLE001 — prober must survive
                    log.exception(
                        "heartbeat probe sweep failed; continuing")

        self._hb_thread = spawn("coordinator", "heartbeat", loop)
        return self

    def stop(self):
        hb = getattr(self, "_hb_stop", None)
        if hb is not None:
            hb.set()
        if self._mv_manager is not None:
            self._mv_manager.stop_refresher()
        for w in self.workers:
            w.stop()
        if self.spool is not None:
            self.spool.close()

    def _task_retry(self) -> bool:
        """Is stage-level recovery (retry_policy=TASK) active for this
        cluster's queries? Requires the spool store — without spooled
        outputs there is nothing sound to recover from."""
        return self.spool is not None and str(
            self.session_properties.get("retry_policy", "")
        ).strip().upper() == "TASK"

    # ------------------------------------------------------------------
    def plan_sql(self, sql: str) -> PlanNode:
        from presto_tpu.sql.parser import parse_sql
        if sql not in self._plans:
            self._plans[sql] = self.planner.plan_query(parse_sql(sql))
        return self._plans[sql]

    def execute_sql(self, sql: str,
                    _capture: bool = False,
                    cancel_event=None) -> List[tuple]:
        from presto_tpu.utils.tracing import query_lifecycle

        # plugin access control: the cluster is the network-exposed
        # entry point (statement server / DBAPI), so it must enforce the
        # security SPI exactly like LocalEngine
        from presto_tpu.spi import manager as _plugins
        user = self.session_properties.get("user", "")
        _plugins.check_can_execute(user, sql)
        _plugins.check_statement_access(
            user, sql,
            plan_full=lambda: self.plan_sql(sql),
            plan_query=self.planner.plan_query)

        with self._lock:
            self._query_counter += 1
            qid = f"cluster_q{self._query_counter}"
        # wide-event query log: exactly ONE event per cluster query id,
        # success or failure — recovery retries happen INSIDE the body,
        # so they can never duplicate it (obs/wide_events.py)
        from presto_tpu.obs import wide_events as _wide
        pre = _wide.pre_query_snapshot(self)
        # bracket the query with LOCAL-ONLY telemetry sweeps so
        # metrics_history holds a before/after pair for every
        # coordinator-side counter the query moved (transport,
        # admission, memory) even when the background heartbeat is not
        # running; worker registries ride the heartbeat cadence —
        # fetching them here would add one HTTP round-trip per worker
        # to every query
        self._scrape_telemetry((), force=True)
        try:
            with query_lifecycle(qid, sql) as box:
                group = self.resource_groups.select(
                    user=self.session_properties.get("user", ""),
                    source=self.session_properties.get("source", ""))
                # when the statement front door already admitted this
                # query (dispatcher pool thread), acquire returns a no-op
                # nested slot — admission happens once per statement
                slot = group.acquire(timeout_s=600, query_id=qid)
                self.last_admission = {
                    "group": slot.group.path,
                    "queue_wait_s": slot.queue_wait_s or 0.0}
                with slot:
                    head = (sql.lstrip().split(None, 1)[0].lower()
                            if sql.strip() else "")
                    if head == "explain":
                        from presto_tpu.plan.nodes import explain as _ex
                        rest = sql.lstrip()[len("explain"):].lstrip()
                        if rest.lower().startswith("analyze"):
                            text = self.explain_analyze_sql(
                                rest[len("analyze"):].lstrip())
                        else:
                            text = _ex(self.plan_sql(rest))
                        box[0] = [(line,) for line in text.splitlines()]
                    elif head in ("create", "insert", "drop",
                                  "delete", "refresh"):
                        box[0] = self._execute_write(sql)
                    else:
                        box[0] = self._execute_plan(
                            self.plan_sql(sql), capture=_capture,
                            cancel_event=cancel_event)
        except Exception as e:
            _wide.emit_wide_event(self, qid, sql, rows=None,
                                  error=str(e), pre=pre)
            raise
        _wide.emit_wide_event(self, qid, sql, rows=box[0], error=None,
                              pre=pre)
        self._scrape_telemetry((), force=True)
        return box[0]

    @property
    def mv_manager(self):
        """Lazy materialized-view manager (presto_tpu/mv/). Refresh
        work executes through this cluster's own execute_sql, so
        admission, task-retry recovery and wide events all apply."""
        if self._mv_manager is None:
            from presto_tpu.config import DEFAULT_MV
            from presto_tpu.mv.manager import MaterializedViewManager
            self._mv_manager = MaterializedViewManager(
                self.connector, run_sql=self.execute_sql,
                groups=self.resource_groups,
                config=self.mv_config or DEFAULT_MV,
                journal_path=self.mv_journal_path)
        return self._mv_manager

    def consume_mv_event(self) -> Optional[dict]:
        """Pop the calling thread's pending refresh annotation for the
        wide-event `mv` block (obs/wide_events.py) — None for queries
        that did not refresh a materialized view."""
        mgr = self._mv_manager
        return mgr.consume_event() if mgr is not None else None

    def _execute_mv(self, stmt) -> List[tuple]:
        """CREATE/REFRESH/DROP MATERIALIZED VIEW — coordinator-side
        metadata ops plus (for REFRESH) delta/full queries dispatched
        through the normal distributed path."""
        from presto_tpu.mv.manager import MVError
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.analyzer import AnalysisError

        try:
            if isinstance(stmt, A.CreateMaterializedView):
                self.mv_manager.create(stmt.name, stmt.sql,
                                       if_not_exists=stmt.if_not_exists)
                return [(0,)]
            if isinstance(stmt, A.RefreshMaterializedView):
                _kind, n = self.mv_manager.refresh(stmt.name)
                return [(n,)]
            self.mv_manager.drop(stmt.name, if_exists=stmt.if_exists)
            return [(0,)]
        except MVError as e:
            raise AnalysisError(str(e)) from e

    def _execute_write(self, sql: str) -> List[tuple]:
        """Distributed CTAS / INSERT ... SELECT: the coordinator runs the
        metadata DDL (CreateTableTask role), then schedules TableWriter
        fragments on the workers — each writes its partition of rows and
        reports a count; the coordinator sums them (TableFinish role).
        Literal-VALUES inserts and bare DDL run coordinator-side."""
        from presto_tpu.plan.nodes import TableWriterNode
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.analyzer import AnalysisError
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.types import BIGINT

        stmt = parse_statement(sql)
        conn = self.connector
        if isinstance(stmt, (A.CreateMaterializedView,
                             A.RefreshMaterializedView,
                             A.DropMaterializedView)):
            return self._execute_mv(stmt)
        if not hasattr(conn, "create"):
            raise AnalysisError("connector is not writable")
        query = getattr(stmt, "query", None)
        if query is None:
            # bare DDL / literal VALUES: coordinator-local metadata ops
            from presto_tpu.exec.engine import LocalEngine
            return LocalEngine(conn).execute_sql(sql)

        plan = self.planner.plan_query(query)
        if isinstance(stmt, A.CreateTableAs):
            if stmt.if_not_exists and conn.exists(stmt.name):
                return [(0,)]
            conn.create(stmt.name, list(zip(plan.output_names,
                                            plan.output_types)))
        elif not conn.exists(stmt.name):
            raise AnalysisError(f"unknown table {stmt.name}")
        if getattr(stmt, "columns", None):
            # INSERT (col list): map SELECT outputs to the declared
            # columns, NULL-fill the rest — same semantics as
            # LocalEngine's literal path (engine.py INSERT handling)
            from presto_tpu.expr.nodes import InputRef, Literal
            from presto_tpu.plan.nodes import ProjectNode
            from presto_tpu.types import UNKNOWN
            schema = conn.schema(stmt.name)
            names = [c for c, _t in schema]
            unknown = [c for c in stmt.columns if c not in names]
            if unknown:
                raise AnalysisError(
                    f"INSERT columns not in table: {unknown}")
            if len(stmt.columns) != len(plan.output_types):
                raise AnalysisError(
                    f"INSERT arity {len(plan.output_types)} != column "
                    f"list {len(stmt.columns)}")
            pos = {c: i for i, c in enumerate(stmt.columns)}
            exprs, types = [], []
            for c, t in schema:
                if c in pos:
                    i = pos[c]
                    exprs.append(InputRef(i, plan.output_types[i]))
                    types.append(plan.output_types[i])
                else:
                    exprs.append(Literal(None, UNKNOWN))
                    types.append(t)
            plan = ProjectNode(tuple(names), tuple(types), plan,
                               tuple(exprs))
        schema = conn.schema(stmt.name)
        if not getattr(stmt, "columns", None) \
                and len(plan.output_types) != len(schema):
            raise AnalysisError(
                f"INSERT arity {len(plan.output_types)} != table "
                f"{len(schema)}")
        # positional semantics: the i-th SELECT output feeds the i-th
        # table column (the column-list case pre-projected to schema
        # order above)
        # Atomic commit (reference: TableFinishOperator + ConnectorPageSink
        # commit — writes become visible only when the whole query
        # succeeds). CTAS targets are freshly created, so drop-on-failure
        # already gives atomicity; INSERT into an existing table stages
        # the task writes into a temp table and moves them into the
        # target only after every fragment finished.
        is_insert = not isinstance(stmt, A.CreateTableAs)
        target = stmt.name
        if is_insert:
            import uuid
            target = f"stage_{uuid.uuid4().hex[:12]}_{stmt.name}"
            conn.create(target, list(schema))
        writer = TableWriterNode(("rows",), (BIGINT,), source=plan,
                                 table=target,
                                 column_names=tuple(
                                     c for c, _t in schema))
        # Scaled writers (reference: execution/scheduler/
        # ScaledWriterScheduler.java + SystemSessionProperties
        # scale_writers/writer_min_size): writer-task count scales with
        # the estimated data volume instead of always using every
        # worker — small inserts get one writer (no N tiny files /
        # per-task commit overhead), big ones fan out. The reference
        # scales at runtime on buffer backlog; with static shapes the
        # volume is estimable at plan time, so admission picks the
        # count up front.
        writer_tasks = None
        if (self.session_properties.get("scale_writers", "true")
                .lower() != "false"):
            try:
                from presto_tpu.exec.executor import _row_bytes
                from presto_tpu.plan.stats import estimate_rows
                est_rows = estimate_rows(plan, conn, self.history)
                min_size = int(self.session_properties.get(
                    "writer_min_size", 32 * 1024 * 1024))
                est_bytes = max(est_rows, 1) * _row_bytes(
                    plan.output_types)
                writer_tasks = max(
                    1, -(-est_bytes // max(min_size, 1)))
            except Exception:   # noqa: BLE001 — estimate is advisory
                writer_tasks = None
        try:
            # NON-idempotent: never auto-retried (a partial write on a
            # surviving worker would duplicate rows; reference: streaming
            # INSERT failures fail the query)
            counts = self._execute_plan_once(writer,
                                             writer_tasks=writer_tasks)
        except Exception:
            if is_insert:
                conn.drop(target, if_exists=True)      # discard the stage
            else:
                conn.drop(stmt.name, if_exists=True)   # no partial CTAS
            raise
        if is_insert:
            # commit: one locked raw-array move (exact decimals, no
            # python-value round trip); any connector without the fast
            # path takes the page route. The stage is always dropped.
            try:
                if hasattr(conn, "move_table_rows"):
                    conn.move_table_rows(target, stmt.name)
                else:
                    t = conn.table(target)
                    cap = max(int(t.num_rows), 1)
                    page = t.page(columns=[c for c, _t in schema],
                                  capacity=cap)
                    conn.append_rows(stmt.name, page.to_pylist())
            finally:
                conn.drop(target, if_exists=True)
        return [(sum(int(r[0]) for r in counts if r[0] is not None),)]

    def explain_analyze_sql(self, sql: str) -> str:
        """Execute, then render per-fragment / per-operator row counts
        from the workers' TaskInfo stats trees (the coordinator's
        EXPLAIN ANALYZE surface over the wire). Stats capture adds one
        TaskInfo GET per task, so it is gated to this entry point."""
        rows = self.execute_sql(sql, _capture=True)
        by_frag: Dict[int, Dict[str, list]] = {}
        for fid, info in getattr(self, "last_task_infos", []):
            stats = info.get("stats") or {}
            for pipe in stats.get("pipelines", []):
                for op in pipe.get("operatorSummaries", []):
                    key = (op.get("planNodeId"), op.get("operatorType"))
                    agg = by_frag.setdefault(fid, {}).setdefault(
                        key, [0, 0, None])
                    agg[0] += int(op.get("outputPositions", 0))
                    agg[1] += 1
                    agg[2] = agg[2] or op.get("canonicalKey")
        lines = [f"EXPLAIN ANALYZE ({len(rows)} result rows)"]
        for fid in sorted(by_frag):
            lines.append(f"Fragment {fid}:")
            for (nid, op_type), (total, ntasks, ckey) in sorted(
                    by_frag[fid].items()):
                # estimates vs actuals: the history entry for this
                # operator's canonical subtree is what the NEXT planning
                # of an equivalent node will estimate
                known = (self.history.rows.get(ckey)
                         if self.history is not None and ckey else None)
                est = f"est_rows={int(known)} " if known is not None \
                    else ""
                lines.append(
                    f"  {op_type} [node {nid}]: {est}{total} rows "
                    f"across {ntasks} task(s)")
        cache_line = self._render_cache_stats(
            getattr(self, "last_task_infos", []))
        if cache_line:
            lines.append(cache_line)
        ex = getattr(self, "last_exchange_stats", None)
        if ex is not None:
            lines.append(
                f"Exchange: fetches={ex['fetches']} "
                f"pages={ex['pages']} bytes={ex['bytes']} "
                f"truncations={ex['truncations']} "
                f"buffered_bytes_hw={ex['buffered_bytes_high_water']} "
                f"buffer_depth_hw={ex['buffer_depth_high_water']}")
        cmesh = getattr(self, "last_cluster_mesh", None)
        if cmesh is not None:
            lines.append(
                f"Mesh: cluster=true worker={cmesh['worker']} "
                f"group={cmesh['group']} ndev={cmesh['ndev']} "
                f"colocated_stages={cmesh['colocated_stages']} "
                f"ici_bytes={cmesh['ici_bytes']} "
                f"fallbacks={cmesh['fallbacks']}")
        spool = getattr(self, "last_spool_stats", None)
        if spool is not None:
            lines.append(
                f"Spool: commits={spool['commits']} "
                f"bytes={spool['bytes_written']} "
                f"recoveries={spool['recoveries']} "
                f"fallback_reads={spool['fallback_reads']} "
                f"gc={spool['gc']}")
        adm = getattr(self, "last_admission", None)
        if adm is not None:
            lines.append(
                f"Admission: group={adm['group']} "
                f"queue_wait={adm['queue_wait_s']:.3f}s")
        if self.cluster_memory is not None:
            cm = self.cluster_memory
            pools = cm.pools
            lines.append(
                f"Memory: reserved={cm.cluster_reserved()} "
                f"budget={cm.cluster_budget()} "
                f"revocations={sum(p.revocations for p in pools)} "
                f"revoked_bytes={sum(p.revoked_bytes for p in pools)} "
                f"kills={cm.kills}")
        mem = getattr(self, "last_membership", None)
        if mem is not None:
            lines.append(
                f"Membership: live={mem['live']} dead={mem['dead']} "
                f"drained={mem['drained']} joins={mem['joins']} "
                f"departures={mem['departures']} "
                f"drains={mem['drains']}")
        hbo = getattr(self, "last_hbo", None) or {}
        df_pruned = sum(
            int((((info.get("stats") or {}).get("runtimeStats") or {})
                 .get("dynamicFilterRowsPruned") or {}).get("sum", 0))
            for _fid, info in getattr(self, "last_task_infos", []))
        lines.append(
            f"HBO: hits={hbo.get('hits', 0)} "
            f"misses={hbo.get('misses', 0)} "
            f"join_reorders={getattr(self, 'last_join_reorders', 0)} "
            f"dynamic_filter_rows_pruned={df_pruned}")
        from presto_tpu.obs.profiler import PROFILER
        ps = PROFILER.stats()
        lines.append(
            f"Profile: samples={ps['samples']} buckets={ps['buckets']} "
            f"overhead={PROFILER.overhead_fraction() * 100:.2f}%")
        trace = self.render_trace()
        if trace:
            lines.append(
                f"Trace {getattr(self, 'last_trace_id', '')}:")
            lines.extend("  " + ln for ln in trace.splitlines())
        return "\n".join(lines)

    # ---------------------------------------------------------- tracing
    def _scrape_worker_traces(self, trace_id: str) -> None:
        """GET /v1/trace/{id} from every worker and stitch the spans
        into the coordinator tracer (span_id dedupe makes this a no-op
        for in-process workers, which share the process tracer)."""
        for uri in self.worker_uris:
            try:
                doc = self.http.get_json(f"{uri}/v1/trace/{trace_id}",
                                         request_class="control")
                TRACER.merge_remote(trace_id, doc)
            except Exception:   # noqa: BLE001 — tracing is best-effort
                log.debug("trace scrape failed for %s", uri,
                          exc_info=True)

    def render_trace(self, query_id: Optional[str] = None) -> str:
        """One cross-node timeline for `query_id` (default: the most
        recent sampled query) — coordinator and worker spans under the
        same query trace id, sorted by start time."""
        qid = query_id or getattr(self, "last_trace_id", None)
        return TRACER.render(qid) if qid else ""

    @staticmethod
    def _render_cache_stats(infos) -> str:
        """Roll the workers' fragmentResultCache* runtime metrics up to
        one EXPLAIN ANALYZE line (reference: FragmentCacheStats surfaced
        through the native worker's runtime metrics). Per-task snapshots
        repeat their worker's process-wide counters, so store counters
        dedupe by worker (latest snapshot wins) while per-task hit flags
        sum directly."""
        per_worker: Dict[str, dict] = {}
        task_hits = 0
        cached_tasks = 0
        for _fid, info in infos:
            rt = (info.get("stats") or {}).get("runtimeStats") or {}
            if "fragmentResultCacheHitCount" not in rt:
                continue
            cached_tasks += 1
            task_hits += int(
                (rt.get("fragmentResultCacheHit") or {}).get("sum", 0))
            uri = str((info.get("taskStatus") or {}).get("self", ""))
            per_worker[uri.split("/v1/", 1)[0]] = rt
        if not per_worker:
            return ""

        def total(name: str) -> int:
            return sum(int((rt.get(name) or {}).get("sum", 0))
                       for rt in per_worker.values())

        return (f"Result cache: {task_hits}/{cached_tasks} tasks served "
                f"from cache; store hits={total('fragmentResultCacheHitCount')} "
                f"misses={total('fragmentResultCacheMissCount')} "
                f"evictions={total('fragmentResultCacheEvictionCount')} "
                f"bytes={total('fragmentResultCacheSizeBytes')}")

    def _execute_plan(self, plan: PlanNode, _retried: bool = False,
                      capture: bool = False,
                      cancel_event=None) -> List[tuple]:
        """Streaming-mode recovery (reference: a worker failure fails the
        query; the dispatcher retries on the surviving nodes once the
        failure detector excludes the dead worker)."""
        try:
            return self._execute_plan_once(plan, capture=capture,
                                           cancel_event=cancel_event)
        except ClusterMemoryKillError:
            raise                   # terminal: killed queries never retry
        except (ClusterQueryError, OSError) as e:
            if cancel_event is not None and cancel_event.is_set():
                raise
            before = set(self.worker_uris)
            alive = set(self.check_workers())
            if _retried or alive == before or not alive:
                if isinstance(e, ClusterQueryError):
                    raise
                # terminal transport failure: surface the query-level
                # contract (clean ClusterQueryError, cause chained) —
                # callers never see raw socket errors
                raise ClusterQueryError(
                    f"query failed on transport error: {e}") from e
            return self._execute_plan(plan, _retried=True,
                                      capture=capture,
                                      cancel_event=cancel_event)

    def _execute_plan_once(self, plan: PlanNode,
                           capture: bool = False,
                           cancel_event=None,
                           writer_tasks: Optional[int] = None
                           ) -> List[tuple]:
        # Uncorrelated scalar subqueries execute through the cluster
        # itself (recursively), not a local engine: distributed partial/
        # final aggregation orders float summation differently, and a
        # literal produced by a different pipeline would break exact
        # comparisons like Q15's total_revenue = (select max(...)).
        plan = _ClusterSubqueryExec(self)._resolve_subqueries(plan)
        from presto_tpu.config import PROPERTIES, Session
        known = {p.name for p in PROPERTIES}
        session = Session({k: v for k, v in
                           self.session_properties.items() if k in known})
        h0 = ((self.history.hits, self.history.misses)
              if self.history is not None else None)
        # history-first greedy join reordering (ReorderJoins): the
        # smaller estimated side becomes the hash build before the
        # exchange planner decides broadcast vs repartition on it
        self.last_join_reorders = 0
        if session["join_reordering_enabled"]:
            plan, self.last_join_reorders = reorder_joins(
                plan, self.connector, self.history)
        ex_plan, merge_keys = _derange(
            add_exchanges(_unshare(plan), self.connector, session,
                          self.history))
        frags = create_fragments(ex_plan)
        # cluster mesh tier (server/mesh_tier.py, THE ICI-vs-HTTP
        # chokepoint): an eligible multi-stage plan fuses into ONE
        # single-task fragment on a mesh worker — the worker re-plans
        # exchanges locally, so every cut that would have been an HTTP
        # page pull lowers to an ICI collective. None keeps the HTTP
        # path byte-for-byte.
        mesh_plan = None
        if writer_tasks is None:
            from presto_tpu.server.mesh_tier import plan_cluster_mesh
            mesh_plan = plan_cluster_mesh(self, plan, len(frags))
        if mesh_plan is not None:
            from presto_tpu.plan.fragment import PlanFragment
            frags = [PlanFragment(0, _unshare(plan),
                                  Partitioning.SINGLE, ())]
            merge_keys = None
        try:
            return self._run_fragments(frags, list(plan.output_types),
                                       capture=capture,
                                       merge_keys=merge_keys,
                                       cancel_event=cancel_event,
                                       writer_tasks=writer_tasks,
                                       mesh_plan=mesh_plan)
        finally:
            # planning-time HBO consultation delta for this query
            # (EXPLAIN ANALYZE's "HBO:" line)
            if h0 is not None:
                self.last_hbo = {
                    "hits": self.history.hits - h0[0],
                    "misses": self.history.misses - h0[1]}
            else:
                self.last_hbo = {"hits": 0, "misses": 0}

    # ------------------------------------------------------------------
    def _run_fragments(self, frags, out_types,
                       capture: bool = False, merge_keys=None,
                       writer_tasks: Optional[int] = None,
                       cancel_event=None, mesh_plan=None) -> List[tuple]:
        with self._lock:
            self._query_counter += 1
            qid = f"q{self._query_counter}_{int(time.time())}"
        by_id = {f.fragment_id: f for f in frags}

        consumers: Dict[int, List[int]] = {}
        for f in frags:
            for src in set(f.remote_sources):
                consumers.setdefault(src, []).append(f.fragment_id)
        for src, cons in consumers.items():
            if len(cons) > 1 and by_id[src].partitioning not in (
                    Partitioning.BROADCAST, Partitioning.SINGLE):
                raise NotImplementedError(
                    "partitioned producer shared by several consumer "
                    "fragments (CTE materialization boundary — planned)")

        # membership snapshot at query START fixes the task COUNTS (W)
        # for the whole query — buffer wiring and split assignment must
        # not shift once any stage is posted. PLACEMENT, by contrast,
        # re-snapshots per stage (see schedule()) so mid-query joins and
        # drains are visible to every not-yet-scheduled stage.
        placement = list(self.worker_uris)
        W = len(placement)
        self.last_membership = self.membership_snapshot()
        specs = {f.fragment_id: fragment_to_protocol(f, self.connector)
                 for f in frags}

        stages: Dict[int, _Stage] = {}

        # hash_partition_count (SystemSessionProperties.
        # HASH_PARTITION_COUNT): tasks per hash-partitioned intermediate
        # stage; 0 = one per worker
        hpc = 0
        try:
            hpc = int(float(self.session_properties.get(
                "hash_partition_count", 0) or 0))
        except (TypeError, ValueError):
            hpc = 0

        def n_tasks(fid: int) -> int:
            spec = specs[fid]
            if mesh_plan is not None:
                # the fused cluster-mesh plan runs as ONE task on the
                # chosen mesh worker — parallelism comes from the mesh
                # devices inside the program, not from task fan-out
                return 1
            if fid == 0 and writer_tasks is not None \
                    and spec.scan_nodes:
                # scaled writers: a SOURCE-partitioned (scan-fed)
                # writer fragment's parallelism follows the estimated
                # data volume; gathered shapes (SINGLE producers under
                # the writer) keep the plan-driven count
                self.last_writer_tasks = max(
                    1, min(int(writer_tasks), W))
                return self.last_writer_tasks
            if spec.scan_nodes:
                return W
            for pfid in spec.remote_nodes.values():
                if by_id[pfid].partitioning == Partitioning.HASH:
                    return hpc if hpc > 0 else W
            return 1

        for f in frags:
            cons = consumers.get(f.fragment_id, [])
            part = f.partitioning
            offsets: Dict[int, int] = {}
            nbuf = 0
            for c in cons:
                offsets[c] = nbuf
                if part == Partitioning.SINGLE and n_tasks(c) > 1:
                    # One buffer would be drained destructively by N
                    # consumer tasks, silently splitting the stream —
                    # needs per-task buffers + broadcast like _emit_output
                    # does for multi-buffer SINGLE.
                    raise NotImplementedError(
                        "SINGLE-partitioned producer feeding a "
                        f"multi-task consumer fragment {c}")
                nbuf += 1 if part == Partitioning.SINGLE else n_tasks(c)
            nbuf = max(nbuf, 1)
            stages[f.fragment_id] = _Stage(
                specs[f.fragment_id], n_tasks(f.fragment_id), nbuf,
                offsets)

        if mesh_plan is not None:
            stages[0].mesh_worker = mesh_plan["worker"]
            stages[0].mesh_descriptor = mesh_plan["descriptor"]

        self._plan_dynamic_filters(stages, by_id)

        # leaf-first scheduling (children before parents so producer task
        # locations exist when consumers are created); dynamic-filter
        # build stages go before their siblings so a probe stage's
        # bounded wait overlaps the build actually running
        scheduled = set()

        def schedule(fid: int):
            if fid in scheduled:
                return
            srcs = list(dict.fromkeys(by_id[fid].remote_sources))
            srcs.sort(key=lambda s:
                      0 if stages[s].df_publish_channel is not None
                      else 1)
            for src in srcs:
                schedule(src)
            # per-STAGE placement snapshot (mid-query join): a worker
            # that announced after the query started is schedulable for
            # every stage not yet placed, and one that began draining
            # stops receiving new stages — while task counts stay
            # pinned to the query-start W so buffer wiring never shifts
            # under running stages
            stage_placement = self.worker_uris or placement
            self._start_stage(qid, fid, stages, by_id, stage_placement)
            scheduled.add(fid)

        batch_mode = (str(self.session_properties.get(
            "exchange_materialization_enabled", ""))
            .strip().lower() == "true")

        #: bound on spool-recovery rounds per query — each round needs a
        #: fresh worker death to do anything, so this never limits a
        #: single-fault run; it stops a flapping cluster from spinning
        MAX_RECOVERY_ROUNDS = 5

        self.last_recovery_events = []
        spool_before = None
        if self.spool is not None:
            from presto_tpu.spool.store import spool_counters
            spool_before = spool_counters()
        # exchange activity this query: counter deltas (process-global
        # registry, so in-process workers' pulls are included) plus the
        # absolute high-water gauges
        exchange_before = exchange_counters()
        # cluster-mesh activity bracket (same process-global-registry
        # assumption): ICI exchange bytes + fallback deltas
        from presto_tpu.server import mesh_tier as _mesh_tier
        mesh_ici_before = _mesh_tier.ici_bytes_total()
        mesh_fb_before = _mesh_tier.fallbacks_total()

        def run_query() -> List[tuple]:
            try:
                if batch_mode:
                    return self._run_fragments_batch(
                        qid, stages, by_id, placement, out_types,
                        merge_keys, capture, cancel_event)
                if self._task_retry():
                    # stage-level recoverable execution (retry_policy=
                    # TASK, Presto@Meta VLDB'23 §3): each failed await
                    # absorbs dead tasks from their committed spools /
                    # re-plans only the lost ones onto survivors, then
                    # awaits again — completed stages never re-run.
                    # Scheduling lives INSIDE the loop: a worker dying
                    # mid-schedule leaves partially-posted stages, and
                    # _recover_spooled's tail pass places the
                    # never-created tasks on survivors — it must never
                    # escape to the whole-query-retry path.
                    rounds = 0
                    need_schedule = True
                    while True:
                        try:
                            if need_schedule:
                                schedule(0)
                                need_schedule = False
                            self._await_all(stages,
                                            cancel_event=cancel_event,
                                            query_id=qid)
                            break
                        except ClusterMemoryKillError:
                            # the low-memory killer is terminal: a
                            # killed query must never re-execute, even
                            # though its spools could replay
                            raise
                        except (ClusterQueryError, OSError):
                            # recovery finishes any partial scheduling
                            # itself; re-running schedule() would
                            # double-post the already-created tasks
                            need_schedule = False
                            if cancel_event is not None \
                                    and cancel_event.is_set():
                                raise
                            if rounds >= MAX_RECOVERY_ROUNDS \
                                    or not self._recover_spooled(
                                        qid, stages, by_id):
                                raise
                            rounds += 1
                else:
                    schedule(0)
                    try:
                        self._await_all(stages,
                                        cancel_event=cancel_event,
                                        query_id=qid)
                    except ClusterMemoryKillError:
                        raise       # terminal: killed queries never retry
                    except (ClusterQueryError, OSError):
                        if cancel_event is not None \
                                and cancel_event.is_set():
                            raise
                        # task-level recovery (reference: scheduler/
                        # group recoverable grouped execution,
                        # SystemSessionProperties
                        # recoverable_grouped_execution): for a
                        # single-stage query, re-run ONLY the tasks that
                        # lived on dead workers — their split assignment
                        # is deterministic, so exactly the lost
                        # lifespans re-run
                        if not self._recover_dead_tasks(qid, stages,
                                                        by_id):
                            raise
                        self._await_all(stages,
                                        cancel_event=cancel_event,
                                        query_id=qid)
                if capture or self.history is not None:
                    self._capture_task_infos(stages)
                    self._record_history(stages, by_id)
                return self._collect_root(stages[0], out_types,
                                          merge_keys)
            finally:
                self._cleanup(stages, qid)
                if spool_before is not None:
                    from presto_tpu.spool.store import spool_counters
                    after = spool_counters()
                    self.last_spool_stats = {
                        k: after[k] - spool_before[k]
                        for k in after}
                ex_after = exchange_counters()
                self.last_exchange_stats = {
                    k: (ex_after[k] - exchange_before[k]
                        if not k.endswith("high_water") else ex_after[k])
                    for k in ex_after}
                # post-query membership view: joins/drains that landed
                # DURING the query show up in EXPLAIN ANALYZE
                self.last_membership = self.membership_snapshot()
                # cluster-mesh outcome for EXPLAIN ANALYZE / wide event
                if mesh_plan is not None:
                    ici = (_mesh_tier.ici_bytes_total()
                           - mesh_ici_before)
                    colocated = (mesh_plan["descriptor"]
                                 ["colocated_stages"] if ici > 0 else 0)
                    self.last_cluster_mesh = {
                        "worker": mesh_plan["worker"],
                        "group": mesh_plan["group"],
                        "ndev": mesh_plan["ndev"],
                        "colocated_stages": colocated,
                        "ici_bytes": int(ici),
                        "fallbacks": int(_mesh_tier.fallbacks_total()
                                         - mesh_fb_before)}
                    _mesh_tier.set_colocation_gauge(colocated)
                else:
                    self.last_cluster_mesh = None
                    _mesh_tier.set_colocation_gauge(0)

        if not DEFAULT_OBS.sampled(random.random()):
            return run_query()
        # sampled query: the coordinator opens the root span, the
        # trace_scope makes every RPC this scheduling thread issues
        # carry X-Presto-Trace, and worker span dumps are scraped back
        # at query end into one stitched timeline
        self.last_trace_id = qid
        with TRACER.span(qid, "query", worker="coordinator",
                         fragments=len(frags)) as root:
            with trace_scope(qid, root.span_id):
                rows = run_query()
        self._scrape_worker_traces(qid)
        return rows

    def _run_fragments_batch(self, qid, stages, by_id, placement,
                             out_types, merge_keys, capture,
                             cancel_event) -> List[tuple]:
        """Materialized-exchange batch execution (reference:
        presto-spark-base's stage-by-stage mode over materialized
        shuffles, ShuffleWrite.cpp): stages run to COMPLETION in
        producer-first order — their output frames persist on disk and
        replay from token 0 (MaterializedClientBuffer) — and a stage
        lost to a worker death re-runs ALONE on the survivors (its
        consumers have not started, its producers' outputs are still
        replayable), instead of failing or retrying the whole query."""
        order: List[int] = []
        seen = set()

        def topo(fid: int):
            if fid in seen:
                return
            seen.add(fid)
            for src in by_id[fid].remote_sources:
                topo(src)
            order.append(fid)

        topo(0)
        live_placement = list(placement)
        for pos, fid in enumerate(order):
            for _attempt in range(2):
                try:
                    if _attempt == 0:
                        self._start_stage(qid, fid, stages, by_id,
                                          live_placement)
                    self._await_all({fid: stages[fid]},
                                    cancel_event=cancel_event,
                                    query_id=qid)
                    break
                except ClusterMemoryKillError:
                    raise           # terminal: killed queries never retry
                except (ClusterQueryError, OSError):
                    if cancel_event is not None \
                            and cancel_event.is_set():
                        raise
                    if _attempt:
                        raise
                    # a dead worker also takes the materialized outputs
                    # of COMPLETED upstream tasks it hosted: regenerate
                    # those first (their survivors return FINISHED
                    # immediately), then re-post the whole current
                    # stage so its split bindings see the new producer
                    # locations
                    alive = set(self.check_workers())
                    if not alive:
                        raise
                    recovered = False
                    for up in order[:pos]:
                        if self._reschedule_stage(qid, up, stages,
                                                  by_id):
                            recovered = True
                            self._await_all({up: stages[up]},
                                            cancel_event=cancel_event,
                                            query_id=qid)
                    if self._reschedule_stage(qid, fid, stages, by_id,
                                              force_all=recovered):
                        recovered = True
                    if not recovered:
                        raise
                    live_placement = [w for w in live_placement
                                      if w in alive] or live_placement
        if capture or self.history is not None:
            self._capture_task_infos(stages)
            self._record_history(stages, by_id)
        return self._collect_root(stages[0], out_types, merge_keys)

    def _recover_dead_tasks(self, qid: str, stages: Dict[int, _Stage],
                            by_id) -> bool:
        """Streaming-mode task recovery: only safe when every stage's
        output is still pullable, i.e. the single-fragment shape
        (consumers re-pull from token 0 of the replacement task);
        multi-stage streaming plans fall back to the whole-query
        retry. Returns True if recovery was performed."""
        if len(stages) != 1:
            return False
        return self._reschedule_stage(qid, 0, stages, by_id)

    def _recover_spooled(self, qid: str, stages: Dict[int, _Stage],
                         by_id) -> bool:
        """retry_policy=TASK recovery round (reference: Presto@Meta
        VLDB'23 §3 — spooled intermediate results make individual task
        retry sound). Producer-first over the stage DAG:

          - a dead worker's task whose spool COMMITTED is absorbed: the
            work is done, its output lives in disaggregated storage;
            consumers read it there (direct spool fallback, or any live
            worker's HTTP spool serving). It is never re-executed.
          - a dead worker's task with NO committed spool lost its work:
            re-plan exactly that task onto a survivor as attempt N+1
            (deterministic split assignment re-reads the same
            lifespans).
          - a live task that FAILED (typically its pull from the dead
            producer exhausted before the spool committed) re-plans the
            same way — its replacement's remote splits point at the
            producers' CURRENT locations.

        Returns True when anything changed (the caller awaits again);
        False means this error is not recoverable here."""
        from presto_tpu.spool.store import record_recovery

        # survivors keep MEMBERSHIP order (static fleet first, then
        # announced joiners in announce order): deterministic like a
        # sort, but a worker that announced mid-query slots into the
        # index the departed worker vacated instead of wherever its
        # ephemeral port happens to sort
        survivors = self.check_workers()
        alive = set(survivors)
        if not alive:
            return False
        order: List[int] = []
        seen: set = set()

        def topo(fid: int):
            if fid in seen:
                return
            seen.add(fid)
            for src in by_id[fid].remote_sources:
                topo(src)
            order.append(fid)

        for fid in stages:
            topo(fid)
        changed = False
        for fid in order:
            stage = stages[fid]
            for t, uri in enumerate(list(stage.task_uris)):
                if t in stage.spool_done:
                    continue
                worker = uri.split("/v1/task/")[0]
                if worker not in alive:
                    committed = self.spool.find_committed_for_task(
                        stage.task_ids[t])
                    if committed is not None:
                        stage.spool_done.add(t)
                        stage.spool_task_ids[t] = committed.task_id
                        record_recovery("absorb")
                        self.last_recovery_events.append(
                            ("spool", fid, t))
                        log.info("task %s absorbed from committed "
                                 "spool %s", stage.task_ids[t],
                                 committed.path)
                        changed = True
                        continue
                    new_worker = survivors[t % len(survivors)]
                else:
                    # live worker: only a FAILED task needs re-planning
                    # (RUNNING consumers of a dead producer recover by
                    # themselves through the spool fallback)
                    try:
                        st = self.http.get_json(
                            f"{uri}/status",
                            headers={"X-Presto-Max-Wait": "0s"},
                            request_class="status_poll")
                    except OSError:
                        continue      # transient; next round retries
                    if st.get("state") != "FAILED":
                        continue
                    try:
                        self.http.delete(uri)
                    except Exception:   # noqa: BLE001 — best effort
                        pass
                    new_worker = worker
                attempt = int(stage.task_ids[t].rsplit(".", 1)[1]) + 1
                task_id, new_uri = self._post_stage_task(
                    qid, fid, stages, by_id, new_worker, t, attempt)
                stage.task_ids[t] = task_id
                stage.task_uris[t] = new_uri
                stage.recovered_tasks += 1
                record_recovery("retask")
                self.last_recovery_events.append(("retask", fid, t))
                log.info("task re-planned as %s on %s", task_id,
                         new_worker)
                changed = True
            # a scheduling-time death can leave the stage partially
            # posted: place the never-created tasks on survivors
            for t in range(len(stage.task_uris), stage.n_tasks):
                task_id, new_uri = self._post_stage_task(
                    qid, fid, stages, by_id,
                    survivors[t % len(survivors)], t, attempt=1)
                stage.task_ids.append(task_id)
                stage.task_uris.append(new_uri)
                stage.recovered_tasks += 1
                record_recovery("retask")
                self.last_recovery_events.append(("retask", fid, t))
                changed = True
            self.last_recovered_tasks = stage.recovered_tasks
        return changed

    def _reschedule_stage(self, qid: str, fid: int,
                          stages: Dict[int, _Stage], by_id,
                          force_all: bool = False) -> bool:
        """Re-post fragment `fid`'s tasks stranded on dead workers to
        survivors with bumped attempt ids (deterministic split
        assignment -> exactly the lost work re-runs). `force_all`
        re-posts EVERY task — needed when upstream producers moved and
        surviving tasks' remote splits still point at the old
        locations (batch-mode recovery)."""
        survivors = self.check_workers()   # membership order, as above
        alive = set(survivors)
        if not alive:
            return False
        stage = stages[fid]
        recovered = False
        for t, uri in enumerate(list(stage.task_uris)):
            worker = uri.split("/v1/task/")[0]
            if worker in alive and not force_all:
                continue
            attempt = int(stage.task_ids[t].rsplit(".", 1)[1]) + 1
            new_worker = (worker if worker in alive
                          else survivors[t % len(survivors)])
            task_id, new_uri = self._post_stage_task(
                qid, fid, stages, by_id, new_worker, t, attempt)
            stage.task_ids[t] = task_id
            stage.task_uris[t] = new_uri
            stage.recovered_tasks += 1
            recovered = True
        # a scheduling-time death can leave the stage partially posted:
        # place the never-created tasks on survivors
        for t in range(len(stage.task_uris), stage.n_tasks):
            task_id, new_uri = self._post_stage_task(
                qid, fid, stages, by_id, survivors[t % len(survivors)],
                t, attempt=1)
            stage.task_ids.append(task_id)
            stage.task_uris.append(new_uri)
            stage.recovered_tasks += 1
            recovered = True
        self.last_recovered_tasks = stage.recovered_tasks
        return recovered

    def _capture_task_infos(self, stages: Dict[int, _Stage]):
        """Fetch every task's TaskInfo (stats tree included) before
        cleanup deletes the tasks — the coordinator's QueryStats
        aggregation source (reference: per-task OperatorStats rolled up
        by SqlStageExecution)."""
        infos = []
        for fid, stage in stages.items():
            for uri in stage.task_uris:
                try:
                    infos.append((fid, self.http.get_json(uri)))
                except Exception:    # noqa: BLE001 — stats best-effort
                    pass
        self.last_task_infos = infos

    def _record_history(self, stages: Dict[int, _Stage], by_id) -> None:
        """Cluster-fed HBO: fold the workers' OBSERVED cardinalities
        back into the coordinator's HistoryStore at query end
        (reference: HistoryBasedPlanStatisticsTracker recording final
        QueryStats keyed by canonical plan hashes). Two granularities:
        per-operator summaries carry the worker-computed canonicalKey
        (local subtrees — scan/filter chains — hash identically to the
        planner's), and each fragment root is keyed by the
        coordinator-side digest of its engine subtree, which is what
        AddExchanges' est(build) consults for broadcast decisions."""
        if self.history is None:
            return
        per_op: Dict[tuple, int] = {}
        per_frag: Dict[int, int] = {}
        for fid, info in getattr(self, "last_task_infos", []):
            stats = info.get("stats") or {}
            per_frag[fid] = per_frag.get(fid, 0) + int(
                stats.get("outputPositions", 0) or 0)
            for pipe in stats.get("pipelines", []):
                for op in pipe.get("operatorSummaries", []):
                    key = op.get("canonicalKey")
                    if key:
                        k = (fid, str(op.get("planNodeId")), key)
                        per_op[k] = per_op.get(k, 0) + int(
                            op.get("outputPositions", 0) or 0)
        for (_fid, _nid, key), rows in per_op.items():
            self.history.record(key, rows)
        for fid, rows in per_frag.items():
            frag = by_id.get(fid)
            if frag is None:
                continue
            try:
                self.history.record(canonical_key(frag.root), rows)
            except Exception:  # noqa: BLE001 — feedback is best-effort
                pass
        try:
            self.history.save()
        except OSError:
            log.debug("HBO save failed", exc_info=True)

    # ----------------------------------------- cross-exchange dynamic filters
    def _plan_dynamic_filters(self, stages: Dict[int, _Stage],
                              by_id) -> None:
        """Decide, per query, which build stage publishes a join-key
        domain and which probe-side scan stage waits for it (reference:
        DynamicFilterService collecting build summaries and pushing
        TupleDomains into not-yet-scheduled probe splits). Eligibility:
        INNER/filtering-SEMI equi-join whose build side was cut into its
        own fragment, numeric key, and a build estimated small enough
        that waiting `dynamic_filter_wait_ms` is plausibly repaid."""
        from presto_tpu.config import PROPERTIES, Session
        from presto_tpu.plan import nodes as P
        from presto_tpu.expr.nodes import InputRef
        known = {p.name for p in PROPERTIES}
        session = Session({k: v for k, v in
                           self.session_properties.items() if k in known})
        if not session["dynamic_filtering_enabled"]:
            return
        wait_ms = int(session["dynamic_filter_wait_ms"])
        threshold = int(session["broadcast_join_threshold_rows"])

        def resolve(fid: int, node, ch: int):
            """Trace output channel `ch` of `node` (in fragment `fid`)
            back to a (fragment, table, column) scan origin, hopping
            exchange cuts into producer fragments."""
            if isinstance(node, P.TableScanNode):
                return (fid, node.table, node.columns[ch])
            if isinstance(node, P.FilterNode):
                return resolve(fid, node.source, ch)
            if isinstance(node, P.ProjectNode):
                e = node.expressions[ch]
                if isinstance(e, InputRef):
                    return resolve(fid, node.source, e.field)
                return None
            if isinstance(node, P.ExchangeNode):
                if node.source is not None:
                    return resolve(fid, node.source, ch)
                pfid = node.remote_fragment
                if pfid is None or pfid not in by_id:
                    return None
                return resolve(pfid, by_id[pfid].root, ch)
            if isinstance(node, P.JoinNode):
                if ch < len(node.probe.output_types):
                    return resolve(fid, node.probe, ch)
                return None
            if isinstance(node, P.AggregationNode):
                # group keys pass values through unchanged: filtering
                # the input on a key domain removes exactly the groups
                # that could not match
                if ch < len(node.group_fields):
                    return resolve(fid, node.source,
                                   node.group_fields[ch])
                return None
            return None

        def walk(n):
            yield n
            for c in n.children():
                if c is not None:
                    yield from walk(c)

        for fid in sorted(by_id):
            for node in walk(by_id[fid].root):
                if not isinstance(node, P.JoinNode) \
                        or not node.probe_keys:
                    continue
                if node.join_type not in (P.JoinType.INNER,
                                          P.JoinType.SEMI) \
                        or node.emit_flag:
                    continue
                build = node.build
                if not (isinstance(build, P.ExchangeNode)
                        and build.source is None
                        and build.remote_fragment in stages):
                    continue
                bfid = build.remote_fragment
                key_t = build.output_types[node.build_keys[0]]
                if key_t.is_string:
                    continue
                try:
                    est = estimate_rows(by_id[bfid].root,
                                        self.connector, self.history)
                except Exception:  # noqa: BLE001 — est gate is advisory
                    continue
                if est > threshold:
                    continue
                resolved = resolve(fid, node.probe,
                                   node.probe_keys[0])
                if resolved is None:
                    continue
                tfid, table, column = resolved
                target = stages.get(tfid)
                if target is None or target.df_spec is not None \
                        or stages[bfid].df_publish_channel is not None:
                    continue
                scan_ids = [nid for nid, tb in
                            target.spec.scan_nodes.items()
                            if tb == table]
                if len(scan_ids) != 1 or tfid == bfid:
                    continue
                stages[bfid].df_publish_channel = node.build_keys[0]
                target.df_spec = {
                    "build_fid": bfid, "scan_node": scan_ids[0],
                    "column": column, "wait_ms": wait_ms}

    def _await_dynamic_filter(self, stages: Dict[int, _Stage],
                              spec: dict) -> Optional[dict]:
        """Poll the build stage's TaskInfos until every task FINISHED
        and published its key domain, bounded by `wait_ms`. Any miss —
        deadline, failed/killed build worker, no domain published —
        degrades to None (unfiltered probe scan): a dynamic filter is
        an optimization, never a correctness dependency."""
        build = stages.get(spec["build_fid"])
        if build is None or build.df_publish_channel is None \
                or not build.task_uris:
            return None
        ch = str(build.df_publish_channel)
        deadline = time.time() + spec["wait_ms"] / 1000.0
        while True:
            domains = []
            done = True
            for uri in build.task_uris:
                try:
                    info = self.http.get_json(
                        uri, request_class="status_poll")
                except Exception:  # noqa: BLE001 — degrade, never block
                    return None
                state = (info.get("taskStatus") or {}).get("state")
                if state in ("FAILED", "ABORTED", "CANCELED"):
                    return None
                if state != "FINISHED":
                    done = False
                    continue
                d = ((info.get("stats") or {})
                     .get("dynamicFilterDomains") or {}).get(ch)
                if d is None:
                    return None   # finished without a domain (e.g.
                                  # string key): nothing to wait for
                domains.append(d)
            if done:
                break
            if time.time() > deadline:
                return None
            time.sleep(0.02)
        col = spec["column"]
        if sum(int(d.get("count", 0) or 0) for d in domains) == 0:
            return {"column": col, "empty": True}
        mins = [d["min"] for d in domains if d.get("min") is not None]
        maxs = [d["max"] for d in domains if d.get("max") is not None]
        if not mins:
            return None
        con = {"column": col, "min": min(mins), "max": max(maxs)}
        vals: Optional[set] = set()
        for d in domains:
            v = d.get("values")
            if v is None:
                vals = None
                break
            vals.update(v)
        if vals:
            con["values"] = sorted(vals)
        return con

    # ------------------------------------------------------------------
    def _start_stage(self, qid: str, fid: int, stages: Dict[int, _Stage],
                     by_id, placement: List[str]):
        stage = stages[fid]
        self._ensure_scan_splits(stage)
        # probe stage with a pending dynamic filter: wait (bounded) for
        # the build stage's domain BEFORE posting tasks, so the
        # constraint rides the very first split assignment
        if stage.df_spec is not None and stage.df_constraint is None:
            stage.df_constraint = self._await_dynamic_filter(
                stages, stage.df_spec)
        # cache-affinity placement: when result caching is on, route each
        # leaf task to the worker that (per the router's memory) holds
        # its fragment's cached result; rendezvous hashing places
        # never-seen fingerprints deterministically so the FIRST and
        # SECOND execution agree on a worker even with no history
        affinity_fp = None
        if stage.spec.scan_nodes and not stage.spec.remote_nodes and \
                str(self.session_properties.get(
                    "fragment_result_cache_enabled", "")
                    ).strip().lower() == "true":
            from presto_tpu.plan.fingerprint import plan_fingerprint
            try:
                affinity_fp = plan_fingerprint(by_id[fid].root)
            except Exception:   # noqa: BLE001 — affinity is advisory
                affinity_fp = None
        for t in range(stage.n_tasks):
            worker = placement[t % len(placement)]
            if stage.mesh_worker is not None:
                if stage.mesh_worker in placement:
                    # co-location: the fused mesh stage lands on the
                    # worker whose slice the planner chose
                    worker = stage.mesh_worker
                else:
                    # chosen mesh worker left between planning and
                    # placement — any survivor runs the same fragment
                    # (mesh-lowered if it has a slice, else generic)
                    from presto_tpu.server.mesh_tier import \
                        note_plan_fallback
                    note_plan_fallback("placement")
            if affinity_fp is not None:
                key = f"{affinity_fp}|t{t}/{stage.n_tasks}"
                picked = self.affinity.pick(key, placement)
                if picked is not None:
                    worker = picked
                self.affinity.record(key, worker)
            task_id, uri = self._post_stage_task(
                qid, fid, stages, by_id, worker, t, attempt=0)
            stage.task_ids.append(task_id)
            stage.task_uris.append(uri)

    def _ensure_scan_splits(self, stage: _Stage):
        """Bind connector splits (one list per scan node, split t to
        task t; reference: ConnectorSplitManager). Lazy so that EVERY
        post path computes them: a worker death during scheduling can
        leave a stage with no tasks posted, and recovery then creates
        its tasks without ever passing through _start_stage — a task
        posted without scan sources would fall back to scanning the
        whole table (SplitExecutor._fetch), duplicating rows once per
        task. Split assignment is a pure function of (fragment,
        n_tasks), so first-caller-wins is deterministic."""
        if stage.scan_splits or not stage.spec.scan_nodes:
            return
        stage.scan_splits = {
            node_id: (self.connector.connector_id(table),
                      self.connector.table_splits(table, stage.n_tasks))
            for node_id, table in stage.spec.scan_nodes.items()}

    def _producer_location(self, producer: _Stage, i: int,
                           uri: str) -> str:
        """Result location of producer task `i` as a consumer should
        see it NOW: normally the live task's URI; for a spool-absorbed
        task, a LIVE worker's URI with the COMMITTED attempt's task id
        — any worker sharing the spool base serves a committed spool
        over the same GET .../results/... protocol, so replacement
        consumers never dial the dead host."""
        if i not in producer.spool_done:
            return uri
        live = self.worker_uris
        host = (live[i % len(live)] if live
                else uri.split("/v1/task/")[0])
        return f"{host}/v1/task/{producer.spool_task_ids[i]}"

    def _post_stage_task(self, qid: str, fid: int, stages, by_id,
                         worker_uri: str, t: int, attempt: int):
        """POST task index `t` of fragment `fid` to one worker. The
        split assignment is a pure function of (fragment, t), so a
        recovery re-post on another worker re-reads exactly the same
        lifespans (reference: scheduler/group recoverable grouped
        execution; attempt is the Presto task-id attempt field)."""
        stage = stages[fid]
        spec = stage.spec
        self._ensure_scan_splits(stage)
        task_id = f"{qid}.{fid}.0.{t}.{attempt}"
        uri = f"{worker_uri}/v1/task/{task_id}"
        sources: List[S.TaskSource] = []
        seq = 0
        for node_id, (cid, all_splits) in stage.scan_splits.items():
            payload = all_splits[t]
            if stage.df_constraint is not None \
                    and stage.df_spec is not None \
                    and node_id == stage.df_spec["scan_node"]:
                payload = constrain_split_payload(
                    payload, stage.df_constraint)
            splits = [S.ScheduledSplit(
                sequenceId=seq, planNodeId=node_id,
                split=S.Split(connectorId=cid,
                              connectorSplit=payload))]
            seq += 1
            sources.append(S.TaskSource(planNodeId=node_id,
                                        splits=splits,
                                        noMoreSplits=True))
        for node_id, pfid in spec.remote_nodes.items():
            producer = stages[pfid]
            part = by_id[pfid].partitioning
            off = producer.buffer_offset.get(fid, 0)
            buffer_id = (str(off) if part == Partitioning.SINGLE
                         else str(off + t))
            splits = []
            for i, u in enumerate(producer.task_uris):
                splits.append(S.ScheduledSplit(
                    sequenceId=seq, planNodeId=node_id,
                    split=S.Split(connectorId="$remote",
                                  connectorSplit=remote_split_payload(
                                      self._producer_location(
                                          producer, i, u),
                                      buffer_id))))
                seq += 1
            sources.append(S.TaskSource(planNodeId=node_id,
                                        splits=splits,
                                        noMoreSplits=True))
        props = dict(self.session_properties)
        if stage.df_publish_channel is not None:
            # marks this task as a dynamic-filter build source; the
            # worker summarizes this output channel's key domain
            props["x_dynamic_filter_channel"] = str(
                stage.df_publish_channel)
        if stage.mesh_descriptor is not None:
            # ICI exchange routing side channel — stamped through the
            # mesh_tier chokepoint so recovery re-posts (any attempt,
            # any worker) carry the SAME descriptor
            from presto_tpu.server.mesh_tier import stamp_ici_descriptor
            stamp_ici_descriptor(props, stage.mesh_descriptor)
        tur = S.TaskUpdateRequest(
            session=S.SessionRepresentation(
                queryId=qid, user="cluster",
                systemProperties=props),
            extraCredentials={},
            fragment=spec.fragment.to_bytes(),
            sources=sources,
            outputIds=S.OutputBuffers(
                type="PARTITIONED", version=1, noMoreBufferIds=True,
                buffers={str(j): j for j in range(stage.n_buffers)}))
        body = tur.dumps().encode()
        tried = set()
        while True:
            try:
                self._post(uri, body)
                return task_id, uri
            except FatalResponseError as e:
                if not e.draining:
                    raise
                # graceful decommission mid-schedule: the worker
                # refused the NEW task with 410 + X-Presto-Draining
                # (the transport already recorded breaker SUCCESS on
                # the 4xx — a draining node takes no availability
                # penalty). Mark it drained through the chokepoint and
                # re-place this task on another live worker.
                err, mutation = e, {"drained_add": [worker_uri]}
                log.info("worker %s draining; re-placing task %s",
                         worker_uri, task_id)
            except TransportError as e:
                # the target died between the membership snapshot and
                # this POST (continuous churn): mark it dead through
                # the chokepoint and re-place instead of failing the
                # query. Safe even if the POST half-landed — task
                # updates are at-least-once and split assignment is
                # deterministic, so a duplicate produces identical
                # output under one task id.
                err, mutation = e, {"dead_add": [worker_uri]}
                log.info("worker %s unreachable; re-placing task %s",
                         worker_uri, task_id)
            tried.add(worker_uri)
            live = [w for w in self._membership(**mutation)
                    if w not in tried]
            if not live:
                raise ClusterQueryError(
                    f"no live workers to place task {task_id}: "
                    f"all candidates draining or dead") from err
            worker_uri = live[t % len(live)]
            uri = f"{worker_uri}/v1/task/{task_id}"

    # ------------------------------------------------------------------
    def _post(self, uri: str, body: bytes) -> dict:
        # TaskUpdateRequest POSTs are at-least-once by protocol (the
        # worker dedupes splits by sequenceId), so transport retries of
        # a dropped response are safe
        return self.http.post(uri, body,
                              request_class="task_post").json()

    def _await_all(self, stages: Dict[int, _Stage],
                   timeout_s: float = 1800, cancel_event=None,
                   query_id: Optional[str] = None):
        """Long-poll every task CONCURRENTLY (reference: one
        ContinuousTaskStatusFetcher per task) — a straggler in one stage
        no longer hides a failure in another, and N tasks cost one
        round-trip time per sweep instead of N. query_max_execution_time
        (when set) caps the wait below the scheduler default."""
        try:
            budget = float(self.session_properties.get(
                "query_max_execution_time", 0) or 0)
        except (TypeError, ValueError):
            budget = 0
        if budget > 0:
            timeout_s = min(timeout_s, budget)
        deadline = time.time() + timeout_s
        # spool-absorbed tasks are DONE by definition (their committed
        # output is the result) — never poll their dead location
        uris = [u for st in stages.values()
                for i, u in enumerate(st.task_uris)
                if i not in st.spool_done]
        results: Dict[str, Optional[dict]] = {}
        errs: Dict[str, BaseException] = {}
        wake = threading.Event()          # first failure OR all done
        remaining = [len(uris)]
        lock = threading.Lock()

        def watch(uri: str):
            state = "PLANNED"
            try:
                while state in ("PLANNED", "RUNNING"):
                    if wake.is_set() and errs:
                        return            # another task already failed
                    if time.time() > deadline:
                        raise ClusterQueryError(f"timeout on {uri}")
                    st = self.http.get_json(
                        f"{uri}/status",
                        headers={"X-Presto-Current-State": state,
                                 "X-Presto-Max-Wait": "1s"},
                        request_class="status_poll")
                    state = st["state"]
                results[uri] = st
                if state != "FINISHED":
                    msgs = [f.get("message", "") for f in
                            st.get("failures", [])]
                    raise ClusterQueryError(
                        f"task {uri} {state}: " + "\n".join(msgs))
            except BaseException as e:    # noqa: BLE001 — re-raised below
                errs[uri] = e
                wake.set()                # fail fast
            finally:
                with lock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        wake.set()

        threads = [spawn("coordinator", f"task-watch-{i}", watch,
                         args=(u,), start=False)
                   for i, u in enumerate(uris)]
        for t in threads:
            t.start()
        # wake on the FIRST failure (fail-fast) or when every watcher
        # finished; stragglers are daemons and die with their long-poll
        # wait in slices so a client DELETE (statement cancellation)
        # interrupts the query instead of merely flagging it: tasks are
        # aborted by the caller's cleanup once we raise
        end = deadline + 60
        while not wake.is_set() and time.time() < end:
            if cancel_event is not None and cancel_event.is_set():
                raise ClusterQueryError("Query was canceled by the user")
            self._memory_kill_sweep(query_id)
            wake.wait(0.25)
        self._memory_kill_sweep(query_id)
        for uri, e in errs.items():
            raise e if isinstance(e, (ClusterQueryError, OSError)) \
                else ClusterQueryError(f"task {uri}: {e}")
        for uri in uris:
            if results.get(uri) is None:
                raise ClusterQueryError(f"no status from {uri}")

    def _memory_kill_sweep(self, query_id: Optional[str]) -> None:
        """Cluster low-memory killer (ClusterMemoryManager.java:106 +
        LowMemoryKiller): when aggregate reservations exceed the
        cluster budget, mark the single biggest query killed; when THIS
        query is the victim, surface the terminal
        EXCEEDED_MEMORY_LIMIT-class error (never retried — see
        ClusterMemoryKillError)."""
        cm = self.cluster_memory
        if cm is None or not self.memory_config.kill_enabled:
            return
        from presto_tpu.exec.memory import ExceededMemoryLimitError
        victim = cm.maybe_kill()
        if victim is not None:
            log.warning("low-memory killer chose query %s", victim)
        if query_id is None:
            return
        try:
            cm.check_killed(query_id)
        except ExceededMemoryLimitError as e:
            raise ClusterMemoryKillError(str(e)) from e

    def _collect_root(self, root: _Stage, out_types,
                      merge_keys=None) -> List[tuple]:
        if merge_keys:
            return self._merge_root(root, out_types, merge_keys)
        # concurrent final-result drain: all root tasks' buffers pull in
        # parallel through the bounded exchange buffer; arrival-order
        # interleaving is legal here because ordered results always
        # carry merge_keys (the _merge_root path), and single-task roots
        # keep exact order (per-stream FIFO)
        locations = [(self._producer_location(root, i, uri), "0")
                     for i, uri in enumerate(root.task_uris)]
        rows: List[tuple] = []
        with ExchangeClient(locations, types=list(out_types),
                            config=self.exchange_config,
                            client=self.http, spool=self.spool) as xc:
            for pages in xc:
                for p in pages:
                    rows.extend(p.to_pylist())
        return rows

    #: per-stream cap on decoded-but-unmerged row batches held at the
    #: coordinator during an ordered-merge collect
    MERGE_QUEUE_PAGES = 4

    def _merge_root(self, root: _Stage, out_types,
                    merge_keys) -> List[tuple]:
        """Ordered-merge exchange at the coordinator
        (operator/MergeOperator.java semantics at the root
        ExchangeClient). The per-task streams drain CONCURRENTLY
        (network overlap across workers) but coordinator residency is
        RE-BOUND: each stream's decoded batches flow through a bounded
        queue into ``heapq.merge`` instead of fully materializing every
        run before a Timsort pass — peak memory is
        ``k * (MERGE_QUEUE_PAGES + 2)`` batches plus the merged output,
        not the sum of all runs twice over."""
        def source(uri):
            def batches():
                for p in stream_pages(
                        uri, buffer_id="0", types=out_types,
                        client=self.http, spool=self.spool,
                        max_size_bytes=self.exchange_config
                        .max_response_bytes):
                    yield p.to_pylist()
            return batches

        class _Key:
            """SQL sort-order comparison over python row values (null
            ordering + per-key direction)."""
            __slots__ = ("row",)

            def __init__(self, row):
                self.row = row

            def __lt__(self, other):
                for k in merge_keys:
                    a = self.row[k.field]
                    b = other.row[k.field]
                    if a is None or b is None:
                        if (a is None) != (b is None):
                            return (a is None) == k.nulls_sort_first
                        continue
                    # NaN sorts after every non-null value regardless of
                    # direction (the shard sort is total-order NaN-last)
                    a_nan = isinstance(a, float) and a != a
                    b_nan = isinstance(b, float) and b != b
                    if a_nan or b_nan:
                        if a_nan != b_nan:
                            return b_nan
                        continue
                    if a == b:
                        continue
                    return (a < b) == k.ascending
                return False

        rows, high = bounded_merge(
            [source(self._producer_location(root, i, u))
             for i, u in enumerate(root.task_uris)], key=_Key,
            queue_pages=self.MERGE_QUEUE_PAGES)
        # observability hook for the bounded-in-flight test
        self.last_merge_inflight_high = high
        _M_MERGE_HIGH.set_max(high)
        return rows

    def _cleanup(self, stages: Dict[int, _Stage], qid: str = ""):
        for stage in stages.values():
            for i, uri in enumerate(stage.task_uris):
                if i in stage.spool_done:
                    continue       # nothing live behind a spooled task
                try:
                    self.http.delete(uri)
                except Exception:   # noqa: BLE001 — best-effort abort
                    pass
        # end-of-query spool retention: the query's whole spool tree
        # goes away with the query (success or failure)
        if self.spool is not None and qid:
            self.spool.gc_query(qid)
