"""Worker server: the HTTP shell that grafts this engine onto an
unmodified Presto coordinator — endpoints, task manager, output buffers,
announcer. Reference: presto-native-execution/presto_cpp/main
(TaskResource.cpp:115-180, TaskManager.cpp, PrestoServer.cpp:497-562,
Announcer.cpp:64)."""

from presto_tpu.server.buffers import OutputBufferManager
from presto_tpu.server.task_manager import TpuTaskManager
from presto_tpu.server.http import TpuWorkerServer

__all__ = ["OutputBufferManager", "TpuTaskManager", "TpuWorkerServer"]
