"""Task output buffers: the pull-protocol server side.

Reference roles: PartitionedOutputBuffer / ClientBuffer
(presto-main-base/.../execution/buffer/PartitionedOutputBuffer.java:44,
buffer/ClientBuffer.java) — per-destination queues of SerializedPages,
consumed by sequenced GET .../results/{buffer}/{token} with acknowledge
semantics (at-least-once; tokens make re-reads idempotent).

All disk-backed variants write through `spool/files.FrameFile` — the
single task-output file path guarded by tests/test_spool_chokepoint.py."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu.obs.metrics import counter as _counter, gauge as _gauge
from presto_tpu.spool.files import FrameFile

class BufferClosedError(RuntimeError):
    """GET on a buffer whose task (or worker) already closed it. A
    closed buffer must REFUSE rather than answer `complete` with no
    frames: a worker shutting down mid-long-poll would otherwise hand
    every consumer a fake clean end-of-stream and the rows it never
    served would silently vanish from the query (the continuous-churn
    row-loss bug). The HTTP layer turns this into a retryable 503 —
    or serves the committed spool when one exists."""


_M_PAGES_ADDED = _counter(
    "presto_tpu_output_buffer_pages_added_total",
    "Frames enqueued into task output buffers")
_M_DEPTH_HIGH = _gauge(
    "presto_tpu_output_buffer_depth_high_water",
    "Max unacknowledged frames ever queued in one client buffer")
_M_BYTES_HIGH = _gauge(
    "presto_tpu_output_buffer_bytes_high_water",
    "Max unacknowledged bytes ever queued in one client buffer")


class ClientBuffer:
    """One destination's page queue with token bookkeeping. Acknowledged
    frames are dropped (tokens stay monotonically global: `base` is the
    token of pages[0]) — the at-least-once window is [acked, produced)."""

    def __init__(self):
        self.pages: List[bytes] = []     # frames for tokens base..
        self.base = 0                    # token of pages[0]
        self.no_more_pages = False
        self.aborted = False
        self.queued_bytes = 0            # bytes in the unacked window

    @property
    def end_token(self) -> int:
        return self.base + len(self.pages)

    def add(self, frame: bytes):
        self.pages.append(frame)
        self.queued_bytes += len(frame)

    def get(self, token: int, max_bytes: int
            ) -> Tuple[List[bytes], int, bool]:
        """(frames, next_token, complete) starting at `token`. Tokens
        below `base` were acknowledged and dropped — re-reads of those are
        a protocol violation and return nothing at the current position."""
        out: List[bytes] = []
        size = 0
        t = max(token, self.base)
        while t < self.end_token:
            f = self.pages[t - self.base]
            if out and size + len(f) > max_bytes:
                break
            out.append(f)
            size += len(f)
            t += 1
        complete = self.no_more_pages and t >= self.end_token
        return out, t, complete

    def acknowledge(self, token: int):
        if token > self.base:
            drop = min(token, self.end_token) - self.base
            self.queued_bytes -= sum(len(f) for f in self.pages[:drop])
            del self.pages[:drop]
            self.base += drop


class FileBackedClientBuffer(ClientBuffer):
    """Shared disk-backed buffer machinery: frames persist to a
    FrameFile as produced and every token stays replayable from 0 — the
    property that makes stage-level retry sound (a replacement consumer
    re-pulls the full stream; RAM holds only the offset index).
    acknowledge() advances the window but never discards."""

    def __init__(self, file: Optional[FrameFile] = None,
                 owns_file: bool = True):
        super().__init__()
        self._file = file if file is not None else FrameFile()
        self._owns_file = owns_file
        self._closed = False

    def add(self, frame: bytes):
        if self._closed:
            return                       # aborted task still emitting
        if not self._file.append(frame):
            return
        self.pages.append(None)          # token bookkeeping only
        self.queued_bytes += len(frame)  # cumulative: nothing discards

    def get(self, token: int, max_bytes: int):
        if self._closed:
            raise BufferClosedError(
                f"buffer closed at token {token} (task deleted or "
                "worker shutting down)")
        out, t = self._file.read_range(token, max_bytes)
        complete = self.no_more_pages and t >= self._file.frame_count
        return out, t, complete

    def acknowledge(self, token: int):
        self.base = min(max(self.base, token), self._file.frame_count)

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._owns_file:
            self._file.close(unlink=True)


class MaterializedClientBuffer(FileBackedClientBuffer):
    """Batch-mode buffer (reference: presto-spark's materialized
    shuffle, presto_cpp ShuffleWrite.cpp): owns a shuffle temp file,
    unlinked when the task is deleted."""


class SpooledClientBuffer(FileBackedClientBuffer):
    """retry_policy=TASK buffer: the FrameFile IS the spool part file
    (no tee, no double write). The TaskSpoolWriter owns the bytes —
    commit publishes them via atomic rename (open handles stay valid,
    so live pulls keep working), and the store's GC reclaims them;
    close() here only stops further reads through this buffer."""

    def __init__(self, file: FrameFile):
        super().__init__(file=file, owns_file=False)


class OutputBufferManager:
    """All buffers of one task (OutputBuffers.type PARTITIONED etc.).

    `spool_writer` (a spool/store.TaskSpoolWriter) switches every buffer
    to SpooledClientBuffer backed by that writer's part files."""

    def __init__(self, buffer_ids: List[str], materialized: bool = False,
                 spool_writer=None):
        self.spool_writer = spool_writer
        if spool_writer is not None:
            self.buffers: Dict[str, ClientBuffer] = {
                b: SpooledClientBuffer(spool_writer.part(b))
                for b in buffer_ids}
        else:
            cls = MaterializedClientBuffer if materialized else ClientBuffer
            self.buffers = {b: cls() for b in buffer_ids}
        self.lock = threading.Lock()
        # Wake plumbing for long-polling result readers. Its OWN
        # Condition (not self.lock): producers fire wakes AFTER
        # releasing the manager lock, so a slow waiter can never stall
        # add_page. The version counter makes the wait race-free — a
        # waiter records the version before (re)checking the buffer,
        # then sleeps only if no wake happened in between.
        self.cond = threading.Condition()
        self._wake_version = 0
        self._wakers: List[Callable[[], None]] = []

    # ------------------------------------------------------------- wakes
    def _wake(self):
        """Page arrived / stream ended / buffer closed: wake every
        parked long-poll (threaded waiters via the Condition, event-loop
        waiters via their registered threadsafe callbacks)."""
        with self.cond:
            self._wake_version += 1
            self.cond.notify_all()
            wakers = list(self._wakers)
        for cb in wakers:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a dead loop's waker
                pass           # must not break the producer

    def wake_version(self) -> int:
        with self.cond:
            return self._wake_version

    def add_waker(self, cb: Callable[[], None]):
        with self.cond:
            self._wakers.append(cb)

    def remove_waker(self, cb: Callable[[], None]):
        with self.cond:
            try:
                self._wakers.remove(cb)
            except ValueError:
                pass

    def wait_for_wake(self, seen_version: int, timeout_s: float):
        """Threaded long-poll park: sleep until a wake newer than
        `seen_version` (or the timeout). Event-driven replacement for
        the old `time.sleep(0.01)` poll loop."""
        with self.cond:
            if self._wake_version == seen_version:
                self.cond.wait(timeout_s)

    def close(self):
        with self.lock:
            for b in self.buffers.values():
                if hasattr(b, "close"):
                    b.close()
            if self.spool_writer is not None:
                self.spool_writer.close()
        self._wake()

    def buffer(self, buffer_id: str) -> Optional[ClientBuffer]:
        return self.buffers.get(buffer_id)

    def add_page(self, buffer_id: str, frame: bytes):
        with self.lock:
            b = self.buffers[buffer_id]
            b.add(frame)
            _M_PAGES_ADDED.inc()
            _M_DEPTH_HIGH.set_max(len(b.pages))
            _M_BYTES_HIGH.set_max(b.queued_bytes)
        self._wake()

    def set_no_more_pages(self):
        with self.lock:
            for b in self.buffers.values():
                b.no_more_pages = True
        self._wake()

    def abort(self, buffer_id: str):
        with self.lock:
            b = self.buffers.get(buffer_id)
            if b is not None:
                b.aborted = True
                b.pages = []
                b.queued_bytes = 0
        self._wake()
