"""Task output buffers: the pull-protocol server side.

Reference roles: PartitionedOutputBuffer / ClientBuffer
(presto-main-base/.../execution/buffer/PartitionedOutputBuffer.java:44,
buffer/ClientBuffer.java) — per-destination queues of SerializedPages,
consumed by sequenced GET .../results/{buffer}/{token} with acknowledge
semantics (at-least-once; tokens make re-reads idempotent)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class ClientBuffer:
    """One destination's page queue with token bookkeeping. Acknowledged
    frames are dropped (tokens stay monotonically global: `base` is the
    token of pages[0]) — the at-least-once window is [acked, produced)."""

    def __init__(self):
        self.pages: List[bytes] = []     # frames for tokens base..
        self.base = 0                    # token of pages[0]
        self.no_more_pages = False
        self.aborted = False

    @property
    def end_token(self) -> int:
        return self.base + len(self.pages)

    def add(self, frame: bytes):
        self.pages.append(frame)

    def get(self, token: int, max_bytes: int
            ) -> Tuple[List[bytes], int, bool]:
        """(frames, next_token, complete) starting at `token`. Tokens
        below `base` were acknowledged and dropped — re-reads of those are
        a protocol violation and return nothing at the current position."""
        out: List[bytes] = []
        size = 0
        t = max(token, self.base)
        while t < self.end_token:
            f = self.pages[t - self.base]
            if out and size + len(f) > max_bytes:
                break
            out.append(f)
            size += len(f)
            t += 1
        complete = self.no_more_pages and t >= self.end_token
        return out, t, complete

    def acknowledge(self, token: int):
        if token > self.base:
            drop = min(token, self.end_token) - self.base
            del self.pages[:drop]
            self.base += drop


class OutputBufferManager:
    """All buffers of one task (OutputBuffers.type PARTITIONED etc.)."""

    def __init__(self, buffer_ids: List[str]):
        self.buffers: Dict[str, ClientBuffer] = {
            b: ClientBuffer() for b in buffer_ids}
        self.lock = threading.Lock()

    def buffer(self, buffer_id: str) -> Optional[ClientBuffer]:
        return self.buffers.get(buffer_id)

    def add_page(self, buffer_id: str, frame: bytes):
        with self.lock:
            self.buffers[buffer_id].add(frame)

    def set_no_more_pages(self):
        with self.lock:
            for b in self.buffers.values():
                b.no_more_pages = True

    def abort(self, buffer_id: str):
        with self.lock:
            b = self.buffers.get(buffer_id)
            if b is not None:
                b.aborted = True
                b.pages = []
