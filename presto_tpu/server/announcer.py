"""Discovery announcer: periodic PUT /v1/announcement/{nodeId}.

Reference: presto_cpp/main/Announcer.cpp:64 — the worker announces itself
to the coordinator's embedded discovery service with its services payload;
DiscoveryNodeManager (presto-main/.../metadata/DiscoveryNodeManager.java:88)
turns announcements into the active worker set.

Multi-coordinator HA: ``coordinator_uri`` may be a single URI or a
sequence of peer coordinator URIs — every announcement round PUTs to
ALL of them so membership converges on every peer (an unreachable peer
is skipped that round; its view catches up on the next one)."""

from __future__ import annotations

import json
import logging
import threading

from presto_tpu.protocol.transport import HttpClient, get_client
from presto_tpu.utils.threads import spawn

log = logging.getLogger("presto_tpu.announcer")


class Announcer:
    def __init__(self, coordinator_uri, self_uri: str, node_id: str,
                 environment: str = "tpu", interval_s: float = 5.0,
                 connector_ids: str = "tpch,tpcds,memory,parquet",
                 client: HttpClient = None, extra_properties=None):
        uris = ([coordinator_uri] if isinstance(coordinator_uri, str)
                else list(coordinator_uri))
        self.coordinator_uris = [u.rstrip("/") for u in uris]
        # single-URI compat alias (existing callers/tests read this)
        self.coordinator_uri = self.coordinator_uris[0]
        self.client = client or get_client()
        self.self_uri = self_uri
        self.node_id = node_id
        self.environment = environment
        self.connector_ids = connector_ids
        # callable returning extra service properties merged into each
        # announcement round (e.g. the cluster-mesh slice fields from
        # server/mesh_tier.py — re-evaluated per round so a drained
        # worker's next announcement withdraws them)
        self.extra_properties = extra_properties
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = spawn("worker", "announcer", self._loop,
                             start=False)
        self.announcements = 0
        self.last_error = None

    def payload(self) -> dict:
        props = {
            "node_version": "presto-tpu-0.3",
            "coordinator": "false",
            "connectorIds": self.connector_ids,
            "http": self.self_uri,
        }
        if self.extra_properties is not None:
            try:
                props.update(self.extra_properties() or {})
            except Exception as e:  # noqa: BLE001 — extras are advisory
                self.last_error = str(e)
        return {
            "environment": self.environment,
            "pool": "general",
            "location": f"/{self.node_id}",
            "services": [{
                "id": self.node_id,
                "type": "presto",
                "properties": props,
            }],
        }

    def announce_once(self) -> bool:
        """One announcement round: PUT to every coordinator peer.
        True when at least one accepted (membership can converge);
        per-peer failures are recorded and retried next round."""
        body = json.dumps(self.payload()).encode()
        ok = False
        for uri in self.coordinator_uris:
            url = f"{uri}/v1/announcement/{self.node_id}"
            try:
                self.client.request(
                    url, method="PUT", body=body,
                    headers={"Content-Type": "application/json"},
                    request_class="announce")
                self.announcements += 1
                ok = True
            except Exception as e:           # noqa: BLE001 — keep retrying
                self.last_error = str(e)
        return ok

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.announce_once()
            except Exception:   # noqa: BLE001 — the loop must survive
                log.exception("announcement attempt failed; continuing")
            self._stop.wait(self.interval_s)

    def retract(self) -> bool:
        """Best-effort final DELETE /v1/announcement/{nodeId}: the
        coordinator learns of departure immediately instead of waiting
        out announcement staleness (DiscoveryNodeManager's expiry).
        DELETEs from every peer; True when all acknowledged."""
        ok = True
        for uri in self.coordinator_uris:
            url = f"{uri}/v1/announcement/{self.node_id}"
            try:
                self.client.request(url, method="DELETE",
                                    request_class="announce")
            except Exception as e:  # noqa: BLE001 — departure is advisory
                self.last_error = str(e)
                ok = False
        return ok

    def start(self):
        self._thread.start()

    def stop(self, retract: bool = True):
        already = self._stop.is_set()
        self._stop.set()
        if retract and not already:
            self.retract()
